"""Benchmark: datapoints aggregated per second per chip.

Runs the fused query pipeline (downsample -> rate -> interpolate ->
aggregate -> group-by, opentsdb_tpu.ops.pipeline) on one chip over a
synthetic workload shaped like BASELINE.json config 3: 1M series, one
hour window, per-minute samples, 5m avg downsample, rate conversion,
group-by sum into 100 groups.

Three paths are timed:
- the dense regular-cadence path the engine auto-selects for
  fixed-interval data (reshape reductions, memory-bandwidth bound)
- the fused Pallas kernel (downsample+groupby as two MXU matmuls)
- the padded scatter-free path (one-hot MXU contraction over the point
  axis) the engine selects for irregular timestamps

The headline value is the best of dense/pallas (what the engine runs
for this workload); the padded number goes to stderr for the record.

Timing method: the backend here may be a tunneled/relayed device where
``jax.block_until_ready`` returns before the device finishes, so naive
wall-clock timing reports pure dispatch latency (we measured 40us for a
workload whose HBM traffic alone needs >250us). Instead each path is
wrapped in an on-device ``lax.fori_loop`` whose carry perturbs the
kernel's own input (so XLA cannot hoist the body as loop-invariant),
the loop is run at two trip counts with a forced host fetch of the tiny
result, and the per-iteration time is the slope -- cancelling the fixed
RPC/dispatch overhead exactly.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against the reference's single-TSD iterator
path, MEASURED on this host by ``bench_baseline.py``: a C++ -O2
replica of the per-datapoint virtual iterator chain
(AggregationIterator.java:253-280, single-threaded per query) on the
same config-3 shape — an upper bound on the JVM original (no JVM
exists in this image), i.e. generous to the reference. The measured
value is read from BASELINE_MEASURED.json; the constant below is the
recorded fallback from the same measurement.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

import numpy as np

# measured 2026-07-30 by bench_baseline.py on this host (see docstring)
JAVA_BASELINE_DPS = 62_262_767.0

# Failure handling (the round-3 lesson: the tunneled TPU backend can
# either raise UNAVAILABLE quickly or hang indefinitely in init; both
# must yield a parseable record, never a bare traceback or a silent
# timeout — cf. the reference treating storage failure as a handled
# path, src/tsd/StorageExceptionHandler.java:31):
#   - the child process runs the real benchmark with an internal
#     watchdog that hard-exits (os._exit from a daemon thread) if
#     backend init doesn't finish in INIT_DEADLINE_S;
#   - the parent enforces ATTEMPT_DEADLINE_S per attempt, retries once,
#     and on final failure prints {"value": null, "error": ...}.
INIT_DEADLINE_S = 120
ATTEMPT_DEADLINE_S = 480
RETRY_BACKOFF_S = 15
_EXIT_TPU_UNAVAILABLE = 3


def _elog(msg: str) -> None:
    print(f"[bench +{time.monotonic() - _T0:6.1f}s] {msg}",
          file=sys.stderr, flush=True)


_T0 = time.monotonic()


def _java_baseline() -> float:
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "BASELINE_MEASURED.json")
    try:
        with open(path) as f:
            return float(json.load(f)["java_baseline_dps"])
    except Exception:  # noqa: BLE001
        return JAVA_BASELINE_DPS


def make_batch(num_series: int, points_per: int, num_buckets: int,
               num_groups: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    n = num_series * points_per
    values = rng.normal(100.0, 15.0, size=n).astype(np.float32)
    series_idx = np.repeat(np.arange(num_series, dtype=np.int32),
                           points_per)
    bucket_idx = np.tile(
        (np.arange(points_per, dtype=np.int32) * num_buckets) // points_per,
        num_series)
    bucket_ts = np.arange(num_buckets, dtype=np.int64) * 300_000
    group_ids = (np.arange(num_series, dtype=np.int32) % num_groups)
    return values, series_idx, bucket_idx, bucket_ts, group_ids


# no single v5e chip can stream faster than this; a slope below the
# floor it implies for the workload's byte count is a cross-traffic
# artifact, not a measurement (819 GB/s HBM + margin)
_IMPOSSIBLE_BW = 1.5e12  # bytes/s


def _time_device(run_step, arrays, iters=24, pairs=7, min_bytes=0):
    """True per-execution device time of ``run_step(eps, *arrays)``.

    run_step must return a small array and must consume ``eps`` in the
    input of its heavy computation. Returns seconds per execution, or
    NaN when no plausible measurement could be taken.

    Robustness on the multi-tenant tunneled device: each (lo, hi)
    trip-count pair is sampled ADJACENTLY in time (2 runs per
    endpoint, min), one slope per pair, and the result is the median
    of the plausible slopes. The previous global-min-of-each-endpoint
    estimator could straddle weather regimes — a busy-window tlo
    against a quiet-window thi collapses the slope to ~0 and records
    an impossibly fast result (observed: a 240MB-stream kernel
    "measured" at 0.00 ms). Slopes below the physical floor implied by
    ``min_bytes`` (bytes the kernel must move per execution) are
    discarded as artifacts.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    @jax.jit
    def rep(n, *arrs):
        def body(_, c):
            out = run_step(c * 1e-30, *arrs)
            return jnp.nan_to_num(out.astype(jnp.float32)).mean()
        return lax.fori_loop(0, n, body, jnp.float32(0))

    lo, hi = 1, 1 + iters
    np.asarray(rep(lo, *arrays))  # compile + warm

    def once(n):
        t0 = time.perf_counter()
        np.asarray(rep(n, *arrays))
        return time.perf_counter() - t0

    floor = min_bytes / _IMPOSSIBLE_BW
    slopes = []
    for _ in range(pairs):
        tl = min(once(lo), once(lo))
        th = min(once(hi), once(hi))
        slopes.append((th - tl) / (hi - lo))
    ok = sorted(s for s in slopes if s > floor)
    if not ok:
        _elog(f"measurement degenerate: all {pairs} slopes below the "
              f"{floor * 1e3:.2f} ms physical floor "
              f"({min_bytes / 1e6:.0f} MB workload)")
        return float("nan")
    return ok[len(ok) // 2]


def _init_backend_watchdog():
    """Initialize the JAX backend under a watchdog.

    jax backend init is uninterruptible from Python, so the watchdog is
    a daemon thread that hard-exits the whole child process with a
    distinctive code when the deadline passes — the supervising parent
    turns that into a retry / error record."""
    done = threading.Event()

    def watchdog():
        if not done.wait(INIT_DEADLINE_S):
            _elog(f"backend init exceeded {INIT_DEADLINE_S}s "
                  "(tunnel hang) — aborting child")
            os._exit(_EXIT_TPU_UNAVAILABLE)

    threading.Thread(target=watchdog, daemon=True).start()
    try:
        import jax
        devs = jax.devices()
    except Exception as e:  # noqa: BLE001 — UNAVAILABLE etc.
        _elog(f"backend init failed: {e}")
        os._exit(_EXIT_TPU_UNAVAILABLE)
    done.set()
    _elog(f"backend up: {len(devs)} x {devs[0].platform} "
          f"({devs[0].device_kind})")


def main() -> None:
    _init_backend_watchdog()
    # persistent compile cache: identical kernels across bench runs
    # (and across the driver's rounds) reload instead of re-paying the
    # tunnel remote_compile; same resolution as the server so they
    # share entries
    from opentsdb_tpu.utils.compile_cache import enable_from_config
    from opentsdb_tpu.utils.config import Config
    enable_from_config(Config())
    import jax
    import jax.numpy as jnp

    from opentsdb_tpu.ops.pipeline import PipelineSpec, run_pipeline_dense

    # config-3 shape: 1M series x 1h @ 1/min, 5m avg downsample + rate,
    # sum group-by into 100 groups
    num_series = 1_000_000
    points_per = 60
    num_buckets = 12
    num_groups = 100
    n_points = num_series * points_per
    k = points_per // num_buckets

    spec = PipelineSpec(
        num_series=num_series, num_buckets=num_buckets,
        num_groups=num_groups, ds_function="avg", agg_name="sum",
        rate=True)

    values, series_idx, bucket_idx, bucket_ts, group_ids = make_batch(
        num_series, points_per, num_buckets, num_groups)

    dtype = jnp.float32
    rate_params = (jnp.asarray(2.0**64 - 1, dtype),
                   jnp.asarray(0.0, dtype))
    fill_value = jnp.asarray(float("nan"), dtype)
    d_bts = jax.device_put(jnp.asarray(bucket_ts))
    d_gids = jax.device_put(jnp.asarray(group_ids))

    # dense path (the engine's choice for this regular workload); eps
    # rides on the values so the reduction re-executes every iteration
    # (the add fuses into the reduction -- no extra HBM traffic)
    d_vals2d = jax.device_put(
        jnp.asarray(values.reshape(num_series, points_per), dtype))
    _elog("inputs device-resident; timing dense path")
    dt_dense = _time_device(
        lambda eps, v, bts, gids: run_pipeline_dense(
            v + eps, bts, gids, rate_params, fill_value, spec, k)[0],
        (d_vals2d, d_bts, d_gids), min_bytes=d_vals2d.nbytes)
    _elog(f"dense path: {dt_dense * 1e3:.2f} ms; timing pallas path")

    # fused Pallas kernel; eps rides on the tiny [B,1] inverse-dt
    # vector instead of the values -- perturbing the 240MB values input
    # would add un-fusable HBM traffic ahead of the opaque pallas_call
    # and mismeasure it. Both group-reduce layouts are timed (the span
    # kernel is the roofline design, but the tunneled device's
    # multi-tenant weather can distort either reading; best-of is
    # robust). Guarded: any Mosaic failure falls back to the dense XLA
    # number.
    dt_pallas = None
    try:
        from opentsdb_tpu.ops import pallas_fused
        if pallas_fused.supported(spec, dtype):
            vals2d = values.reshape(num_series, points_per)
            for allow_span in (True, False):
                args, tile_s, interp = pallas_fused.prepare(
                    vals2d, bucket_ts, group_ids, spec, k,
                    dtype=dtype, allow_span=allow_span)
                layout = "span" if len(args) == 6 else "one-hot"
                dt = _time_device(
                    lambda eps, *a: pallas_fused._run(
                        a[0], a[1], a[2], a[3] + eps, *a[4:],
                        spec=spec, tile_s=tile_s, interpret=interp)[0],
                    args, min_bytes=args[0].nbytes)
                _elog(f"pallas[{layout}]: {dt * 1e3:.2f} ms")
                if not np.isnan(dt):
                    dt_pallas = dt if dt_pallas is None \
                        else min(dt_pallas, dt)
                if layout == "one-hot":
                    break  # span layout unavailable; don't time twice
    except Exception as e:  # noqa: BLE001
        print(f"pallas path unavailable: {e}", file=sys.stderr)

    _elog("timing padded path")
    # padded scatter-free path (the engine's choice for irregular
    # timestamps): same data, row layout with the bucket map as an
    # explicit [S,P] index
    from opentsdb_tpu.ops.pipeline import run_pipeline_padded
    d_bidx2d = jax.device_put(jnp.asarray(
        bucket_idx.reshape(num_series, points_per)))
    dt_padded = _time_device(
        lambda eps, v, bi, bts, gids: run_pipeline_padded(
            v + eps, bi, bts, gids, rate_params, fill_value, spec)[0],
        (d_vals2d, d_bidx2d, d_bts, d_gids), iters=8,
        min_bytes=d_vals2d.nbytes + d_bidx2d.nbytes)

    # config-4 shape for the record: 1M histogram series x 64 buckets,
    # p99/p999 via the device merge+percentile kernel
    from opentsdb_tpu.ops.histogram_kernels import (merge_histograms,
                                                    percentiles_from_merged)
    rng = np.random.default_rng(1)
    h_counts = jax.device_put(jnp.asarray(
        rng.integers(0, 50, (num_series, 64)).astype(np.float32)))
    h_seg = jax.device_put(jnp.asarray(
        (np.arange(num_series) % num_groups).astype(np.int32)))
    h_mids = jax.device_put(jnp.arange(64, dtype=jnp.float32) + 0.5)
    h_qs = jax.device_put(jnp.asarray([99.0, 99.9], dtype=jnp.float32))
    _elog("timing histogram-percentile path")
    # sub-ms workload: need a long loop for the slope to clear the
    # multi-tenant noise floor (~10 ms) on the tunneled device
    dt_hist = _time_device(
        lambda eps, c, s, m, q: percentiles_from_merged(
            merge_histograms(c + eps, s, num_groups), m, q),
        (h_counts, h_seg, h_mids, h_qs), iters=96,
        min_bytes=h_counts.nbytes)
    print(f"hist p99/p999 (1Mx64 -> {num_groups} groups): "
          f"{dt_hist * 1e3:.2f} ms", file=sys.stderr)

    print(f"dense: {dt_dense * 1e3:.2f} ms ({n_points / dt_dense / 1e9:.1f}"
          f" G dp/s)  "
          + (f"pallas: {dt_pallas * 1e3:.2f} ms "
             f"({n_points / dt_pallas / 1e9:.1f} G dp/s)  "
             if dt_pallas else "pallas: n/a  ")
          + f"padded: {dt_padded * 1e3:.2f} ms "
          f"({n_points / dt_padded / 1e9:.1f} G dp/s)",
          file=sys.stderr)
    cands = [dt for dt in (dt_dense, dt_pallas)
             if dt is not None and not np.isnan(dt)]
    if not cands:
        # every path's slopes were below the physical floor — bursty
        # cross-traffic made this window unmeasurable; a parseable
        # record beats a fabricated number
        print(json.dumps({
            "metric": "datapoints aggregated/sec/chip",
            "value": None, "unit": "datapoints/s",
            "vs_baseline": None, "error": "measurement_degenerate",
        }))
        return
    dps = n_points / min(cands)
    print(json.dumps({
        "metric": "datapoints aggregated/sec/chip",
        "value": round(dps),
        "unit": "datapoints/s",
        "vs_baseline": round(dps / _java_baseline(), 2),
    }))


def _supervise() -> int:
    """Run the benchmark in a child process with a hard deadline and
    one retry; always leave ONE parseable JSON line on stdout."""
    me = os.path.abspath(__file__)
    last_rc: int | None = None
    for attempt in range(2):
        if attempt:
            _elog(f"retrying in {RETRY_BACKOFF_S}s")
            time.sleep(RETRY_BACKOFF_S)
        env = dict(os.environ, _BENCH_CHILD="1")
        _elog(f"attempt {attempt + 1}/2: launching benchmark child "
              f"(deadline {ATTEMPT_DEADLINE_S}s)")
        proc = subprocess.Popen([sys.executable, me], env=env,
                                stdout=subprocess.PIPE, text=True)
        try:
            out, _ = proc.communicate(timeout=ATTEMPT_DEADLINE_S)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.communicate()
            _elog(f"attempt {attempt + 1} exceeded "
                  f"{ATTEMPT_DEADLINE_S}s — killed")
            last_rc = None  # hang, not an exit
            continue
        if proc.returncode == 0 and out.strip():
            line = out.strip().splitlines()[-1]
            if attempt == 0 and "measurement_degenerate" in line:
                # the window was unmeasurable (cross-traffic burst);
                # one more attempt may land in calmer weather. (If the
                # retry then hangs or crashes, THAT outcome is what
                # gets recorded — a stale degenerate record must not
                # mask an infra outage or a code regression.)
                _elog("degenerate measurement; retrying once")
                continue
            # relay the child's result line verbatim
            sys.stdout.write(line + "\n")
            return 0
        _elog(f"attempt {attempt + 1} failed rc={proc.returncode}")
        last_rc = proc.returncode
    # distinguish infra unavailability (watchdog exit / hang) from a
    # genuine benchmark crash — a code regression must not be recorded
    # as a TPU flake
    infra = last_rc is None or last_rc == _EXIT_TPU_UNAVAILABLE
    print(json.dumps({
        "metric": "datapoints aggregated/sec/chip",
        "value": None,
        "unit": "datapoints/s",
        "vs_baseline": None,
        "error": "tpu_unavailable" if infra
                 else f"bench_failed_rc{last_rc}",
    }))
    return 0  # the record above IS the result; don't mask it with rc!=0


if __name__ == "__main__":
    if os.environ.get("_BENCH_CHILD"):
        main()
    else:
        sys.exit(_supervise())
