"""Benchmark: datapoints aggregated per second per chip.

Runs the fused query pipeline (downsample -> rate -> interpolate ->
aggregate -> group-by, opentsdb_tpu.ops.pipeline) on one chip over a
synthetic workload shaped like BASELINE.json config 3: 1M series, one
hour window, per-minute samples, 5m avg downsample, rate conversion,
group-by sum into 100 groups.

Two paths are timed:
- the dense regular-cadence path the engine auto-selects for
  fixed-interval data (reshape reductions, memory-bandwidth bound)
- the general scatter path (sorted segment reductions) used for
  irregular timestamps

The headline value is the dense path (what the engine actually runs
for this workload); the scatter number is printed to stderr for the
record.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

``vs_baseline`` compares against the reference's single-TSD Java
iterator path. OpenTSDB publishes no numbers (BASELINE.md); the Java
pipeline is a per-datapoint virtual-call chain
(AggregationIterator.java:253-280, single-threaded per query), measured
in public deployments at single-digit millions of dp/s per query
thread. We use 10M dp/s as the comparison constant — generous to the
reference — until a measured Java baseline lands in BASELINE.json.
"""

from __future__ import annotations

import json
import sys
import time

import numpy as np

JAVA_BASELINE_DPS = 10_000_000.0  # see module docstring


def make_batch(num_series: int, points_per: int, num_buckets: int,
               num_groups: int, seed: int = 0):
    rng = np.random.default_rng(seed)
    n = num_series * points_per
    values = rng.normal(100.0, 15.0, size=n).astype(np.float32)
    series_idx = np.repeat(np.arange(num_series, dtype=np.int32),
                           points_per)
    bucket_idx = np.tile(
        (np.arange(points_per, dtype=np.int32) * num_buckets) // points_per,
        num_series)
    bucket_ts = np.arange(num_buckets, dtype=np.int64) * 300_000
    group_ids = (np.arange(num_series, dtype=np.int32) % num_groups)
    return values, series_idx, bucket_idx, bucket_ts, group_ids


def _time(fn, iters=5):
    """Median wall time with per-iteration blocking (async dispatch
    without a barrier under-reports on relayed backends)."""
    import jax
    jax.block_until_ready(fn())  # warmup/compile
    times = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready(fn())
        times.append(time.perf_counter() - t0)
    return float(np.median(times))


def main() -> None:
    import jax
    import jax.numpy as jnp

    from opentsdb_tpu.ops.pipeline import (PipelineSpec, run_pipeline,
                                           run_pipeline_dense)

    # config-3 shape: 1M series x 1h @ 1/min, 5m avg downsample + rate,
    # sum group-by into 100 groups
    num_series = 1_000_000
    points_per = 60
    num_buckets = 12
    num_groups = 100
    n_points = num_series * points_per
    k = points_per // num_buckets

    spec = PipelineSpec(
        num_series=num_series, num_buckets=num_buckets,
        num_groups=num_groups, ds_function="avg", agg_name="sum",
        rate=True)

    values, series_idx, bucket_idx, bucket_ts, group_ids = make_batch(
        num_series, points_per, num_buckets, num_groups)

    dtype = jnp.float32
    rate_params = (jnp.asarray(2.0**64 - 1, dtype),
                   jnp.asarray(0.0, dtype))
    fill_value = jnp.asarray(float("nan"), dtype)
    d_bts = jax.device_put(jnp.asarray(bucket_ts))
    d_gids = jax.device_put(jnp.asarray(group_ids))

    # dense path (the engine's choice for this regular workload)
    d_vals2d = jax.device_put(
        jnp.asarray(values.reshape(num_series, points_per), dtype))
    dt_dense = _time(lambda: run_pipeline_dense(
        d_vals2d, d_bts, d_gids, rate_params, fill_value, spec, k)[0])

    # fused Pallas kernel (MXU one-hot group reduction); guarded — any
    # Mosaic failure falls back to the dense XLA number
    dt_pallas = None
    try:
        from opentsdb_tpu.ops import pallas_fused
        if pallas_fused.supported(spec, dtype):
            vals2d = values.reshape(num_series, points_per)
            args, tile_s, interp = pallas_fused.prepare(
                vals2d, bucket_ts, group_ids, spec, k, dtype=dtype)
            dt_pallas = _time(lambda: pallas_fused._run(
                *args, spec, tile_s, interp)[0])
    except Exception as e:  # noqa: BLE001
        print(f"pallas path unavailable: {e}", file=sys.stderr)

    # general scatter path (irregular-timestamp workloads)
    d_vals = jax.device_put(jnp.asarray(values, dtype))
    d_sidx = jax.device_put(jnp.asarray(series_idx))
    d_bidx = jax.device_put(jnp.asarray(bucket_idx))
    dt_scatter = _time(lambda: run_pipeline(
        d_vals, d_sidx, d_bidx, d_bts, d_gids, rate_params, fill_value,
        spec)[0])

    dt_best = min(dt_dense, dt_pallas) if dt_pallas else dt_dense
    dps = n_points / dt_best
    print(f"dense: {dt_dense * 1e3:.1f} ms ({n_points / dt_dense / 1e9:.2f}"
          f" G dp/s)  "
          + (f"pallas: {dt_pallas * 1e3:.1f} ms "
             f"({n_points / dt_pallas / 1e9:.2f} G dp/s)  "
             if dt_pallas else "pallas: n/a  ")
          + f"scatter: {dt_scatter * 1e3:.1f} ms "
          f"({n_points / dt_scatter / 1e9:.2f} G dp/s)",
          file=sys.stderr)
    print(json.dumps({
        "metric": "datapoints aggregated/sec/chip",
        "value": round(dps),
        "unit": "datapoints/s",
        "vs_baseline": round(dps / JAVA_BASELINE_DPS, 2),
    }))


if __name__ == "__main__":
    main()
