"""Measure the single-TSD reference-architecture baseline.

The image ships no JVM, so OpenTSDB's actual Java iterator chain cannot
run here. Instead ``opentsdb_tpu/native/baseline_ref.cc`` replicates
its query hot loop faithfully — per-datapoint pull through virtual
SeekableView chains (RowSeq -> Downsampler -> RateSpan) merged k-way by
an AggregationIterator with LERP, single-threaded per query (SURVEY.md
§3.3) — in C++. An -O2 C++ build of the same per-point virtual-dispatch
architecture is an upper bound on the JIT'd Java original, so the
resulting ``vs_baseline`` figures are conservative (generous to the
reference).

Writes BASELINE_MEASURED.json; bench.py picks the headline-shape value
up from there.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
SRC = os.path.join(HERE, "opentsdb_tpu", "native", "baseline_ref.cc")
OUT = os.path.join(HERE, "BASELINE_MEASURED.json")

# (name, S, P, B, G, rate, reps) — BASELINE.json config shapes
SHAPES = [
    ("config1_1k_series_1h_at_10s_1m_avg", 1000, 360, 60, 1, 0, 5),
    ("config2_100k_series_groupby", 100_000, 60, 12, 1000, 0, 3),
    ("config3_1M_series_rate_5m_avg_groupby", 1_000_000, 60, 12, 100,
     1, 3),
]
HEADLINE = "config3_1M_series_rate_5m_avg_groupby"


def main() -> None:
    exe = os.path.join("/tmp", "baseline_ref")
    subprocess.run(["g++", "-O2", "-o", exe, SRC], check=True)
    results = {}
    for name, s, p, b, g, rate, reps in SHAPES:
        proc = subprocess.run(
            [exe, str(s), str(p), str(b), str(g), str(rate),
             str(reps)],
            check=True, capture_output=True, text=True)
        r = json.loads(proc.stdout)
        results[name] = r
        print(f"{name}: {r['dps'] / 1e6:.1f} M dp/s "
              f"({r['seconds'] * 1e3:.1f} ms)", file=sys.stderr)
    doc = {
        "methodology": (
            "C++ -O2 replica of the reference's per-datapoint virtual "
            "iterator chain (RowSeq -> Downsampler -> RateSpan -> "
            "AggregationIterator k-way LERP merge), single-threaded "
            "per query like the reference; no JVM exists in this "
            "image, and C++ >= JIT'd Java for this architecture, so "
            "these numbers are an upper bound on the Java baseline."),
        "source": "opentsdb_tpu/native/baseline_ref.cc",
        "headline": HEADLINE,
        "java_baseline_dps": results[HEADLINE]["dps"],
        "results": results,
    }
    with open(OUT, "w") as f:
        json.dump(doc, f, indent=1)
    print(json.dumps({"java_baseline_dps": results[HEADLINE]["dps"]}))


if __name__ == "__main__":
    main()
