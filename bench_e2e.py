"""End-to-end /api/query benchmark over the BASELINE.json configs.

Times the FULL query path the TSD server runs — store materialize ->
filter/group construction -> device pipeline -> result assembly ->
HTTP JSON serialization — not just the device kernels (bench.py).
This is the north-star measurement: p50 latency of config 3
(1M series x 1h@1s, 5m avg downsample + rate) answered from the 1m
rollup tier, target < 2 s (BASELINE.json "north_star";
ref: the single-threaded Java iterator chain behind
/root/reference/src/core/TsdbQuery.java:742).

Data setup writes the rollup tiers directly through the store layer —
in the reference, rollups are also produced by external jobs and
written through the API (SURVEY.md §2.3), so a query benchmark may
legitimately start from populated tiers. Raw configs (1, 2) ingest
through ``tsdb.add_points``.

Usage: python bench_e2e.py [--cpu] [--configs 1,2,3,4] [--repeats N]
Prints one JSON line per config plus a summary line.
"""

from __future__ import annotations

import argparse
import json
import sys
import time

import numpy as np

BASE_S = 1356998400
BASE_MS = BASE_S * 1000


def _percentile(times: list[float], q: float) -> float:
    return float(np.percentile(np.asarray(times), q))


def _run_query(tsdb, serializer, query_obj, repeats: int
               ) -> tuple[dict, bytes]:
    """Execute + serialize `repeats` times; returns timing stats and
    the last response body. One untimed warmup run absorbs the
    first-compile cost (recorded as cold_ms) — production servers
    pre-compile the shape buckets at start (tsd.tpu.warmup), so warm
    timings are the steady-state number and the criterion is
    max_ms < 2x p50 across the timed runs."""
    from opentsdb_tpu.query.model import TSQuery
    times = []
    body = b""
    # the serve-path RESULT cache is disabled for the warm loop so
    # p50 stays comparable with earlier rounds (it measures the real
    # scan -> pipeline -> serialize chain); the repeat-query loop at
    # the end re-enables it and reports the cache-hit numbers
    tsdb.config.override_config("tsd.query.cache.enable", "false")
    # server-start warmup first (tsd.tpu.warmup): cold_ms below then
    # measures the first query of a WARMED server — the production
    # number (VERDICT r03 #3: cold tails were 14-16s unwarmed)
    from opentsdb_tpu.tsd.warmup import run_warmup
    t0 = time.perf_counter()
    run_warmup(tsdb)
    warmup_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    tsq = TSQuery.from_json(query_obj).validate()
    tsdb.execute_query(tsq)
    cold = time.perf_counter() - t0
    exec_times, ser_times = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        tsq = TSQuery.from_json(query_obj).validate()
        results = tsdb.execute_query(tsq)
        t1 = time.perf_counter()
        body = serializer.format_query(tsq, results)
        t2 = time.perf_counter()
        times.append(t2 - t0)
        exec_times.append(t1 - t0)
        ser_times.append(t2 - t1)
    # per-stage breakdown (VERDICT r4 weak #1: no stage evidence in
    # the artifact even though QueryStats exists): one extra run
    # traced through QueryStats, plus the engine/serializer split
    # medians from the timed runs above
    from opentsdb_tpu.stats.stats import QueryStats
    st = QueryStats(remote="bench_e2e", query=None)
    tsq = TSQuery.from_json(query_obj).validate()
    tsdb.new_query().run(tsq, st)
    st.mark_complete()
    stages = {k: round(v, 1) for k, v in sorted(st.stats.items())}
    stages["engineMedianMs"] = round(_percentile(exec_times, 50) * 1e3,
                                     1)
    stages["serializeMedianMs"] = round(
        _percentile(ser_times, 50) * 1e3, 1)
    # repeat-query (cache-hit) metric: the same dashboard refresh
    # answered from the serve-path result cache — one populating run,
    # then timed hits. repeat_exec is the engine-only number (what the
    # cache removes); repeat_p50 includes serialization, which a hit
    # still pays.
    tsdb.config.override_config("tsd.query.cache.enable", "true")
    tsq = TSQuery.from_json(query_obj).validate()
    tsdb.execute_query(tsq)  # populate
    hit_full, hit_exec = [], []
    for _ in range(repeats):
        t0 = time.perf_counter()
        tsq = TSQuery.from_json(query_obj).validate()
        results = tsdb.execute_query(tsq)
        t1 = time.perf_counter()
        serializer.format_query(tsq, results)
        t2 = time.perf_counter()
        hit_exec.append(t1 - t0)
        hit_full.append(t2 - t0)
    rcache = tsdb.result_cache
    assert rcache is not None and rcache.hits >= repeats, \
        "repeat loop did not hit the result cache"
    repeat_exec_p50 = _percentile(hit_exec, 50) * 1e3
    warm_exec_p50 = _percentile(exec_times, 50) * 1e3
    out_extra = {
        "repeat_p50_ms": round(_percentile(hit_full, 50) * 1e3, 1),
        "repeat_exec_p50_ms": round(repeat_exec_p50, 2),
        "cache_speedup": round(
            warm_exec_p50 / max(repeat_exec_p50, 1e-3), 1),
    }
    return {
        **out_extra,
        "p50_ms": round(_percentile(times, 50) * 1e3, 1),
        "min_ms": round(min(times) * 1e3, 1),
        "max_ms": round(max(times) * 1e3, 1),
        "cold_ms": round(cold * 1e3, 1),
        "warmup_s": round(warmup_s, 1),
        "runs": repeats,
        "stages": stages,
    }, body


def _mk_tsdb(rollups: bool = False):
    from opentsdb_tpu import TSDB, Config
    cfg = {
        "tsd.core.auto_create_metrics": "true",
        "tsd.storage.backend": "native",
    }
    if rollups:
        cfg["tsd.rollups.enable"] = "true"
    return TSDB(Config(**cfg))


def bench_config1(repeats: int) -> dict:
    """1k series x 1h @ 10s, avg downsample 1m (ref: CliQuery path)."""
    tsdb = _mk_tsdb()
    ts = np.arange(BASE_S, BASE_S + 3600, 10, dtype=np.int64)
    rng = np.random.default_rng(0)
    t0 = time.perf_counter()
    for i in range(1000):
        tsdb.add_points("sys.bench1", ts,
                        rng.normal(100, 10, len(ts)),
                        {"host": f"h{i:04d}"})
    ingest_s = time.perf_counter() - t0
    n = 1000 * len(ts)
    stats, body = _run_query(
        tsdb, _serializer(), {
            "start": BASE_MS, "end": BASE_MS + 3_600_000,
            "queries": [{"metric": "sys.bench1", "aggregator": "avg",
                         "downsample": "1m-avg"}]}, repeats)
    return {"config": 1, "series": 1000, "points": n,
            "ingest_mpps": round(n / ingest_s / 1e6, 2),
            "resp_bytes": len(body), **stats}


def bench_config2(repeats: int) -> dict:
    """100k series, sum+max multi-aggregator, wildcard tagv group-by
    (ref: GroupByAndAggregateCB + TagVWildcardFilter)."""
    tsdb = _mk_tsdb()
    n_series = 100_000
    pts_per = 30  # 30m @ 1/min
    ts = np.arange(BASE_S, BASE_S + pts_per * 60, 60, dtype=np.int64)
    rng = np.random.default_rng(1)
    vals = rng.normal(50, 5, (n_series, pts_per))
    t0 = time.perf_counter()
    for i in range(n_series):
        tsdb.add_points("sys.bench2", ts, vals[i],
                        {"host": f"h{i % 1000:04d}",
                         "task": f"t{i // 1000:03d}"})
    ingest_s = time.perf_counter() - t0
    n = n_series * pts_per
    stats, body = _run_query(
        tsdb, _serializer(), {
            "start": BASE_MS, "end": BASE_MS + pts_per * 60_000,
            "queries": [
                {"metric": "sys.bench2", "aggregator": "sum",
                 "filters": [{"type": "wildcard", "tagk": "host",
                              "filter": "*", "groupBy": True}]},
                {"metric": "sys.bench2", "aggregator": "max",
                 "filters": [{"type": "wildcard", "tagk": "host",
                              "filter": "*", "groupBy": True}]},
            ]}, repeats)
    return {"config": 2, "series": n_series, "points": n,
            "groups": 1000, "ingest_mpps": round(n / ingest_s / 1e6, 2),
            "resp_bytes": len(body), **stats}


def _populate_tier(tsdb, metric: str, n_series: int, n_buckets: int,
                   interval_ms: int, chunk: int = 50_000) -> float:
    """Write 1m rollup tiers (sum/count/min/max) for n_series, each
    with n_buckets aligned points — the state an external rollup job
    leaves behind (ref: TSDB.addAggregatePoint writers)."""
    from opentsdb_tpu.rollup.job import ROLLUP_AGGS
    mid = tsdb.uids.metrics.get_or_create_id(metric)
    kid = tsdb.uids.tag_names.get_or_create_id("host")
    bucket_ts = BASE_MS + np.arange(n_buckets, dtype=np.int64) \
        * interval_ms
    rng = np.random.default_rng(2)
    t0 = time.perf_counter()
    mask = np.ones((0, n_buckets), dtype=bool)
    for lo in range(0, n_series, chunk):
        hi = min(lo + chunk, n_series)
        tags_list = [((kid, tsdb.uids.tag_values.get_or_create_id(
            f"h{i:07d}")),) for i in range(lo, hi)]
        sids = {}
        for agg in ROLLUP_AGGS:
            sids[agg] = tsdb.rollup_store.tier("1m", agg) \
                .get_or_create_series_bulk(mid, tags_list)
        m = hi - lo
        if mask.shape[0] != m:
            mask = np.ones((m, n_buckets), dtype=bool)
        base_vals = rng.normal(100, 10, (m, n_buckets))
        grids = {"sum": base_vals * 60.0,
                 "count": np.full((m, n_buckets), 60.0),
                 "min": base_vals - 3.0, "max": base_vals + 3.0}
        for agg in ROLLUP_AGGS:
            tsdb.rollup_store.tier(agg=agg, interval="1m") \
                .append_grid(sids[agg], bucket_ts, grids[agg], mask)
    return time.perf_counter() - t0


def bench_config3(repeats: int, n_series: int = 1_000_000) -> dict:
    """North star: 1M series x 1h@1s, 5m avg downsample + rate,
    answered from the 1m rollup tier (sum/count division) — the only
    tier-correct way to satisfy the < 2 s budget; the raw window is
    3.6e9 points (ref: TsdbQuery rollup best-match :143, RollupSpan
    sum/count qualifiers)."""
    tsdb = _mk_tsdb(rollups=True)
    setup_s = _populate_tier(tsdb, "sys.bench3", n_series, 60, 60_000)
    raw_equiv = n_series * 3600          # 1h @ 1s
    tier_pts = n_series * 60 * 2         # sum + count read by the query
    stats, body = _run_query(
        tsdb, _serializer(), {
            "start": BASE_MS, "end": BASE_MS + 3_600_000,
            "queries": [{"metric": "sys.bench3", "aggregator": "sum",
                         "downsample": "5m-avg", "rate": True}]},
        repeats)
    return {"config": 3, "series": n_series,
            "raw_equiv_points": raw_equiv, "tier_points": tier_pts,
            "setup_s": round(setup_s, 1), "resp_bytes": len(body),
            **stats, "north_star_pass": stats["p50_ms"] < 2000.0}


def bench_config4(repeats: int, n_series: int = 200_000) -> dict:
    """p99/p999 percentiles over histogram series (ref:
    SimpleHistogram.percentile via the device merge kernel)."""
    from opentsdb_tpu.core.histogram import SimpleHistogram
    tsdb = _mk_tsdb()
    bounds = [float(b) for b in np.logspace(0, 4, 65)]
    rng = np.random.default_rng(3)
    all_counts = rng.integers(0, 50, (n_series, 64))
    t0 = time.perf_counter()
    batch = []
    for i in range(n_series):
        h = SimpleHistogram(bounds)
        h.counts = all_counts[i].tolist()
        batch.append(("sys.bench4", BASE_S,
                      tsdb.histogram_manager.encode(h),
                      {"host": f"h{i:07d}"}))
        if len(batch) == 25_000:
            tsdb.add_histogram_batch(batch)
            batch = []
    if batch:
        tsdb.add_histogram_batch(batch)
    ingest_s = time.perf_counter() - t0
    stats, body = _run_query(
        tsdb, _serializer(), {
            "start": BASE_MS, "end": BASE_MS + 60_000,
            "queries": [{"metric": "sys.bench4", "aggregator": "sum",
                         "percentiles": [99.0, 99.9]}]}, repeats)
    return {"config": 4, "series": n_series,
            "ingest_s": round(ingest_s, 1), "resp_bytes": len(body),
            **stats}


def bench_config5(repeats: int, n_series: int = 100_000,
                  hours: int = 2) -> dict:
    """Rollup job: raw @1s -> 1m/1h tiers (ref: BASELINE config 5;
    RollupUtils.java:27, TSDB.java:1320). Sized to the bench host's
    RAM; the reported rate is raw points processed per second, which
    scales linearly in series count (the job streams fixed-size
    series-chunk x window tiles)."""
    from opentsdb_tpu.rollup.job import run_rollup_job
    tsdb = _mk_tsdb(rollups=True)
    span = hours * 3600
    # ingest raw @1s via bulk grids: [chunk, span] per chunk
    rng = np.random.default_rng(5)
    t0 = time.perf_counter()
    ts_grid = BASE_MS + np.arange(span, dtype=np.int64) * 1000
    chunk = max(1, 20_000_000 // span)
    mid = tsdb.uids.metrics.get_or_create_id("sys.bench5")
    kid = tsdb.uids.tag_names.get_or_create_id("host")
    mask = np.ones((chunk, span), dtype=bool)
    for lo in range(0, n_series, chunk):
        hi = min(lo + chunk, n_series)
        sids = np.asarray([
            tsdb.store.get_or_create_series(
                mid, [(kid, tsdb.uids.tag_values.get_or_create_id(
                    f"h{i:07d}"))])
            for i in range(lo, hi)], dtype=np.int64)
        vals = rng.normal(100, 10, (hi - lo, span))
        tsdb.store.append_grid(sids, ts_grid,
                               vals, mask[:hi - lo])
    n_raw = n_series * span
    ingest_s = time.perf_counter() - t0
    times = []
    written = None
    for _ in range(max(1, repeats)):
        # fresh tier stores per run so repeats measure the same work
        tsdb.rollup_store._tiers.clear()
        tsdb.rollup_store._has_data_cache.clear()
        t0 = time.perf_counter()
        written = run_rollup_job(tsdb, BASE_MS,
                                 BASE_MS + span * 1000 - 1,
                                 intervals=["1m", "1h"])
        times.append(time.perf_counter() - t0)
    job_s = min(times)
    return {"config": 5, "series": n_series, "raw_points": n_raw,
            "hours": hours,
            "ingest_mpps": round(n_raw / ingest_s / 1e6, 1),
            "rollup_written": written,
            "job_s": round(job_s, 1), "runs": len(times),
            "job_raw_mpps": round(n_raw / job_s / 1e6, 1)}


def bench_live(repeats: int, n_series: int = 5_000,
               span_s: int = 1800) -> dict:
    """Live-dashboard config: a standing query maintained by the
    continuous-query subsystem under sustained ingest. Reports the
    p50 of a refresh served from maintained windows (fold pending +
    pipeline tail, no store scan) vs the p50 of a full recompute
    (streaming serve + result cache disabled: scan -> grid -> tail),
    plus the SSE push latency from acknowledged write to delivered
    event. Acceptance: incremental refresh >= 10x cheaper than full
    recompute."""
    from opentsdb_tpu.query.model import TSQuery
    tsdb = _mk_tsdb()
    # explicit flush-driven publishes only: the bench times the push
    # itself, not the rate limiter
    tsdb.config.override_config(
        "tsd.streaming.publish_min_interval_ms", "1000000000")
    rng = np.random.default_rng(11)
    mid = tsdb.uids.metrics.get_or_create_id("sys.live")
    kid = tsdb.uids.tag_names.get_or_create_id("host")
    ts_grid = BASE_MS + np.arange(span_s, dtype=np.int64) * 1000
    chunk = max(1, 10_000_000 // span_s)
    t0 = time.perf_counter()
    for lo in range(0, n_series, chunk):
        hi = min(lo + chunk, n_series)
        sids = np.asarray([
            tsdb.store.get_or_create_series(
                mid, [(kid, tsdb.uids.tag_values.get_or_create_id(
                    f"h{i:05d}"))])
            for i in range(lo, hi)], dtype=np.int64)
        vals = rng.normal(100, 10, (hi - lo, span_s))
        tsdb.store.append_grid(sids, ts_grid, vals,
                               np.ones((hi - lo, span_s), dtype=bool))
    ingest_s = time.perf_counter() - t0
    end_ms = BASE_MS + span_s * 1000
    qobj = {"start": BASE_MS, "end": end_ms,
            "queries": [{"metric": "sys.live", "aggregator": "sum",
                         "downsample": "1m-avg"}]}
    reg = tsdb.streaming
    cq = reg.register(qobj, now_ms=end_ms)

    def run_query():
        return tsdb.execute_query(TSQuery.from_json(qobj).validate())

    def run_full():
        tsdb.config.override_config("tsd.streaming.serve", "false")
        tsdb.config.override_config("tsd.query.cache.enable", "false")
        try:
            t0 = time.perf_counter()
            run_query()
            return time.perf_counter() - t0
        finally:
            tsdb.config.override_config("tsd.streaming.serve", "true")
            tsdb.config.override_config("tsd.query.cache.enable",
                                        "true")
    run_query()   # warm the incremental tail compile
    run_full()    # warm the batch pipeline compile
    sub = reg.subscribe(cq)
    while not sub.queue.empty():
        sub.queue.get_nowait()  # drop the snapshot
    rounds = max(repeats, 5)
    incr, full, sse_lat = [], [], []
    tick_hosts = min(n_series, 500)
    for r in range(rounds):
        # sustained ingest: one fresh point per tick host, landing in
        # the live window
        ts_s = BASE_MS // 1000 + span_s - 30 + (r % 20)
        for j in range(tick_hosts):
            tsdb.add_point("sys.live", ts_s, 100.0 + r,
                           {"host": f"h{j:05d}"})
        hits0 = reg.serve_hits
        t0 = time.perf_counter()
        run_query()
        incr.append(time.perf_counter() - t0)
        assert reg.serve_hits == hits0 + 1, \
            "refresh was not served from maintained windows"
        while not sub.queue.empty():
            sub.queue.get_nowait()
        t0 = time.perf_counter()
        tsdb.add_point("sys.live", ts_s, 1.0, {"host": "h00000"})
        reg.flush()
        sub.queue.get(timeout=10)
        sse_lat.append(time.perf_counter() - t0)
        full.append(run_full())
    incr_p50 = _percentile(incr, 50) * 1e3
    full_p50 = _percentile(full, 50) * 1e3
    speedup = full_p50 / max(incr_p50, 1e-3)
    return {"config": "live", "series": n_series,
            "points": n_series * span_s,
            "ingest_mpps": round(n_series * span_s / ingest_s / 1e6, 1),
            "tick_points": tick_hosts,
            "incremental_p50_ms": round(incr_p50, 2),
            "full_p50_ms": round(full_p50, 2),
            "refresh_speedup": round(speedup, 1),
            "sse_push_p50_ms": round(_percentile(sse_lat, 50) * 1e3, 2),
            "rounds": rounds,
            "criterion_pass": bool(speedup >= 10.0)}


def bench_streamv2(repeats: int, n_ticks: int = 400,
                   n_points_fold: int = 240_000) -> dict:
    """Streaming engine v2: (1) durable per-point ingest p50 with
    0 / 10 / 50 standing tumbling CQs over the ingested metric — the
    tap is an O(1) enqueue into shared partials and folds run on the
    worker pool, so the 50-CQ tax must stay <= 1.25x the zero-CQ
    p50; (2) shared-plan fold scaling — total fold time for 16 CQs
    sharing one (metric, downsample) <= 2x a single CQ's (one
    partial array serves all 16); (3) sliding-window serve p50 from
    the maintained partials; (4) a tier-seeded bootstrap serving a
    pre-demotion-boundary window incrementally (no batch fallback)."""
    import shutil
    import tempfile
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.query.model import TSQuery

    end_ms = BASE_MS + 1800 * 1000
    fns = ["1m-sum", "1m-avg", "1m-max", "1m-min", "1m-count",
           "2m-sum", "2m-avg", "2m-max", "2m-min", "2m-count"]
    aggs = ["sum", "avg", "max", "min", "sum"]

    def qobj(i=0, ds=None):
        return {"start": BASE_MS, "end": end_ms, "queries": [
            {"metric": "sys.sv2", "aggregator": aggs[i % len(aggs)],
             "downsample": ds or fns[i % len(fns)]}]}

    # --- (1) durable ingest tax at 0 / 10 / 50 standing CQs
    def ingest_p50_us(n_cqs: int) -> float:
        d = tempfile.mkdtemp(prefix="sv2bench-")
        t = TSDB(Config(**{
            "tsd.core.auto_create_metrics": "true",
            "tsd.storage.backend": "memory",
            "tsd.storage.data_dir": d}))
        try:
            for i in range(n_cqs):
                t.streaming.register(qobj(i), now_ms=end_ms)
            best = None
            for _ in range(max(repeats, 3)):
                times = []
                for i in range(n_ticks):
                    t0 = time.perf_counter()
                    t.add_point("sys.sv2", BASE_S + i, 1.0,
                                {"host": f"h{i % 8:02d}"})
                    times.append(time.perf_counter() - t0)
                p50 = _percentile(times, 50) * 1e6
                best = p50 if best is None else min(best, p50)
            return best
        finally:
            t.shutdown()
            shutil.rmtree(d, ignore_errors=True)

    p50_0 = ingest_p50_us(0)
    p50_10 = ingest_p50_us(10)
    p50_50 = ingest_p50_us(50)
    tax_10 = p50_10 / max(p50_0, 1e-3)
    tax_50 = p50_50 / max(p50_0, 1e-3)

    # --- (2) shared-plan fold scaling: 1 CQ vs 16 CQs, same
    # (metric, downsample) — workers off so the drain is timed
    # deterministically on this thread
    def fold_time_s(n_cqs: int) -> float:
        t = TSDB(Config(**{
            "tsd.core.auto_create_metrics": "true",
            "tsd.streaming.workers.count": "0",
            "tsd.streaming.buffer_points": str(1 << 30),
            "tsd.streaming.workers.max_pending_points":
                str(1 << 30)}))
        reg = t.streaming
        for i in range(n_cqs):
            obj = qobj(0)
            obj["id"] = f"f{i}"
            reg.register(obj, now_ms=end_ms)
        rng = np.random.default_rng(3)
        n_series = 64
        per = n_points_fold // n_series
        ts = BASE_MS + (np.arange(per, dtype=np.int64) * 1800_000
                        // per)
        best = None
        for _ in range(max(repeats, 3)):
            for g in reg._partials:
                g.take_pending()
            for i in range(n_series):
                t.add_points("sys.sv2", ts + i % 7,
                             rng.normal(100, 10, per),
                             {"host": f"h{i:03d}"})
            groups = list(reg._partials)
            t0 = time.perf_counter()
            for g in groups:
                reg._drain_group(g)
            dt = time.perf_counter() - t0
            best = dt if best is None else min(best, dt)
        folded = sum(g.points_folded for g in reg._partials)
        assert folded >= n_points_fold, folded
        return best

    fold_1 = fold_time_s(1)
    fold_16 = fold_time_s(16)
    fold_ratio = fold_16 / max(fold_1, 1e-9)

    # --- (3) sliding-window serve p50 from maintained partials
    t = TSDB(Config(**{"tsd.core.auto_create_metrics": "true"}))
    rng = np.random.default_rng(5)
    ts = np.arange(BASE_S, BASE_S + 1800, 2, dtype=np.int64)
    for i in range(200):
        t.add_points("sys.sv2", ts, rng.normal(100, 10, len(ts)),
                     {"host": f"h{i:03d}"})
    cq = t.streaming.register(
        dict(qobj(0, ds="1m-sum"),
             window={"type": "sliding", "size": "5m"}),
        now_ms=end_ms)
    t.streaming.current_results(cq, now_ms=end_ms)  # warm the tail
    sliding = []
    for r in range(max(repeats, 5)):
        t.add_point("sys.sv2", BASE_S + 1700 + r, 1.0,
                    {"host": "h000"})
        t0 = time.perf_counter()
        rows = t.streaming.current_results(cq, now_ms=end_ms)
        sliding.append(time.perf_counter() - t0)
        assert rows and rows[0]["dps"]
    sliding_p50 = _percentile(sliding, 50) * 1e3

    # --- (4) tier-seeded bootstrap: pre-boundary window serves
    # incrementally (no batch fallback)
    tl = TSDB(Config(**{
        "tsd.core.auto_create_metrics": "true",
        "tsd.rollups.enable": "true",
        "tsd.lifecycle.enable": "true",
        "tsd.lifecycle.demote_after": "30m",
        "tsd.lifecycle.demote_tiers": "1m"}))
    span = 7200
    now_ms = BASE_MS + span * 1000
    ts = np.arange(BASE_S, BASE_S + span, 5, dtype=np.int64)
    for i in range(4):
        tl.add_points("sys.sv2", ts, rng.normal(100, 10, len(ts)),
                      {"host": f"h{i}"})
    tl.lifecycle.sweep(now_ms=now_ms)
    reg = tl.streaming
    reg.register({"start": BASE_MS, "end": now_ms, "queries": [
        {"metric": "sys.sv2", "aggregator": "sum",
         "downsample": "5m-avg"}]}, now_ms=now_ms)
    tsq = TSQuery.from_json({
        "start": BASE_MS, "end": now_ms, "queries": [
            {"metric": "sys.sv2", "aggregator": "sum",
             "downsample": "5m-avg"}]}).validate()
    tl.execute_query(tsq)
    tier_ok = bool(reg.serve_hits == 1 and reg.serve_fallbacks == 0
                   and reg._partials[0].tier_seeded)

    return {"config": "streamv2",
            "ingest_p50_us_0cq": round(p50_0, 1),
            "ingest_p50_us_10cq": round(p50_10, 1),
            "ingest_p50_us_50cq": round(p50_50, 1),
            "ingest_tax_10cq": round(tax_10, 3),
            "ingest_tax_50cq": round(tax_50, 3),
            "fold_s_1cq": round(fold_1, 4),
            "fold_s_16cq_shared": round(fold_16, 4),
            "fold_scaling_16cq": round(fold_ratio, 2),
            "fold_points": n_points_fold,
            "sliding_serve_p50_ms": round(sliding_p50, 2),
            "tier_seeded_preboundary_serve": tier_ok,
            "criterion_pass": bool(tax_50 <= 1.25
                                   and fold_ratio <= 2.0
                                   and tier_ok)}


def bench_lifecycle(repeats: int, n_series: int = 2000,
                    span_s: int = 7200) -> dict:
    """Aged-store lifecycle config: n_series x span @1s raw, a
    demote_after=30m policy folding everything older into the 1m
    rollup tiers (sum/count/min/max) and compacting the tail. Reports
    resident bytes before/after the sweep (criterion: >= 2x reduction)
    and the p50 of a boundary-spanning 1m-avg query on the swept
    store vs an identical all-raw baseline store (criterion: within
    1.5x — the stitched tier+tail read must not tax the dashboard).
    Sanity-checks the stitched result against the all-raw answer."""
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.query.model import TSQuery

    def mk(lifecycle: bool):
        cfg = {"tsd.core.auto_create_metrics": "true",
               "tsd.storage.backend": "memory",
               "tsd.rollups.enable": "true"}
        if lifecycle:
            cfg.update({"tsd.lifecycle.enable": "true",
                        "tsd.lifecycle.demote_after": "30m",
                        "tsd.lifecycle.demote_tiers": "1m"})
        return TSDB(Config(**cfg))

    t_raw, t_lc = mk(False), mk(True)
    ts = np.arange(BASE_S, BASE_S + span_s, dtype=np.int64)
    rng = np.random.default_rng(13)
    t0 = time.perf_counter()
    for i in range(n_series):
        vals = rng.normal(100, 10, span_s)
        for t in (t_raw, t_lc):
            t.add_points("sys.aged", ts, vals, {"host": f"h{i:05d}"})
    ingest_s = time.perf_counter() - t0
    now_ms = BASE_MS + span_s * 1000
    before = t_lc.storage_memory_info()["total"]["resident_bytes"]
    t0 = time.perf_counter()
    rep = t_lc.lifecycle.sweep(now_ms=now_ms)
    sweep_s = time.perf_counter() - t0
    after = t_lc.storage_memory_info()["total"]["resident_bytes"]
    qobj = {"start": BASE_MS, "end": now_ms,
            "queries": [{"metric": "sys.aged", "aggregator": "sum",
                         "downsample": "1m-avg"}]}

    def p50(tsdb):
        tsdb.config.override_config("tsd.query.cache.enable", "false")
        times = []
        tsdb.execute_query(TSQuery.from_json(qobj).validate())  # warm
        for _ in range(max(repeats, 3)):
            t0 = time.perf_counter()
            out = tsdb.execute_query(TSQuery.from_json(qobj).validate())
            times.append(time.perf_counter() - t0)
        return _percentile(times, 50) * 1e3, out

    lc_p50, lc_out = p50(t_lc)
    raw_p50, raw_out = p50(t_raw)
    d_lc, d_raw = dict(lc_out[0].dps), dict(raw_out[0].dps)
    assert d_lc.keys() == d_raw.keys(), "stitched dropped buckets"
    worst = max(abs(d_lc[k] - d_raw[k]) / max(abs(d_raw[k]), 1e-12)
                for k in d_raw)
    bytes_ratio = before / max(after, 1)
    p50_ratio = lc_p50 / max(raw_p50, 1e-3)
    return {"config": "lifecycle", "series": n_series,
            "points": n_series * span_s,
            "ingest_mpps": round(n_series * span_s / ingest_s / 1e6,
                                 1),
            "sweep_s": round(sweep_s, 1),
            "points_demoted": rep.get("demoted", 0),
            "tier_points_written": rep.get("tierPointsWritten", 0),
            "resident_bytes_before": before,
            "resident_bytes_after": after,
            "bytes_ratio": round(bytes_ratio, 1),
            "boundary_p50_ms": round(lc_p50, 1),
            "all_raw_p50_ms": round(raw_p50, 1),
            "p50_ratio": round(p50_ratio, 2),
            "stitch_worst_rel_err": float(f"{worst:.2e}"),
            "criterion_pass": bool(bytes_ratio >= 2.0
                                   and p50_ratio <= 1.5)}


def bench_cold(repeats: int, n_series: int = 2000,
               span_s: int = 7200) -> dict:
    """Aged-spilled cold-tier config: n_series x span @1s raw, a
    demote_after=30m policy folding aged raw into the 1m tiers, then
    spill_after=32m moving all but the freshest tier band into
    mmap-backed cold segments (opentsdb_tpu/coldstore/) and releasing
    the tier RAM. Compares against an identical no-spill store (tiers
    stay in RAM). Criteria: resident RAM for AGED history (the rollup
    tier stores) >= 5x lower than no-spill, and the p50 of a
    boundary-spanning 1m-avg query over cold+tier+raw within 2x of
    the all-RAM store. Sanity-checks the stitched result against the
    no-spill answer."""
    import shutil
    import tempfile
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.query.model import TSQuery

    cold_dir = tempfile.mkdtemp(prefix="coldbench-")

    def mk(spill: bool):
        cfg = {"tsd.core.auto_create_metrics": "true",
               "tsd.storage.backend": "memory",
               "tsd.rollups.enable": "true",
               "tsd.lifecycle.enable": "true",
               "tsd.lifecycle.demote_after": "30m",
               "tsd.lifecycle.demote_tiers": "1m"}
        if spill:
            cfg.update({"tsd.lifecycle.spill_after": "32m",
                        "tsd.coldstore.dir": cold_dir})
        return TSDB(Config(**cfg))

    def aged_bytes(tsdb):
        """Resident bytes of the rollup tier stores — where aged
        (demoted) history lives in RAM."""
        info = tsdb.storage_memory_info()
        return sum(v["resident_bytes"] for k, v in info.items()
                   if k.startswith("rollup:"))

    t_ram, t_cold = mk(False), mk(True)
    ts = np.arange(BASE_S, BASE_S + span_s, dtype=np.int64)
    rng = np.random.default_rng(17)
    t0 = time.perf_counter()
    for i in range(n_series):
        vals = rng.normal(100, 10, span_s)
        for t in (t_ram, t_cold):
            t.add_points("sys.aged", ts, vals, {"host": f"h{i:05d}"})
    ingest_s = time.perf_counter() - t0
    now_ms = BASE_MS + span_s * 1000
    for t in (t_ram, t_cold):
        rep = t.lifecycle.sweep(now_ms=now_ms)
        assert rep.get("demoted", 0) > 0, rep
    spilled = rep.get("spilled", 0)
    cold = t_cold.lifecycle.coldstore
    aged_ram = aged_bytes(t_ram)
    aged_spill = aged_bytes(t_cold)
    total_ram = t_ram.storage_memory_info()["total"]["resident_bytes"]
    total_spill = t_cold.storage_memory_info()["total"][
        "resident_bytes"]
    qobj = {"start": BASE_MS, "end": now_ms,
            "queries": [{"metric": "sys.aged", "aggregator": "sum",
                         "downsample": "1m-avg"}]}

    def p50(tsdb):
        tsdb.config.override_config("tsd.query.cache.enable", "false")
        times = []
        tsdb.execute_query(TSQuery.from_json(qobj).validate())  # warm
        for _ in range(max(repeats, 3)):
            t0 = time.perf_counter()
            out = tsdb.execute_query(
                TSQuery.from_json(qobj).validate())
            times.append(time.perf_counter() - t0)
        return _percentile(times, 50) * 1e3, out

    cold_p50, cold_out = p50(t_cold)
    ram_p50, ram_out = p50(t_ram)
    d_cold, d_ram = dict(cold_out[0].dps), dict(ram_out[0].dps)
    assert d_cold.keys() == d_ram.keys(), "stitch dropped buckets"
    worst = max(abs(d_cold[k] - d_ram[k]) / max(abs(d_ram[k]), 1e-12)
                for k in d_ram)
    aged_ratio = aged_ram / max(aged_spill, 1)
    p50_ratio = cold_p50 / max(ram_p50, 1e-3)
    out = {"config": "cold", "series": n_series,
           "points": n_series * span_s,
           "ingest_mpps": round(n_series * span_s / ingest_s / 1e6,
                                1),
           "points_spilled": spilled,
           "cold_segments": cold.segments_written,
           "cold_disk_bytes": cold.cold_bytes(),
           "aged_resident_bytes_nospill": aged_ram,
           "aged_resident_bytes_spill": aged_spill,
           "aged_bytes_ratio": round(aged_ratio, 1),
           "total_resident_bytes_nospill": total_ram,
           "total_resident_bytes_spill": total_spill,
           "total_bytes_ratio": round(
               total_ram / max(total_spill, 1), 2),
           "boundary_p50_ms": round(cold_p50, 1),
           "all_ram_p50_ms": round(ram_p50, 1),
           "p50_ratio": round(p50_ratio, 2),
           "stitch_worst_rel_err": float(f"{worst:.2e}"),
           "criterion_pass": bool(aged_ratio >= 5.0
                                  and p50_ratio <= 2.0)}
    shutil.rmtree(cold_dir, ignore_errors=True)
    return out


def bench_sketch(repeats: int, n_series: int = 64,
                 span_s: int = 7200) -> dict:
    """Quantile-sketch config: p99 percentile queries over the three
    storage shapes the sketch column serves — all-raw (live fold),
    tier-demoted (persisted sketch cells), and cold-spilled (mmap
    sketch blobs stitched with tier + raw tail) — plus a 3-shard
    scatter/gather whose merged partials must be bit-equal to a
    single-node oracle. Every answer is checked against the exact
    lower order statistic of the pooled raw values per bucket;
    criterion: worst relative error <= 1.1 * alpha for all shapes
    and a bit-equal cluster merge."""
    import shutil
    import tempfile
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.query.model import TSQuery

    cold_dir = tempfile.mkdtemp(prefix="sketchbench-")

    def mk(shape: str):
        cfg = {"tsd.core.auto_create_metrics": "true",
               "tsd.storage.backend": "memory",
               "tsd.query.cache.enable": "false",
               "tsd.tpu.warmup": "false"}
        if shape in ("demoted", "cold"):
            cfg.update({"tsd.rollups.enable": "true",
                        "tsd.lifecycle.enable": "true",
                        "tsd.lifecycle.demote_after": "30m",
                        "tsd.lifecycle.demote_tiers": "1m"})
        if shape == "cold":
            cfg.update({"tsd.lifecycle.spill_after": "60m",
                        "tsd.coldstore.dir": cold_dir})
        return TSDB(Config(**cfg))

    stores = {s: mk(s) for s in ("raw", "demoted", "cold")}
    alpha = stores["raw"].config.get_float("tsd.sketch.alpha", 0.01)
    bound = 1.1 * alpha
    ts = np.arange(BASE_S, BASE_S + span_s, dtype=np.int64)
    rng = np.random.default_rng(23)
    vals = rng.lognormal(3.0, 1.0, (n_series, span_s))
    t0 = time.perf_counter()
    for i in range(n_series):
        for t in stores.values():
            t.add_points("sys.lat", ts, vals[i],
                         {"host": f"h{i:04d}"})
    ingest_s = time.perf_counter() - t0
    now_ms = BASE_MS + span_s * 1000
    rep = stores["demoted"].lifecycle.sweep(now_ms=now_ms)
    assert rep.get("demoted", 0) > 0, rep
    rep = stores["cold"].lifecycle.sweep(now_ms=now_ms)
    assert rep.get("spilled", 0) > 0, rep

    # exact p99 per 5m bucket over the pooled raw values
    bucket_ms = 300_000
    slots = (ts * 1000) - (ts * 1000) % bucket_ms
    exact = {int(s): float(np.percentile(
        vals[:, slots == s].ravel(), 99.0, method="lower"))
        for s in np.unique(slots)}

    qobj = {"start": BASE_MS, "end": now_ms,
            "queries": [{"metric": "sys.lat", "aggregator": "sum",
                         "downsample": "5m-avg",
                         "percentiles": [99.0]}]}

    def p50(tsdb):
        tsdb.execute_query(TSQuery.from_json(qobj).validate())  # warm
        times, out = [], None
        for _ in range(max(repeats, 3)):
            t0 = time.perf_counter()
            out = tsdb.execute_query(
                TSQuery.from_json(qobj).validate())
            times.append(time.perf_counter() - t0)
        return _percentile(times, 50) * 1e3, out

    lat, err = {}, {}
    for shape, t in stores.items():
        ms, out_rows = p50(t)
        rows = [r for r in out_rows
                if r.metric.endswith("_pct_99")]
        got = {}
        for r in rows:
            got.update(r.dps)
        assert set(got) == set(exact), (shape, "buckets differ")
        lat[shape] = ms
        err[shape] = max(
            abs(got[s] - exact[s]) / max(abs(exact[s]), 1e-12)
            for s in exact)

    cluster = _bench_sketch_cluster(repeats)
    out = {"config": "sketch", "alpha": alpha,
           "error_bound": round(bound, 4),
           "series": n_series, "points": n_series * span_s,
           "ingest_mpps": round(
               3 * n_series * span_s / ingest_s / 1e6, 2),
           "points_spilled": rep["spilled"],
           "p99_raw_p50_ms": round(lat["raw"], 1),
           "p99_demoted_p50_ms": round(lat["demoted"], 1),
           "p99_cold_p50_ms": round(lat["cold"], 1),
           "cold_vs_raw_ratio": round(
               lat["cold"] / max(lat["raw"], 1e-3), 2),
           "worst_rel_err": {k: float(f"{v:.2e}")
                             for k, v in err.items()},
           "cluster": cluster,
           "criterion_pass": bool(
               all(v <= bound for v in err.values())
               and cluster["merged_bit_equal"])}
    for t in stores.values():
        t.shutdown()
    shutil.rmtree(cold_dir, ignore_errors=True)
    return out


def _bench_sketch_cluster(repeats: int, n_hosts: int = 24,
                          span_s: int = 600) -> dict:
    """3-shard percentile scatter/gather leg of the sketch config:
    the router folds per-shard serialized sketch partials and must
    answer bit-equal to a single node holding all the points."""
    import asyncio
    import json as _json
    import threading

    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter
    from opentsdb_tpu.tsd.server import TSDServer

    peer_cfg = {"tsd.core.auto_create_metrics": "true",
                "tsd.tpu.warmup": "false"}

    class Peer:
        def __init__(self):
            self.tsdb = TSDB(Config(**peer_cfg))
            self.loop = asyncio.new_event_loop()
            self.server = TSDServer(self.tsdb, host="127.0.0.1",
                                    port=0)
            started = threading.Event()

            def run():
                asyncio.set_event_loop(self.loop)
                self.loop.run_until_complete(self.server.start())
                started.set()
                self.loop.run_forever()

            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()
            assert started.wait(30)
            self.port = (self.server._server.sockets[0]
                         .getsockname()[1])

        def stop(self):
            try:
                asyncio.run_coroutine_threadsafe(
                    self.server.stop(), self.loop).result(20)
            except Exception:  # noqa: BLE001
                pass
            self.loop.call_soon_threadsafe(self.loop.stop)

    def req(method, path, body=None, **params):
        return HttpRequest(
            method=method, path=path,
            params={k: [str(v)] for k, v in params.items()},
            body=_json.dumps(body).encode()
            if body is not None else b"")

    peers = [Peer() for _ in range(3)]
    spec = ",".join(f"s{i}=127.0.0.1:{p.port}"
                    for i, p in enumerate(peers))
    router = TSDB(Config(**{
        "tsd.cluster.role": "router", "tsd.cluster.peers": spec,
        "tsd.query.cache.enable": "false",
        "tsd.tpu.warmup": "false"}))
    http = HttpRpcRouter(router)
    router.cluster.start()
    single = TSDB(Config(**{**peer_cfg,
                            "tsd.query.cache.enable": "false"}))
    single_http = HttpRpcRouter(single)

    rng = np.random.default_rng(29)
    points = [{"metric": "bench.sk", "timestamp": BASE_S + i,
               "value": float(v),
               "tags": {"host": f"h{h:03d}"}}
              for h in range(n_hosts)
              for i, v in enumerate(rng.lognormal(2, 1, span_s))]
    for target in (http, single_http):
        for i in range(0, len(points), 4000):
            resp = target.handle(req("POST", "/api/put",
                                     points[i:i + 4000],
                                     summary="true"))
            assert resp.status == 200
            assert _json.loads(resp.body)["failed"] == 0

    qbody = {"start": BASE_MS - 1000,
             "end": BASE_MS + span_s * 1000,
             "queries": [{"metric": "bench.sk", "aggregator": "sum",
                          "downsample": "1m-avg",
                          "percentiles": [99.0]}]}

    def read_p50(target):
        target.handle(req("POST", "/api/query", qbody))  # warm
        times, body = [], b""
        for _ in range(max(repeats, 3)):
            t0 = time.perf_counter()
            resp = target.handle(req("POST", "/api/query", qbody))
            times.append(time.perf_counter() - t0)
            assert resp.status == 200
            body = resp.body
        return _percentile(times, 50) * 1e3, body

    scatter_p50, scatter_body = read_p50(http)
    single_p50, single_body = read_p50(single_http)

    def rows(body):
        doc = _json.loads(body)
        if doc and isinstance(doc[-1], dict) \
                and "shardsDegraded" in doc[-1]:
            doc = doc[:-1]
        return sorted((r["metric"], sorted(r["tags"].items()),
                       sorted(r["dps"].items())) for r in doc)

    merged = rows(scatter_body)
    bit_equal = bool(merged and merged == rows(single_body))
    for p in peers:
        p.stop()
    router.shutdown()
    single.shutdown()
    return {"shards": 3, "series": n_hosts,
            "points": len(points),
            "scatter_p99_p50_ms": round(scatter_p50, 1),
            "single_p99_p50_ms": round(single_p50, 1),
            "scatter_gather_overhead": round(
                scatter_p50 / max(single_p50, 1e-3), 2),
            "merged_bit_equal": bit_equal}


def bench_wal(repeats: int, n_series: int = 500,
              pts_per: int = 4000) -> dict:
    """Ingest throughput with the write-ahead log off / on. 'on'
    fsyncs per write call (group commit), the acked-means-durable
    default; 'on_nosync' appends but never fsyncs (the OS flushes) —
    the reference's setDurable(false) class of durability."""
    import shutil
    import tempfile
    from opentsdb_tpu import TSDB, Config
    ts = np.arange(BASE_S, BASE_S + pts_per, dtype=np.int64)
    rng = np.random.default_rng(7)
    vals = rng.normal(100, 10, (n_series, pts_per))
    out = {"config": "wal", "series": n_series,
           "points": n_series * pts_per}
    for label, cfg in (
            ("off", {"tsd.storage.wal.enable": "false"}),
            ("on", {"tsd.storage.wal.fsync": "always"}),
            ("on_nosync", {"tsd.storage.wal.fsync": "never"})):
        best = float("inf")
        for _ in range(max(1, repeats // 2)):
            d = tempfile.mkdtemp(prefix="walbench-")
            try:
                tsdb = TSDB(Config(**{
                    "tsd.core.auto_create_metrics": "true",
                    "tsd.storage.data_dir": d, **cfg}))
                t0 = time.perf_counter()
                for i in range(n_series):
                    tsdb.add_points("sys.walbench", ts, vals[i],
                                    {"host": f"h{i:04d}"})
                best = min(best, time.perf_counter() - t0)
                if tsdb.wal is not None:
                    tsdb.wal.close()
            finally:
                shutil.rmtree(d, ignore_errors=True)
        out[f"ingest_mpps_{label}"] = round(
            n_series * pts_per / best / 1e6, 2)
    return out


def bench_ingest(repeats: int, n_points: int = 120_000,
                 n_series: int = 200) -> dict:
    """Durable ingest raw speed through the three front doors —
    telnet ``put`` line bursts (columnar batch decode), HTTP
    ``/api/put`` JSON bodies, and the import buffer — with the WAL
    off vs ``fsync=always`` (acked => fsynced). Also measures the
    PER-REQUEST durable rate (one point per telnet line / HTTP body,
    one fsync each — the pre-group-commit behavior) as the baseline
    the batch path must beat.

    Criteria: durable batch ingest >= 1/3 of the WAL-off rate on the
    import path (the 10x durability tax collapses to <= 3x), and the
    batched telnet/HTTP durable rates >= 3x their per-request rates.
    """
    import json as _json
    import shutil
    import tempfile
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter
    from opentsdb_tpu.tsd.telnet import TelnetRouter

    rng = np.random.default_rng(23)
    ts = BASE_S + np.arange(n_points, dtype=np.int64) % 7200
    hosts = np.arange(n_points) % n_series
    vals = np.round(rng.normal(100, 10, n_points), 2)
    telnet_lines = [f"put sys.ing {ts[i]} {vals[i]} host=h{hosts[i]:04d}"
                    for i in range(n_points)]
    import_buf = "".join(
        f"sys.ing {ts[i]} {vals[i]} host=h{hosts[i]:04d}\n"
        for i in range(n_points)).encode()
    put_dicts = [{"metric": "sys.ing", "timestamp": int(ts[i]),
                  "value": float(vals[i]),
                  "tags": {"host": f"h{hosts[i]:04d}"}}
                 for i in range(n_points)]

    def mk(cfg):
        d = tempfile.mkdtemp(prefix="ingbench-")
        t = TSDB(Config(**{
            "tsd.core.auto_create_metrics": "true",
            "tsd.storage.backend": "memory",
            "tsd.storage.data_dir": d, **cfg}))
        return d, t

    def run(door, cfg, points) -> float:
        """Best-of-repeats Mpps for one front door x WAL config."""
        best = float("inf")
        for _ in range(max(1, repeats // 2)):
            d, t = mk(cfg)
            try:
                if door == "import":
                    t0 = time.perf_counter()
                    written, errs = t.import_buffer(import_buf)
                    dt = time.perf_counter() - t0
                elif door == "telnet":
                    router = TelnetRouter(t)
                    burst = 4096  # ~one socket read's worth of lines
                    t0 = time.perf_counter()
                    for lo in range(0, points, burst):
                        resp, _exc = router.execute_lines(
                            telnet_lines[lo:lo + burst])
                        assert not resp, resp
                    dt = time.perf_counter() - t0
                elif door == "http":
                    router = HttpRpcRouter(t)
                    body_pts = 2000  # one /api/put body
                    bodies = [
                        _json.dumps(put_dicts[lo:lo + body_pts])
                        .encode()
                        for lo in range(0, points, body_pts)]
                    t0 = time.perf_counter()
                    for body in bodies:
                        r = router.handle(HttpRequest(
                            "POST", "/api/put", {}, body=body))
                        assert r.status == 204, r.body
                    dt = time.perf_counter() - t0
                elif door == "telnet_scalar":
                    router = TelnetRouter(t)
                    t0 = time.perf_counter()
                    for ln in telnet_lines[:points]:
                        out = router.execute(ln)
                        assert not out, out
                    dt = time.perf_counter() - t0
                else:  # http_scalar: one point per request body
                    router = HttpRpcRouter(t)
                    bodies = [_json.dumps([dp]).encode()
                              for dp in put_dicts[:points]]
                    t0 = time.perf_counter()
                    for body in bodies:
                        r = router.handle(HttpRequest(
                            "POST", "/api/put", {}, body=body))
                        assert r.status == 204, r.body
                    dt = time.perf_counter() - t0
                assert t.store.total_points() > 0
                best = min(best, dt)
                if t.wal is not None:
                    t.wal.close()
            finally:
                shutil.rmtree(d, ignore_errors=True)
        return best

    wal_off = {"tsd.storage.wal.enable": "false"}
    wal_on = {"tsd.storage.wal.fsync": "always"}
    out = {"config": "ingest", "points": n_points,
           "series": n_series}
    for door in ("import", "telnet", "http"):
        n = n_points
        out[f"{door}_mpps_off"] = round(n / run(door, wal_off, n) / 1e6,
                                        3)
        out[f"{door}_mpps_durable"] = round(
            n / run(door, wal_on, n) / 1e6, 3)
    # per-request (pre-overhaul) durable baselines: one fsync per
    # point — sized down, these are the slow paths being replaced
    scalar_n = 3000
    out["telnet_scalar_kpps_durable"] = round(
        scalar_n / run("telnet_scalar", wal_on, scalar_n) / 1e3, 2)
    out["http_scalar_kpps_durable"] = round(
        scalar_n / run("http_scalar", wal_on, scalar_n) / 1e3, 2)
    out["durability_tax"] = round(
        out["import_mpps_off"] / max(out["import_mpps_durable"], 1e-9),
        2)
    out["telnet_batch_vs_scalar"] = round(
        out["telnet_mpps_durable"] * 1e3
        / max(out["telnet_scalar_kpps_durable"], 1e-9), 1)
    out["http_batch_vs_scalar"] = round(
        out["http_mpps_durable"] * 1e3
        / max(out["http_scalar_kpps_durable"], 1e-9), 1)
    out["criterion_pass"] = bool(
        out["durability_tax"] <= 3.0
        and out["telnet_batch_vs_scalar"] >= 3.0
        and out["http_batch_vs_scalar"] >= 3.0)
    return out


def bench_obs(repeats: int, n_points: int = 60_000,
              n_series: int = 200) -> dict:
    """Tracing overhead config: the ``ingest`` (HTTP /api/put door)
    and ``viz`` (dense dashboard query) workloads with tracing ON at
    default sampling (tsd.trace.enable=true, sample=64) vs OFF.
    Requests route through HttpRpcRouter.handle so they pay the real
    root-trace + stage-span cost. WAL off and result cache off — the
    strictest (least-amortized) setting for relative overhead.
    Criterion: p50 overhead <= 5% on both workloads."""
    import json as _json
    import shutil
    import tempfile
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter

    rng = np.random.default_rng(31)
    ts = BASE_S + np.arange(n_points, dtype=np.int64) % 7200
    hosts = np.arange(n_points) % n_series
    vals = np.round(rng.normal(100, 10, n_points), 2)
    body_pts = 2000
    put_dicts = [{"metric": "sys.obs", "timestamp": int(ts[i]),
                  "value": float(vals[i]),
                  "tags": {"host": f"h{hosts[i]:04d}"}}
                 for i in range(n_points)]
    bodies = [_json.dumps(put_dicts[lo:lo + body_pts]).encode()
              for lo in range(0, n_points, body_pts)]

    def mk(trace_on: bool):
        d = tempfile.mkdtemp(prefix="obsbench-")
        t = TSDB(Config(**{
            "tsd.core.auto_create_metrics": "true",
            "tsd.storage.backend": "memory",
            "tsd.storage.data_dir": d,
            "tsd.storage.wal.enable": "false",
            "tsd.query.cache.enable": "false",
            "tsd.tpu.warmup": "false",
            "tsd.trace.enable": "true" if trace_on else "false",
        }))
        return d, t, HttpRpcRouter(t)

    def ingest_pass(trace_on: bool) -> float:
        d, t, router = mk(trace_on)
        try:
            t0 = time.perf_counter()
            for body in bodies:
                r = router.handle(HttpRequest(
                    "POST", "/api/put", {}, body=body))
                assert r.status == 204, r.body
            return time.perf_counter() - t0
        finally:
            t.shutdown()
            shutil.rmtree(d, ignore_errors=True)

    # interleave off/on passes (host noise on a shared box swings
    # single-config timings by +-30% — far more than the effect under
    # test; alternation distributes it fairly) and compare best-of
    ing = {False: [], True: []}
    for _ in range(max(repeats, 4)):
        for mode in (False, True):
            ing[mode].append(ingest_pass(mode))

    span_s = 4 * 3600  # 4h @ 1s x 12 series: serialization-heavy
    ts_grid = BASE_MS + np.arange(span_s, dtype=np.int64) * 1000

    def mk_viz(trace_on: bool):
        d, t, router = mk(trace_on)
        mid = t.uids.metrics.get_or_create_id("sys.viz")
        kid = t.uids.tag_names.get_or_create_id("host")
        sids = np.asarray([
            t.store.get_or_create_series(
                mid, [(kid,
                       t.uids.tag_values.get_or_create_id(
                           f"h{j}"))])
            for j in range(12)], dtype=np.int64)
        t.store.append_grid(
            sids, ts_grid, rng.normal(100, 10, (12, span_s)),
            np.ones((12, span_s), dtype=bool))
        return d, t, router

    qb = _json.dumps({
        "start": BASE_MS, "end": BASE_MS + span_s * 1000,
        "queries": [{"metric": "sys.viz", "aggregator": "sum",
                     "downsample": "1s-avg",
                     "filters": [{"type": "wildcard", "tagk": "host",
                                  "filter": "*",
                                  "groupBy": True}]}],
        "pixels": 1500}).encode()
    viz = {False: mk_viz(False), True: mk_viz(True)}
    times = {False: [], True: []}
    try:
        for mode in (False, True):  # warm compiles (shared cache)
            r = viz[mode][2].handle(HttpRequest(
                "POST", "/api/query", {}, body=qb))
            assert r.status == 200, r.body
        for _ in range(max(repeats, 9)):
            for mode in (False, True):
                t0 = time.perf_counter()
                r = viz[mode][2].handle(HttpRequest(
                    "POST", "/api/query", {}, body=qb))
                times[mode].append(time.perf_counter() - t0)
                assert r.status == 200
        trace_counters = viz[True][1].tracer.health_info()
    finally:
        for mode in (False, True):
            viz[mode][1].shutdown()
            shutil.rmtree(viz[mode][0], ignore_errors=True)

    out = {
        "config": "obs", "points": n_points,
        "ingest_s_trace_off": round(min(ing[False]), 4),
        "ingest_s_trace_on": round(min(ing[True]), 4),
        "ingest_overhead": round(
            min(ing[True]) / max(min(ing[False]), 1e-9), 4),
        "viz_p50_ms_trace_off": round(
            _percentile(times[False], 50) * 1e3, 2),
        "viz_p50_ms_trace_on": round(
            _percentile(times[True], 50) * 1e3, 2),
        "viz_overhead": round(
            _percentile(times[True], 50)
            / max(_percentile(times[False], 50), 1e-9), 4),
        "trace_counters_on": trace_counters,
    }
    out["criterion_pass"] = bool(out["ingest_overhead"] <= 1.05
                                 and out["viz_overhead"] <= 1.05)
    return out


def bench_obs2(repeats: int, n_points: int = 40_000,
               n_series: int = 200) -> dict:
    """Fleet-observability overhead config: (1) ``GET /metrics``
    render cost on a registry populated with realistic histogram +
    counter state (what a Prometheus scrape pays), and (2) the
    ingest/viz workloads with the continuous profiler ON at its
    default rate (tsd.profile.hz=4) AND a concurrent /metrics
    scraper — vs both off. Criterion: p50 overhead <= 5% on both
    workloads (the ISSUE-15 acceptance bound)."""
    import json as _json
    import shutil
    import tempfile
    import threading as _threading
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter

    # -- part 1: /metrics render cost ----------------------------------
    t = TSDB(Config(**{
        "tsd.core.auto_create_metrics": "true",
        "tsd.storage.backend": "memory",
        "tsd.tpu.warmup": "false",
    }))
    rng = np.random.default_rng(41)
    for v in rng.gamma(2.0, 20.0, size=4000):
        t.stats.latency_query.add(float(v))
        t.stats.latency_put.add(float(v) / 4)
    for stage in ("query.plan", "query.execute", "query.assemble",
                  "query.serialize", "ingest.decode",
                  "store.scatter", "wal.commit_wait",
                  "query.admission"):
        for v in rng.gamma(2.0, 8.0, size=2000):
            t.stats.observe_stage(stage, float(v))
    router = HttpRpcRouter(t)
    render_times = []
    body_bytes = 0
    for _ in range(max(repeats * 4, 20)):
        t0 = time.perf_counter()
        resp = router.handle(HttpRequest("GET", "/metrics", {}))
        render_times.append(time.perf_counter() - t0)
        assert resp.status == 200
        body_bytes = len(resp.body)
    t.shutdown()

    # -- part 2: profiler + scrape overhead on real workloads ----------
    ts = BASE_S + np.arange(n_points, dtype=np.int64) % 7200
    hosts = np.arange(n_points) % n_series
    vals = np.round(rng.normal(100, 10, n_points), 2)
    body_pts = 2000
    put_dicts = [{"metric": "sys.obs2", "timestamp": int(ts[i]),
                  "value": float(vals[i]),
                  "tags": {"host": f"h{hosts[i]:04d}"}}
                 for i in range(n_points)]
    bodies = [_json.dumps(put_dicts[lo:lo + body_pts]).encode()
              for lo in range(0, n_points, body_pts)]

    def mk(obs_on: bool):
        d = tempfile.mkdtemp(prefix="obs2bench-")
        tt = TSDB(Config(**{
            "tsd.core.auto_create_metrics": "true",
            "tsd.storage.backend": "memory",
            "tsd.storage.data_dir": d,
            "tsd.storage.wal.enable": "false",
            "tsd.query.cache.enable": "false",
            "tsd.tpu.warmup": "false",
            "tsd.profile.enable": "true" if obs_on else "false",
        }))
        rt = HttpRpcRouter(tt)
        stop = None
        if obs_on:
            tt.profiler.start()   # default 4 Hz, the always-on rate
            stop = _threading.Event()

            def scrape():
                while not stop.wait(0.25):
                    rt.handle(HttpRequest("GET", "/metrics", {}))

            scr = _threading.Thread(target=scrape, daemon=True)
            scr.start()
            stop.thread = scr
        return d, tt, rt, stop

    def fin(d, tt, stop):
        if stop is not None:
            stop.set()
            stop.thread.join(5)
        tt.shutdown()
        shutil.rmtree(d, ignore_errors=True)

    def ingest_pass(obs_on: bool) -> float:
        d, tt, rt, stop = mk(obs_on)
        try:
            t0 = time.perf_counter()
            for body in bodies:
                r = rt.handle(HttpRequest("POST", "/api/put", {},
                                          body=body))
                assert r.status == 204, r.body
            return time.perf_counter() - t0
        finally:
            fin(d, tt, stop)

    ing = {False: [], True: []}
    for _ in range(max(repeats, 4)):
        for mode in (False, True):
            ing[mode].append(ingest_pass(mode))

    span_s = 2 * 3600
    ts_grid = BASE_MS + np.arange(span_s, dtype=np.int64) * 1000

    def mk_viz(obs_on: bool):
        d, tt, rt, stop = mk(obs_on)
        mid = tt.uids.metrics.get_or_create_id("sys.viz")
        kid = tt.uids.tag_names.get_or_create_id("host")
        sids = np.asarray([
            tt.store.get_or_create_series(
                mid, [(kid, tt.uids.tag_values.get_or_create_id(
                    f"h{j}"))])
            for j in range(8)], dtype=np.int64)
        tt.store.append_grid(
            sids, ts_grid, rng.normal(100, 10, (8, span_s)),
            np.ones((8, span_s), dtype=bool))
        return d, tt, rt, stop

    qb = _json.dumps({
        "start": BASE_MS, "end": BASE_MS + span_s * 1000,
        "queries": [{"metric": "sys.viz", "aggregator": "sum",
                     "downsample": "1s-avg",
                     "filters": [{"type": "wildcard", "tagk": "host",
                                  "filter": "*",
                                  "groupBy": True}]}],
        "pixels": 1500}).encode()
    viz = {False: mk_viz(False), True: mk_viz(True)}
    times = {False: [], True: []}
    try:
        for mode in (False, True):  # warm compiles (shared cache)
            r = viz[mode][2].handle(HttpRequest(
                "POST", "/api/query", {}, body=qb))
            assert r.status == 200, r.body
        for _ in range(max(repeats, 9)):
            for mode in (False, True):
                t0 = time.perf_counter()
                r = viz[mode][2].handle(HttpRequest(
                    "POST", "/api/query", {}, body=qb))
                times[mode].append(time.perf_counter() - t0)
                assert r.status == 200
        profiler_counters = viz[True][1].profiler.health_info()
    finally:
        for mode in (False, True):
            fin(viz[mode][0], viz[mode][1], viz[mode][3])

    out = {
        "config": "obs2", "points": n_points,
        "metrics_render_p50_ms": round(
            _percentile(render_times, 50) * 1e3, 3),
        "metrics_body_bytes": body_bytes,
        "ingest_s_obs_off": round(min(ing[False]), 4),
        "ingest_s_obs_on": round(min(ing[True]), 4),
        "ingest_overhead": round(
            min(ing[True]) / max(min(ing[False]), 1e-9), 4),
        "viz_p50_ms_obs_off": round(
            _percentile(times[False], 50) * 1e3, 2),
        "viz_p50_ms_obs_on": round(
            _percentile(times[True], 50) * 1e3, 2),
        "viz_overhead": round(
            _percentile(times[True], 50)
            / max(_percentile(times[False], 50), 1e-9), 4),
        "profiler_counters_on": profiler_counters,
    }
    out["criterion_pass"] = bool(out["ingest_overhead"] <= 1.05
                                 and out["viz_overhead"] <= 1.05)
    return out


def bench_viz(repeats: int, n_hosts: int = 8, per_host: int = 5,
              span_s: int = 172_800) -> dict:
    """Pixel-aware serve-path downsampling config: a config2-style
    wildcard group-by dashboard query over a DENSE window (48h @ 1s
    per series — the response class where serialization dominates the
    warm p50), answered at full resolution and with
    ``downsample=1500px`` (M4). Criteria: response bytes reduced
    >= 20x and e2e p50 (engine + serialize) reduced >= 2x, with
    identical per-pixel min/max/first/last guaranteed by the oracle
    battery (tests/test_visual_downsample.py). Also records the SSE
    frame-size delta for a live continuous query carrying a pixel
    budget."""
    import json as _json
    from opentsdb_tpu.query.model import TSQuery
    tsdb = _mk_tsdb()
    serializer = _serializer()
    rng = np.random.default_rng(29)
    mid = tsdb.uids.metrics.get_or_create_id("sys.viz")
    kid_h = tsdb.uids.tag_names.get_or_create_id("host")
    kid_t = tsdb.uids.tag_names.get_or_create_id("task")
    ts_grid = BASE_MS + np.arange(span_s, dtype=np.int64) * 1000
    n_series = n_hosts * per_host
    t0 = time.perf_counter()
    mask = np.ones((per_host, span_s), dtype=bool)
    for h in range(n_hosts):
        hv = tsdb.uids.tag_values.get_or_create_id(f"h{h:04d}")
        sids = np.asarray([
            tsdb.store.get_or_create_series(
                mid, [(kid_h, hv),
                      (kid_t, tsdb.uids.tag_values.get_or_create_id(
                          f"t{j}"))])
            for j in range(per_host)], dtype=np.int64)
        tsdb.store.append_grid(
            sids, ts_grid, rng.normal(100, 10, (per_host, span_s)),
            mask)
    ingest_s = time.perf_counter() - t0
    end_ms = BASE_MS + span_s * 1000
    base_q = {"start": BASE_MS, "end": end_ms,
              "queries": [{"metric": "sys.viz", "aggregator": "sum",
                           "downsample": "1s-avg",
                           "filters": [{"type": "wildcard",
                                        "tagk": "host", "filter": "*",
                                        "groupBy": True}]}]}
    px_q = _json.loads(_json.dumps(base_q))
    px_q["pixels"] = 1500

    tsdb.config.override_config("tsd.query.cache.enable", "false")

    def measure(qobj):
        tsq = TSQuery.from_json(qobj).validate()
        results = tsdb.execute_query(tsq)          # warm compile
        serializer.format_query(tsq, results)
        tot, ex, ser = [], [], []
        body = b""
        for _ in range(max(repeats, 3)):
            t0 = time.perf_counter()
            tsq = TSQuery.from_json(qobj).validate()
            results = tsdb.execute_query(tsq)
            t1 = time.perf_counter()
            body = serializer.format_query(tsq, results)
            t2 = time.perf_counter()
            tot.append(t2 - t0)
            ex.append(t1 - t0)
            ser.append(t2 - t1)
        dps = sum(r.num_dps for r in results)
        return {"p50_ms": _percentile(tot, 50) * 1e3,
                "exec_p50_ms": _percentile(ex, 50) * 1e3,
                "serialize_p50_ms": _percentile(ser, 50) * 1e3,
                "resp_bytes": len(body), "dps": dps}

    full = measure(base_q)
    px = measure(px_q)
    bytes_ratio = full["resp_bytes"] / max(px["resp_bytes"], 1)
    p50_ratio = full["p50_ms"] / max(px["p50_ms"], 1e-3)

    # SSE frame-size delta: the same live standing query registered
    # with and without a pixel budget (40min @ 1s-avg windows)
    tsdb.config.override_config(
        "tsd.streaming.publish_min_interval_ms", "1000000000")
    reg = tsdb.streaming
    live_start = end_ms - 2400 * 1000
    cq_body = {"start": live_start, "end": end_ms,
               "queries": [{"metric": "sys.viz", "aggregator": "sum",
                            "downsample": "1s-avg",
                            "filters": [{"type": "wildcard",
                                         "tagk": "host",
                                         "filter": "*",
                                         "groupBy": True}]}]}
    px_body = _json.loads(_json.dumps(cq_body))
    px_body["queries"][0]["pixels"] = 150
    cq_f = reg.register(dict(cq_body, id="vizfull"), now_ms=end_ms)
    cq_p = reg.register(dict(px_body, id="vizpx"), now_ms=end_ms)
    sub_f = reg.subscribe(cq_f)
    sub_p = reg.subscribe(cq_p)
    snap_f = sub_f.queue.get(timeout=30)
    snap_p = sub_p.queue.get(timeout=30)

    out = {"config": "viz", "series": n_series, "groups": n_hosts,
           "points": n_series * span_s,
           "ingest_mpps": round(n_series * span_s / ingest_s / 1e6, 1),
           "pixels": 1500,
           "resp_bytes_full": full["resp_bytes"],
           "resp_bytes_px": px["resp_bytes"],
           "bytes_ratio": round(bytes_ratio, 1),
           "dps_full": full["dps"], "dps_px": px["dps"],
           "p50_full_ms": round(full["p50_ms"], 1),
           "p50_px_ms": round(px["p50_ms"], 1),
           "p50_ratio": round(p50_ratio, 2),
           "exec_p50_full_ms": round(full["exec_p50_ms"], 1),
           "exec_p50_px_ms": round(px["exec_p50_ms"], 1),
           "serialize_p50_full_ms": round(full["serialize_p50_ms"], 1),
           "serialize_p50_px_ms": round(px["serialize_p50_ms"], 1),
           "sse_snapshot_bytes_full": len(snap_f),
           "sse_snapshot_bytes_px": len(snap_p),
           "sse_frame_ratio": round(len(snap_f)
                                    / max(len(snap_p), 1), 1),
           "criterion_pass": bool(bytes_ratio >= 20.0
                                  and p50_ratio >= 2.0)}
    return out


def bench_cluster(repeats: int, n_hosts: int = 120,
                  span_s: int = 600) -> dict:
    """Sharded cluster tier config: 3 shard TSDs on real sockets
    behind a consistent-hash router, vs a single-node TSD holding the
    same points. Runs the whole measurement TWICE — once over the
    binary columnar wire (the default transport) and once pinned to
    per-request JSON HTTP (``tsd.cluster.wire.enable=false``) — so the
    record prices the transport change itself, then reports the wire
    run as primary with the JSON run alongside."""
    js = _bench_cluster_once(repeats, n_hosts, span_s, wire=False)
    wired = _bench_cluster_once(repeats, n_hosts, span_s, wire=True)
    out = dict(wired)
    out["json_transport"] = {k: js[k] for k in (
        "router_ingest_kpps", "read_p50_cluster_ms",
        "scatter_gather_overhead", "read_p50_degraded_ms")}
    out["wire_vs_json_ingest_speedup"] = round(
        wired["router_ingest_kpps"]
        / max(js["router_ingest_kpps"], 1e-3), 2)
    out["wire_vs_json_read_speedup"] = round(
        js["read_p50_cluster_ms"]
        / max(wired["read_p50_cluster_ms"], 1e-3), 2)
    out["router_ingest_vs_single"] = round(
        wired["router_ingest_kpps"]
        / max(wired["single_ingest_kpps"], 1e-3), 2)
    return out


def _bench_cluster_once(repeats: int, n_hosts: int, span_s: int,
                        wire: bool) -> dict:
    """One full cluster-vs-single measurement over one transport
    (the chaos battery in tests/test_cluster.py proves the values;
    this config prices the transport)."""
    import asyncio
    import json as _json
    import threading

    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter
    from opentsdb_tpu.tsd.server import TSDServer

    peer_cfg = {"tsd.core.auto_create_metrics": "true",
                "tsd.tpu.warmup": "false"}

    class Peer:
        def __init__(self, name):
            self.name = name
            self.tsdb = TSDB(Config(**peer_cfg))
            self.loop = asyncio.new_event_loop()
            self.server = TSDServer(self.tsdb, host="127.0.0.1",
                                    port=0)
            started = threading.Event()

            def run():
                asyncio.set_event_loop(self.loop)
                self.loop.run_until_complete(self.server.start())
                started.set()
                self.loop.run_forever()

            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()
            assert started.wait(30)
            self.port = (self.server._server.sockets[0]
                         .getsockname()[1])

        def _call(self, coro):
            return asyncio.run_coroutine_threadsafe(
                coro, self.loop).result(20)

        def kill(self):
            async def _close():
                srv = self.server._server
                if srv is not None:
                    srv.close()
                    await srv.wait_closed()
                    self.server._server = None
            self._call(_close())

        def stop(self):
            try:
                self._call(self.server.stop())
            except Exception:  # noqa: BLE001
                pass
            self.loop.call_soon_threadsafe(self.loop.stop)

    def req(method, path, body=None, **params):
        return HttpRequest(
            method=method, path=path,
            params={k: [str(v)] for k, v in params.items()},
            body=_json.dumps(body).encode()
            if body is not None else b"")

    peers = [Peer(f"s{i}") for i in range(3)]
    spec = ",".join(f"{p.name}=127.0.0.1:{p.port}" for p in peers)
    router = TSDB(Config(**{
        "tsd.cluster.role": "router", "tsd.cluster.peers": spec,
        "tsd.cluster.wire.enable": "true" if wire else "false",
        "tsd.query.cache.enable": "false",
        "tsd.tpu.warmup": "false"}))
    http = HttpRpcRouter(router)
    router.cluster.start()
    single = TSDB(Config(**{**peer_cfg,
                            "tsd.query.cache.enable": "false"}))
    single_http = HttpRpcRouter(single)

    points = [{"metric": "bench.cluster",
               "timestamp": BASE_S + i,
               "value": (h * 37 + i) % 1000,
               "tags": {"host": f"h{h:03d}"}}
              for h in range(n_hosts) for i in range(span_s)]
    batches = [points[i:i + 4000]
               for i in range(0, len(points), 4000)]

    def ingest(target):
        t0 = time.perf_counter()
        for b in batches:
            resp = target.handle(req("POST", "/api/put", b,
                                     summary="true"))
            assert resp.status == 200
            assert _json.loads(resp.body)["failed"] == 0
        return time.perf_counter() - t0

    router_ingest_s = ingest(http)
    single_ingest_s = ingest(single_http)

    qbody = {"start": BASE_MS - 1000,
             "end": BASE_MS + span_s * 1000,
             "queries": [{"metric": "bench.cluster",
                          "aggregator": "sum",
                          "downsample": "10s-sum",
                          "filters": [{"type": "wildcard",
                                       "tagk": "host", "filter": "*",
                                       "groupBy": True}]}]}

    def read_p50(target, reps):
        target.handle(req("POST", "/api/query", qbody))  # warm
        times = []
        body = b""
        for _ in range(max(reps, 3)):
            t0 = time.perf_counter()
            resp = target.handle(req("POST", "/api/query", qbody))
            times.append(time.perf_counter() - t0)
            assert resp.status == 200
            body = resp.body
        return _percentile(times, 50) * 1e3, body

    cluster_p50, cluster_body = read_p50(http, repeats)
    single_p50, single_body = read_p50(single_http, repeats)

    def rows(body):
        doc = _json.loads(body)
        if doc and isinstance(doc[-1], dict) and "shardsDegraded" \
                in doc[-1]:
            doc = doc[:-1]
        return sorted(((r["tags"].get("host", ""), r["dps"])
                       for r in doc))

    merged_identical = rows(cluster_body) == rows(single_body)

    # degraded reads: one shard killed, answers must stay 200 with
    # the marker — never a 5xx
    peers[1].kill()
    degraded_times, degraded_ok = [], True
    for _ in range(max(repeats, 3)):
        t0 = time.perf_counter()
        resp = http.handle(req("POST", "/api/query", qbody))
        degraded_times.append(time.perf_counter() - t0)
        doc = _json.loads(resp.body)
        degraded_ok &= (resp.status == 200 and bool(doc)
                        and isinstance(doc[-1], dict)
                        and doc[-1].get("shardsDegraded") == ["s1"])
    degraded_p50 = _percentile(degraded_times, 50) * 1e3

    if wire:  # the wire must actually have carried the traffic
        assert any(p.wire_connects > 0
                   for p in router.cluster.peers.values())
    out = {"config": "cluster", "shards": 3,
           "transport": "wire" if wire else "json",
           "series": n_hosts, "points": len(points),
           "router_ingest_kpps":
               round(len(points) / router_ingest_s / 1e3, 1),
           "single_ingest_kpps":
               round(len(points) / single_ingest_s / 1e3, 1),
           "read_p50_cluster_ms": round(cluster_p50, 1),
           "read_p50_single_ms": round(single_p50, 1),
           "scatter_gather_overhead":
               round(cluster_p50 / max(single_p50, 1e-3), 2),
           "read_p50_degraded_ms": round(degraded_p50, 1),
           "merged_identical_to_single_node": merged_identical,
           "degraded_always_200_with_marker": degraded_ok,
           "criterion_pass": bool(merged_identical and degraded_ok)}
    router.shutdown()
    single.shutdown()
    for p in peers:
        p.stop()
    return out


def bench_cluster_rf(repeats: int, n_hosts: int = 60,
                     span_s: int = 300) -> dict:
    """Replicated cluster config (``tsd.cluster.rf = 2``): two
    3-shard clusters ingest the same points at RF=1 and RF=2
    (interleaved batches — host noise on a shared box swings
    single-config timings far more than the effect under test), then
    reads interleave healthy passes, then one RF=2 replica dies and
    the read-fallback p50 is measured (answers must stay COMPLETE
    marker-less 200s). Finally the RF=1 cluster resizes online to 4
    shards and the cutover-window read overhead is recorded.
    Criteria: RF=2 write amplification ~2x (1.8-2.2), every
    one-dead-replica read complete + marker-less, every
    reshard-window read complete."""
    import asyncio
    import json as _json
    import threading

    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter
    from opentsdb_tpu.tsd.server import TSDServer

    peer_cfg = {"tsd.core.auto_create_metrics": "true",
                "tsd.tpu.warmup": "false"}

    class Peer:
        def __init__(self, name):
            self.name = name
            self.tsdb = TSDB(Config(**peer_cfg))
            self.loop = asyncio.new_event_loop()
            self.server = TSDServer(self.tsdb, host="127.0.0.1",
                                    port=0)
            started = threading.Event()

            def run():
                asyncio.set_event_loop(self.loop)
                self.loop.run_until_complete(self.server.start())
                started.set()
                self.loop.run_forever()

            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()
            assert started.wait(30)
            self.port = (self.server._server.sockets[0]
                         .getsockname()[1])

        def _call(self, coro):
            return asyncio.run_coroutine_threadsafe(
                coro, self.loop).result(20)

        def kill(self):
            async def _close():
                srv = self.server._server
                if srv is not None:
                    srv.close()
                    await srv.wait_closed()
                    self.server._server = None
            self._call(_close())

        def stop(self):
            try:
                self._call(self.server.stop())
            except Exception:  # noqa: BLE001
                pass
            self.loop.call_soon_threadsafe(self.loop.stop)

    def req(method, path, body=None, **params):
        return HttpRequest(
            method=method, path=path,
            params={k: [str(v)] for k, v in params.items()},
            body=_json.dumps(body).encode()
            if body is not None else b"")

    def mk_router(peers, rf):
        spec = ",".join(f"{p.name}=127.0.0.1:{p.port}"
                        for p in peers)
        t = TSDB(Config(**{
            "tsd.cluster.role": "router",
            "tsd.cluster.peers": spec,
            "tsd.cluster.rf": str(rf),
            "tsd.cluster.breaker.reset_timeout_ms": "300",
            "tsd.cluster.reshard.interval_ms": "3600000",
            "tsd.query.cache.enable": "false",
            "tsd.tpu.warmup": "false"}))
        t.cluster.start()
        return t, HttpRpcRouter(t)

    fleets = {1: [Peer(f"a{i}") for i in range(3)],
              2: [Peer(f"b{i}") for i in range(3)]}
    routers = {rf: mk_router(peers, rf)
               for rf, peers in fleets.items()}

    points = [{"metric": "bench.rf",
               "timestamp": BASE_S + i,
               "value": (h * 37 + i) % 1000,
               "tags": {"host": f"h{h:03d}"}}
              for h in range(n_hosts) for i in range(span_s)]
    batches = [points[i:i + 4000]
               for i in range(0, len(points), 4000)]

    ingest_s = {1: 0.0, 2: 0.0}
    for b in batches:  # interleaved per batch
        for rf in (1, 2):
            t0 = time.perf_counter()
            resp = routers[rf][1].handle(
                req("POST", "/api/put", b, summary="true"))
            ingest_s[rf] += time.perf_counter() - t0
            assert resp.status == 200
            assert _json.loads(resp.body)["failed"] == 0

    def delivered(rf):
        return sum(p.forwarded_points + p.spooled_points
                   for p in routers[rf][0].cluster.peers.values())

    amplification = round(delivered(2) / max(delivered(1), 1), 2)

    qbody = {"start": BASE_MS - 1000,
             "end": BASE_MS + span_s * 1000,
             "queries": [{"metric": "bench.rf",
                          "aggregator": "sum",
                          "downsample": "10s-sum",
                          "filters": [{"type": "wildcard",
                                       "tagk": "host", "filter": "*",
                                       "groupBy": True}]}]}

    def read_pass(rf):
        t0 = time.perf_counter()
        resp = routers[rf][1].handle(req("POST", "/api/query",
                                         qbody))
        dt = time.perf_counter() - t0
        assert resp.status == 200
        doc = _json.loads(resp.body)
        degraded = doc and isinstance(doc[-1], dict) and \
            "shardsDegraded" in doc[-1]
        return dt, degraded

    for rf in (1, 2):
        read_pass(rf)  # warm
    healthy = {1: [], 2: []}
    for _ in range(max(repeats, 5)):
        for rf in (1, 2):
            dt, degraded = read_pass(rf)
            assert not degraded
            healthy[rf].append(dt)

    # one RF=2 replica dies: reads must stay complete + marker-less
    fleets[2][1].kill()
    fallback_times, fallback_ok = [], True
    for _ in range(max(repeats, 5)):
        dt, degraded = read_pass(2)
        fallback_times.append(dt)
        fallback_ok &= not degraded
    fallbacks = routers[2][0].cluster.read_fallbacks

    # online reshard of the RF=1 cluster: 3 -> 4 shards
    joiner = Peer("a3")
    rt1, http1 = routers[1]
    resp = http1.handle(req(
        "POST", "/api/cluster/reshard",
        {"peers": rt1.config.get_string("tsd.cluster.peers", "")
         + f",a3=127.0.0.1:{joiner.port}"}))
    assert resp.status == 200, resp.body
    window_times, window_ok = [], True
    for _ in range(max(repeats, 5)):
        dt, degraded = read_pass(1)
        window_times.append(dt)
        window_ok &= not degraded
    while rt1.cluster.resharding:
        info = rt1.cluster.backfill_step()
        assert info.get("phase") != "blocked", info
    post_times = []
    for _ in range(max(repeats, 5)):
        dt, degraded = read_pass(1)
        assert not degraded
        post_times.append(dt)

    h1 = _percentile(healthy[1], 50) * 1e3
    h2 = _percentile(healthy[2], 50) * 1e3
    fb = _percentile(fallback_times, 50) * 1e3
    win = _percentile(window_times, 50) * 1e3
    post = _percentile(post_times, 50) * 1e3
    out = {"config": "cluster_rf", "shards": 3, "rf": 2,
           "series": n_hosts, "points": len(points),
           "write_amplification_rf2": amplification,
           "ingest_kpps_rf1":
               round(len(points) / ingest_s[1] / 1e3, 1),
           "ingest_kpps_rf2":
               round(len(points) / ingest_s[2] / 1e3, 1),
           "read_p50_rf1_ms": round(h1, 1),
           "read_p50_rf2_ms": round(h2, 1),
           "read_p50_rf2_one_dead_ms": round(fb, 1),
           "read_fallbacks": fallbacks,
           "one_dead_reads_complete_markerless": fallback_ok,
           "reshard_window_read_p50_ms": round(win, 1),
           "reshard_window_overhead":
               round(win / max(h1, 1e-3), 2),
           "post_reshard_read_p50_ms": round(post, 1),
           "reshard_window_reads_complete": window_ok,
           "criterion_pass": bool(
               1.8 <= amplification <= 2.2 and fallback_ok
               and window_ok)}
    for rf in (1, 2):
        routers[rf][0].shutdown()
    for peers in fleets.values():
        for p in peers:
            p.stop()
    joiner.stop()
    return out


def bench_multirouter(repeats: int, n_hosts: int = 60,
                      span_s: int = 300) -> dict:
    """Multi-router front door (ISSUE 16): TWO routers on real
    sockets over a shared 3-shard set, exchanging cache-invalidation
    deltas on the gossip bus (cluster/gossip.py). Prices what the
    single-router cluster config cannot: the gossip push round-trip,
    the write-on-A-coherent-read-on-B lag (THE multi-router number),
    the cached-read hit path with gossip healthy, and the
    conservative cache-BYPASSED read served while the sibling is
    unreachable (the degraded mode that replaces stale serves).
    tests/test_multirouter.py proves the values; this config prices
    the transport."""
    import asyncio
    import http.client
    import json as _json
    import socket
    import threading

    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.server import TSDServer

    def free_port():
        with socket.socket() as s:
            s.bind(("127.0.0.1", 0))
            return s.getsockname()[1]

    class Node:
        def __init__(self, cfg, port=0):
            self.tsdb = TSDB(Config(**cfg))
            self.loop = asyncio.new_event_loop()
            self.server = TSDServer(self.tsdb, host="127.0.0.1",
                                    port=port)
            started = threading.Event()

            def run():
                asyncio.set_event_loop(self.loop)
                self.loop.run_until_complete(self.server.start())
                started.set()
                self.loop.run_forever()

            self._thread = threading.Thread(target=run, daemon=True)
            self._thread.start()
            assert started.wait(30)
            self.port = (self.server._server.sockets[0]
                         .getsockname()[1])

        def _call(self, coro):
            return asyncio.run_coroutine_threadsafe(
                coro, self.loop).result(20)

        def kill(self):
            async def _close():
                srv = self.server._server
                if srv is not None:
                    srv.close()
                    await srv.wait_closed()
                    self.server._server = None
            self._call(_close())

        def stop(self):
            try:
                self._call(self.server.stop())
            except Exception:  # noqa: BLE001
                pass
            self.loop.call_soon_threadsafe(self.loop.stop)

    def request(port, method, path, body=None):
        conn = http.client.HTTPConnection("127.0.0.1", port,
                                          timeout=30)
        try:
            data = (_json.dumps(body).encode()
                    if body is not None else None)
            conn.request(method, path, body=data)
            resp = conn.getresponse()
            return resp.status, resp.read()
        finally:
            conn.close()

    peer_cfg = {"tsd.core.auto_create_metrics": "true",
                "tsd.tpu.warmup": "false"}
    shards = [Node(peer_cfg) for _ in range(3)]
    spec = ",".join(f"s{i}=127.0.0.1:{p.port}"
                    for i, p in enumerate(shards))
    ports = [free_port(), free_port()]
    routers = [Node({
        "tsd.cluster.role": "router",
        "tsd.cluster.peers": spec,
        "tsd.cluster.routers": f"r{1 - i}=127.0.0.1:{ports[1 - i]}",
        "tsd.cluster.gossip.interval_ms": "50",
        "tsd.cluster.gossip.stale_ms": "2000",
        "tsd.tpu.warmup": "false"}, port=ports[i])
        for i in (0, 1)]

    points = [{"metric": "bench.mr",
               "timestamp": BASE_S + i,
               "value": (h * 37 + i) % 1000,
               "tags": {"host": f"h{h:03d}"}}
              for h in range(n_hosts) for i in range(span_s)]
    batches = [points[i:i + 4000]
               for i in range(0, len(points), 4000)]

    # LB-style alternating ingest over both front doors, then the
    # same batches through ONE door (idempotent rewrite): the ratio
    # prices what the second router costs/buys on the write path
    t0 = time.perf_counter()
    for k, b in enumerate(batches):
        st, body = request(routers[k % 2].port,
                           "POST", "/api/put?summary=true", b)
        assert st == 200 and _json.loads(body)["failed"] == 0
    lb_ingest_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for b in batches:
        st, body = request(routers[0].port,
                           "POST", "/api/put?summary=true", b)
        assert st == 200 and _json.loads(body)["failed"] == 0
    one_ingest_s = time.perf_counter() - t0

    qbody = {"start": BASE_MS - 1000,
             "end": BASE_MS + span_s * 1000,
             "queries": [{"metric": "bench.mr",
                          "aggregator": "sum",
                          "downsample": "10s-sum",
                          "filters": [{"type": "wildcard",
                                       "tagk": "host", "filter": "*",
                                       "groupBy": True}]}]}

    def read_p50(port, reps):
        request(port, "POST", "/api/query", qbody)  # warm + cache
        times, body = [], b""
        for _ in range(max(reps, 3)):
            t1 = time.perf_counter()
            st, body = request(port, "POST", "/api/query", qbody)
            times.append(time.perf_counter() - t1)
            assert st == 200
        return _percentile(times, 50) * 1e3, body

    r0_p50, r0_body = read_p50(routers[0].port, repeats)
    r1_p50, r1_body = read_p50(routers[1].port, repeats)
    merged_identical = r0_body == r1_body

    # gossip push round-trip (one delta round to the sibling)
    bus0 = routers[0].tsdb.cluster.gossip
    push_times = []
    for _ in range(max(repeats, 5)):
        t1 = time.perf_counter()
        assert bus0.push_once() == 1
        push_times.append(time.perf_counter() - t1)
    push_p50 = _percentile(push_times, 50) * 1e3

    # write-on-B / coherent-read-on-A lag: the wall-clock from an
    # acked sibling write to the first r0 answer that contains it
    # (wake-on-write + one gossip push; polls are 1 ms)
    probe_q = {"start": BASE_MS - 1000,
               "end": BASE_MS + (span_s + 100) * 1000,
               "queries": [{"metric": "bench.mr.probe",
                            "aggregator": "sum"}]}
    lag_times, coherent = [], True
    for k in range(max(repeats, 5)):
        dp = [{"metric": "bench.mr.probe",
               "timestamp": BASE_S + span_s + k,
               "value": k + 1, "tags": {"host": "lb"}}]
        st, body = request(routers[1].port,
                           "POST", "/api/put?summary=true", dp)
        assert st == 200 and _json.loads(body)["failed"] == 0
        t1 = time.perf_counter()
        deadline = t1 + 10
        seen = False
        while time.perf_counter() < deadline:
            st, body = request(routers[0].port, "POST",
                               "/api/query", probe_q)
            if st == 200 and f'"{BASE_S + span_s + k}"' \
                    in body.decode():
                seen = True
                break
            time.sleep(0.001)
        coherent &= seen
        lag_times.append(time.perf_counter() - t1)
    lag_p50 = _percentile(lag_times, 50) * 1e3

    # sibling gone: the router degrades to cache-BYPASSED reads —
    # conservative exactness, never stale, never a 5xx
    routers[1].kill()
    deadline = time.monotonic() + 10
    while not bus0.degraded() and time.monotonic() < deadline:
        time.sleep(0.05)
    degraded_verdict = bus0.degraded()
    bypass_before = bus0.cache_bypasses
    degraded_times, degraded_ok = [], True
    for _ in range(max(repeats, 3)):
        t1 = time.perf_counter()
        st, body = request(routers[0].port, "POST", "/api/query",
                           qbody)
        degraded_times.append(time.perf_counter() - t1)
        degraded_ok &= (st == 200 and body == r0_body)
    degraded_p50 = _percentile(degraded_times, 50) * 1e3
    bypassed = bus0.cache_bypasses > bypass_before

    out = {"config": "multirouter", "routers": 2, "shards": 3,
           "series": n_hosts, "points": len(points),
           "lb_ingest_kpps":
               round(len(points) / lb_ingest_s / 1e3, 1),
           "single_door_ingest_kpps":
               round(len(points) / one_ingest_s / 1e3, 1),
           "read_p50_r0_ms": round(r0_p50, 1),
           "read_p50_r1_ms": round(r1_p50, 1),
           "gossip_push_p50_ms": round(push_p50, 2),
           "sibling_write_coherence_lag_p50_ms": round(lag_p50, 1),
           "read_p50_sibling_dead_bypassed_ms":
               round(degraded_p50, 1),
           "merged_identical_across_routers": merged_identical,
           "coherent_after_sibling_write": coherent,
           "degraded_reads_exact_200": degraded_ok,
           "degraded_verdict_raised": degraded_verdict,
           "cache_bypassed_while_degraded": bypassed,
           "criterion_pass": bool(
               merged_identical and coherent and degraded_ok
               and degraded_verdict and bypassed)}
    for r in routers:
        r.stop()
    for p in shards:
        p.stop()
    return out


def bench_control(repeats: int, n_series: int = 48,
                  span_s: int = 7200) -> dict:
    """Control-plane config. (1) Adaptive materialization: a hot
    decomposable dashboard shape is mined from the query-shape log
    and auto-registered as a standing continuous query; the repeat
    pull (served from the standing fold) must be >= 5x faster than
    the cold first-miss execution, with the result cache OFF so every
    non-served repeat pays the full execution. (2) Noisy-tenant
    isolation: a gold-weighted interactive tenant vs a bronze batch
    flood of closed-loop clients that honor Retry-After on a shed
    and pace requests with think time. The victim's p99 must stay
    within 1.5x of its solo baseline while the flood absorbs every
    tenant shed. Contended and solo passes are interleaved and
    compared best-of (the bench_obs idiom) to fight host noise."""
    import random
    import shutil
    import tempfile
    import threading

    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.tsd.http_api import HttpRequest, HttpRpcRouter

    # -- part 1: miner-materialized repeat speedup ---------------------
    d = tempfile.mkdtemp(prefix="ctlbench-")
    now_s = int(time.time())
    t = TSDB(Config(**{
        "tsd.core.auto_create_metrics": "true",
        "tsd.storage.backend": "memory",
        "tsd.storage.data_dir": d,
        "tsd.storage.wal.enable": "false",
        "tsd.query.cache.enable": "false",
        "tsd.trace.enable": "true",
        "tsd.trace.sample": "1",
        "tsd.control.enable": "true",
        "tsd.control.materialize.min_score": "0",
        "tsd.tpu.warmup": "false",
    }))
    rng = np.random.default_rng(37)
    ts = np.arange(now_s - span_s, now_s, 1, dtype=np.int64)
    for i in range(n_series):
        t.add_points("ctl.dash", ts, rng.normal(100, 10, span_s),
                     {"host": f"h{i:03d}"})
    router = HttpRpcRouter(t)
    params = {"start": ["2h-ago"], "m": ["sum:1m-sum:ctl.dash"]}

    def pull() -> float:
        t0 = time.perf_counter()
        r = router.handle(HttpRequest("GET", "/api/query", params))
        assert r.status == 200, r.body
        return time.perf_counter() - t0

    n = max(repeats, 7)
    pull()                                   # warm compiles
    cold = [pull() for _ in range(n)]        # every miss re-executes
    rep = t.control.tick()
    materialized = rep.get("materialize", {}).get("registered", 0)
    hits0 = t.streaming.serve_hits
    warm = [pull() for _ in range(n)]
    serve_hits = t.streaming.serve_hits - hits0
    cold_p50 = _percentile(cold, 50) * 1e3
    warm_p50 = _percentile(warm, 50) * 1e3
    t.shutdown()
    shutil.rmtree(d, ignore_errors=True)

    # -- part 2: noisy-tenant isolation ---------------------------------
    # In-process: the bench replays the server's exact admission
    # sequence (try_admit -> started -> handle -> finished) per
    # request. End-to-end socket behaviour (503 + Retry-After, header
    # extraction) is covered by tests/test_control.py; over a
    # loopback socket this measurement would be dominated by the
    # single-threaded accept-loop churn of per-request connections,
    # which the governor does not control.
    max_inflight = 4
    tsdb = TSDB(Config(**{
        "tsd.core.auto_create_metrics": "true",
        "tsd.storage.backend": "memory",
        "tsd.query.cache.enable": "false",
        "tsd.control.enable": "true",
        "tsd.control.qos.enable": "true",
        "tsd.control.qos.weights": "victim:4,noisy:1",
        "tsd.query.admission.max_inflight": str(max_inflight),
        "tsd.query.admission.retry_after_s": "1",
        "tsd.tpu.warmup": "false",
    }))
    assert tsdb.control is not None
    governor = tsdb.control.qos
    nts = np.arange(now_s - 7200, now_s, 1, dtype=np.int64)
    for i in range(48):
        tsdb.add_points("nt.dense", nts,
                        rng.normal(100, 10, len(nts)),
                        {"host": f"h{i:02d}"})
    lts = np.arange(now_s - 120, now_s, 1, dtype=np.int64)
    tsdb.add_points("nt.light", lts,
                    rng.normal(100, 10, len(lts)), {"host": "h0"})
    nt_router = HttpRpcRouter(tsdb)
    victim_q = {"start": ["2h-ago"], "m": ["sum:1m-sum:nt.dense"]}
    noisy_q = {"start": ["2m-ago"], "m": ["sum:1m-sum:nt.light"]}

    def admit_and_run(tenant: str, q: dict) -> bool:
        shed = governor.try_admit(tenant, max_inflight)
        if shed is not None:
            return False
        governor.started(tenant)
        try:
            r = nt_router.handle(HttpRequest("GET", "/api/query", q))
            assert r.status == 200, r.body
        finally:
            governor.finished(tenant)
        return True

    def victim_pass(k: int) -> list[float]:
        times = []
        for _ in range(k):
            t0 = time.perf_counter()
            assert admit_and_run("victim", victim_q)
            times.append(time.perf_counter() - t0)
        return times

    n_victim = max(repeats * 30, 150)
    victim_pass(8)                           # warm compiles
    # 3 interleaved contended/solo cycles so both sides sample the
    # same host-noise epochs; compare best-of (the bench_obs idiom)
    solo_p99s: list[float] = []
    cont_p99s: list[float] = []
    for _ in range(3):
        stop = threading.Event()

        def noisy_flood():
            while not stop.is_set():
                admitted = admit_and_run("noisy", noisy_q)
                # closed-loop client: honor Retry-After on a tenant
                # shed (scaled down so the bench stays short),
                # think-time pacing otherwise; jittered to avoid a
                # synchronized retry herd
                base = 0.02 if admitted else 0.25
                time.sleep(base * (0.7 + 0.6 * random.random()))

        flood = [threading.Thread(target=noisy_flood, daemon=True)
                 for _ in range(4)]
        for th in flood:
            th.start()
        time.sleep(0.25)                     # flood reaches steady state
        try:
            cont_p99s.append(_percentile(victim_pass(n_victim), 99))
        finally:
            stop.set()
            for th in flood:
                th.join(10)
        solo_p99s.append(_percentile(victim_pass(n_victim), 99))
    qdoc = governor.describe()
    noisy_shed = qdoc["tenants"].get("noisy", {}).get("shed", 0)
    victim_shed = qdoc["tenants"].get("victim", {}).get("shed", 0)
    tsdb.shutdown()

    solo_p99 = min(solo_p99s) * 1e3
    cont_p99 = min(cont_p99s) * 1e3
    out = {
        "config": "control",
        "series": n_series, "span_s": span_s,
        "materialized": materialized,
        "repeat_serve_hits": serve_hits,
        "cold_miss_p50_ms": round(cold_p50, 2),
        "materialized_repeat_p50_ms": round(warm_p50, 2),
        "repeat_speedup": round(cold_p50 / max(warm_p50, 1e-6), 1),
        "victim_solo_p99_ms": round(solo_p99, 1),
        "victim_contended_p99_ms": round(cont_p99, 1),
        "victim_p99_ratio": round(cont_p99 / max(solo_p99, 1e-6), 2),
        "noisy_sheds": int(noisy_shed),
        "victim_sheds": int(victim_shed),
    }
    out["criterion_pass"] = bool(
        materialized >= 1 and serve_hits >= 1
        and out["repeat_speedup"] >= 5.0
        and out["victim_p99_ratio"] <= 1.5
        and noisy_shed > 0 and victim_shed == 0)
    return out


def bench_eventtime(repeats: int, n_users: int = 1_000_000,
                    n_sample: int = 20_000) -> dict:
    """Event-time layer at user scale: one session CQ keyed by a
    ``user`` tag with 1M distinct values (1M concurrent sessions in
    ONE columnar partial). (1) ingest tax — per-point write+fold
    throughput with the session CQ standing vs a zero-CQ control
    over the same 1M-series store, criterion <= 1.5x; (2) gap-close
    throughput — the completeness marker's watermark-driven
    open/closed sweep over all 1M session rows (one vectorized
    pass); (3) late-refold cost — folding an in-lateness batch into
    already-published buckets vs an equal at-the-front batch.

    Folds are timed deterministically on this thread (workers off,
    drain via the registry's own ``_drain_group``, no publish): the
    tap+fold pair IS the write-path cost a standing CQ adds — SSE
    publish is subscriber-driven and benched in ``live``."""
    from opentsdb_tpu import TSDB, Config
    from opentsdb_tpu.streaming.eventtime.watermark import (
        completeness_marker)

    end_ms = BASE_MS + 1800 * 1000

    def _mk():
        return TSDB(Config(**{
            "tsd.core.auto_create_metrics": "true",
            "tsd.tpu.warmup": "false",
            "tsd.streaming.workers.count": "0",
            "tsd.streaming.buffer_points": str(1 << 30),
            "tsd.streaming.workers.max_pending_points":
                str(1 << 30)}))

    def _drain(t):
        for g in t.streaming._partials:
            t.streaming._drain_group(g)

    def _preingest(t):
        # one point per user, event time swept monotonically across
        # 0..24m so the per-pass watermark commit never declares the
        # bulk late; drained every 100k to bound the pending buffer
        t0 = time.perf_counter()
        for u in range(n_users):
            t.add_point("evt.sess", BASE_S + (u * 1440) // n_users,
                        1.0, {"user": f"u{u:07d}"})
            if (u + 1) % 100_000 == 0:
                _drain(t)
        _drain(t)
        return time.perf_counter() - t0

    # sampled follow-up traffic: 20k distinct already-admitted users
    # (steady-state fold, no admission cost), event times at the
    # 25..30m front edge so nothing is late on first contact
    stride = max(n_users // n_sample, 1)
    sample_users = [f"u{(i * stride) % n_users:07d}"
                    for i in range(n_sample)]
    sample_ts = [BASE_S + 1500 + (i * 280) // n_sample
                 for i in range(n_sample)]

    def _ingest_pass(t) -> float:
        t0 = time.perf_counter()
        for u, ts in zip(sample_users, sample_ts):
            t.add_point("evt.sess", ts, 2.0, {"user": u})
        _drain(t)
        return time.perf_counter() - t0

    # --- zero-CQ control: same 1M-series store, no streaming tap
    t = _mk()
    setup_zero_s = _preingest(t)
    zero_s = min(_ingest_pass(t) for _ in range(max(repeats, 3)))
    t.shutdown()

    # --- session-CQ arm: register FIRST so every pre-ingest point
    # rides the live tap+fold path (1M admissions into user rows)
    t = _mk()
    cq = t.streaming.register(
        {"start": BASE_MS, "end": end_ms, "queries": [
            {"metric": "evt.sess", "aggregator": "none",
             "downsample": "1m-sum"}],
         "window": {"type": "session", "gap": "2m", "by": "user"},
         "watermark": {"allowedLateness": "5m"}},
        now_ms=end_ms)
    setup_cq_s = _preingest(t)
    cq_s = min(_ingest_pass(t) for _ in range(max(repeats, 3)))
    tax = cq_s / max(zero_s, 1e-9)

    part = t.streaming._partials[0]
    assert len(part._sids) == n_users, len(part._sids)

    # --- gap-close throughput: the marker's watermark sweep closes
    # sessions whose last bucket the watermark passed by > gap —
    # one vectorized pass over all 1M rows per pull
    marker = None
    sweep = []
    for _ in range(max(repeats, 5)):
        t0 = time.perf_counter()
        marker = completeness_marker(t.streaming, cq, end_ms)
        sweep.append(time.perf_counter() - t0)
    sweep_p50 = _percentile(sweep, 50)
    assert marker["sessionsClosed"] > n_users // 2, marker
    assert marker["sessionsOpen"] > 0, marker

    # --- late-refold cost: equal batches folded at the front edge
    # vs 4.5m behind the watermark (inside the 5m lateness horizon,
    # landing in already-published buckets)
    def _fold_batch(off_s: int) -> float:
        for i, u in enumerate(sample_users):
            t.add_point("evt.sess", BASE_S + off_s + i % 60, 3.0,
                        {"user": u})
        t0 = time.perf_counter()
        _drain(t)
        return time.perf_counter() - t0

    live_s = min(_fold_batch(1740) for _ in range(max(repeats, 3)))
    refold_before = part.late_refolded
    late_s = min(_fold_batch(1500) for _ in range(max(repeats, 3)))
    late_refolded = part.late_refolded - refold_before
    assert late_refolded > 0, "late batch never hit the refold path"
    t.shutdown()

    return {
        "config": "eventtime",
        "users": n_users,
        "sample_points": n_sample,
        "setup_zero_s": round(setup_zero_s, 1),
        "setup_cq_s": round(setup_cq_s, 1),
        "zero_cq_kpps": round(n_sample / zero_s / 1e3, 1),
        "session_cq_kpps": round(n_sample / cq_s / 1e3, 1),
        "ingest_tax": round(tax, 2),
        "gap_close_p50_ms": round(sweep_p50 * 1e3, 1),
        "gap_close_msessions_per_s": round(
            n_users / max(sweep_p50, 1e-9) / 1e6, 1),
        "sessions_open": marker["sessionsOpen"],
        "sessions_closed": marker["sessionsClosed"],
        "live_fold_us_per_point": round(live_s / n_sample * 1e6, 2),
        "late_refold_us_per_point": round(
            late_s / n_sample * 1e6, 2),
        "late_refold_ratio": round(late_s / max(live_s, 1e-9), 2),
        "late_refolded_points": int(late_refolded),
        "criterion_pass": bool(tax <= 1.5),
    }


def _serializer():
    from opentsdb_tpu.tsd.json_serializer import HttpJsonSerializer
    return HttpJsonSerializer()


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--cpu", action="store_true",
                    help="force CPU (debug; bench runs on TPU)")
    ap.add_argument("--configs", default="1,2,3,4")
    ap.add_argument("--repeats", type=int, default=5)
    ap.add_argument("--series3", type=int, default=1_000_000)
    args = ap.parse_args()
    if args.cpu:
        import os
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        jax.config.update("jax_platforms", "cpu")

    runners = {1: bench_config1, 2: bench_config2,
               3: lambda r: bench_config3(r, args.series3),
               4: bench_config4, 5: bench_config5,
               "wal": bench_wal, "live": bench_live,
               "lifecycle": bench_lifecycle, "cold": bench_cold,
               "sketch": bench_sketch,
               "ingest": bench_ingest, "viz": bench_viz,
               "cluster": bench_cluster,
               "cluster_rf": bench_cluster_rf,
               "multirouter": bench_multirouter,
               "streamv2": bench_streamv2, "obs": bench_obs,
               "obs2": bench_obs2, "control": bench_control,
               "eventtime": bench_eventtime}
    out = []
    for c in ((int(x) if x.isdigit() else x)
              for x in args.configs.split(",")):
        t0 = time.perf_counter()
        res = runners[c](args.repeats)
        res["total_s"] = round(time.perf_counter() - t0, 1)
        out.append(res)
        print(json.dumps(res), flush=True)
    ns = [r for r in out if r.get("config") == 3]
    if ns:
        print(json.dumps({
            "metric": "p50 /api/query e2e latency, north-star config",
            "value": ns[0]["p50_ms"], "unit": "ms",
            "north_star_pass": ns[0]["north_star_pass"]}),
            file=sys.stderr)


if __name__ == "__main__":
    main()
