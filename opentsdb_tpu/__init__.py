"""opentsdb_tpu — a TPU-native time-series database framework.

A from-scratch rebuild of the capabilities of OpenTSDB 2.4 (reference:
neilmrp/opentsdb) designed TPU-first: the per-datapoint Java iterator
pipeline (``src/core/AggregationIterator.java``) is replaced with batched,
jit-compiled segmented reductions over ``[series x timebucket]`` arrays,
sharded over a ``jax.sharding.Mesh`` where the reference used 20-way
salt-bucket HBase scans and stateless TSD scale-out.

Layer map (mirrors SURVEY.md section 1):

- ``core``      storage model: byte codec, UID dictionary, host column store,
                TSDB facade (ref: ``src/core``, ``src/uid``)
- ``ops``       the compute kernels: aggregators, downsampling, rate,
                interpolation, group-by (ref: ``src/core/Aggregators.java``,
                ``Downsampler.java``, ``RateSpan.java``,
                ``AggregationIterator.java``)
- ``query``     query model, tag filters, planner, expressions
                (ref: ``src/core/TsdbQuery.java``, ``src/query``)
- ``parallel``  device-mesh sharding of the pipeline (ref: the salt-scanner
                parallelism of ``src/core/SaltScanner.java``)
- ``rollup``    pre-aggregation tiers (ref: ``src/rollup``)
- ``tsd``       HTTP + telnet network server (ref: ``src/tsd``)
- ``stats``     observability (ref: ``src/stats``)
- ``meta``/``tree``/``search``/``auth``  metadata, hierarchies, lookup, auth
- ``tools``     CLI tools (ref: ``src/tools``)
"""

__version__ = "0.1.0"

from opentsdb_tpu.core.tsdb import TSDB  # noqa: F401
from opentsdb_tpu.utils.config import Config  # noqa: F401
