"""Authentication / authorization (ref: ``src/auth/``).

ABI parity with ``Authentication.java:36`` / ``Authorization`` /
``AuthState`` / ``Permissions.java:25``: a pluggable authenticator
invoked as the first exchange on a connection (telnet ``auth`` command or
HTTP), plus a permission enum gating each RPC. The built-in
:class:`SimpleAuthentication` mirrors the reference's example
``AllowAllAuthenticatingAuthorizer`` unless users are configured.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
from enum import Enum, auto


class Permissions(Enum):
    """The full reference permission set
    (ref: src/auth/Permissions.java:25-27)."""
    TELNET_PUT = auto()
    HTTP_PUT = auto()
    HTTP_QUERY = auto()
    CREATE_TAGK = auto()
    CREATE_TAGV = auto()
    CREATE_METRIC = auto()


ALL_PERMISSIONS = frozenset(Permissions)


class AuthStatus(Enum):
    SUCCESS = auto()
    UNAUTHORIZED = auto()
    FORBIDDEN = auto()
    REDIRECTED = auto()
    ERROR = auto()


class AuthState:
    """(ref: src/auth/AuthState.java)"""

    def __init__(self, user: str, status: AuthStatus,
                 message: str = "", roles: set[str] | None = None,
                 permissions: frozenset | None = None):
        self.user = user
        self.status = status
        self.message = message
        self.roles = roles or set()
        # None = no role config: every authenticated user gets
        # everything (AllowAllAuthenticatingAuthorizer parity)
        self.permissions = (ALL_PERMISSIONS if permissions is None
                            else permissions)
        self.token: bytes | None = None

    def has_permission(self, perm: Permissions) -> bool:
        """(ref: Permissions.java gating HTTP_QUERY/HTTP_PUT/
        TELNET_PUT/CREATE_* per role)"""
        return self.status == AuthStatus.SUCCESS and \
            perm in self.permissions


class SimpleAuthentication:
    """Username/password authenticator with role-based authorization.

    - ``tsd.core.authentication.users`` =
      ``user1:sha256hex[:role1|role2],user2:sha256hex`` — with no
      users configured every auth attempt succeeds
      (AllowAllAuthenticatingAuthorizer parity).
    - ``tsd.core.authentication.roles`` =
      ``reader:http_query,writer:http_put|telnet_put,admin:all`` —
      maps role names to granted :class:`Permissions`; with no roles
      configured every authenticated user holds every permission.
      A user with no roles (while roles ARE configured) holds none.
    """

    def __init__(self, config):
        self._users: dict[str, tuple[str, set[str]]] = {}
        spec = config.get_string("tsd.core.authentication.users", "")
        for entry in filter(None, (e.strip() for e in spec.split(","))):
            parts = entry.split(":")
            user = parts[0]
            digest = parts[1].lower() if len(parts) > 1 else ""
            roles = set(filter(None, parts[2].split("|"))) \
                if len(parts) > 2 else set()
            self._users[user] = (digest, roles)
        self._role_grants: dict[str, frozenset] = {}
        rspec = config.get_string("tsd.core.authentication.roles", "")
        for entry in filter(None, (e.strip()
                                   for e in rspec.split(","))):
            role, _, perms = entry.partition(":")
            granted = set()
            for p in filter(None, perms.split("|")):
                if p.strip().lower() in ("all", "*"):
                    granted |= ALL_PERMISSIONS
                else:
                    try:
                        granted.add(Permissions[p.strip().upper()])
                    except KeyError:
                        valid = ", ".join(
                            x.name.lower() for x in Permissions)
                        raise ValueError(
                            "invalid permission "
                            f"{p.strip()!r} in tsd.core."
                            f"authentication.roles entry "
                            f"{entry!r} (valid: {valid}, 'all')"
                        ) from None
            self._role_grants[role.strip()] = frozenset(granted)

    def _permissions_for(self, roles: set[str]) -> frozenset | None:
        if not self._role_grants:
            return None  # no role config: everything
        granted: set = set()
        for r in roles:
            granted |= self._role_grants.get(r, frozenset())
        return frozenset(granted)

    def authenticate(self, user: str, password: str) -> AuthState:
        if not self._users:
            return AuthState(user or "anonymous", AuthStatus.SUCCESS)
        digest = hashlib.sha256(password.encode()).hexdigest()
        entry = self._users.get(user)
        if entry is not None and hmac.compare_digest(digest, entry[0]):
            state = AuthState(user, AuthStatus.SUCCESS,
                              roles=set(entry[1]),
                              permissions=self._permissions_for(
                                  entry[1]))
            state.token = secrets.token_bytes(16)
            return state
        return AuthState(user, AuthStatus.UNAUTHORIZED,
                         "invalid credentials")

    def authenticate_telnet(self, command: list[str]) -> AuthState:
        """telnet: ``auth <user> <password>``
        (ref: AuthenticationChannelHandler.java:50)."""
        if len(command) < 3:
            return AuthState("", AuthStatus.ERROR,
                             "format: auth <user> <password>")
        return self.authenticate(command[1], command[2])

    def authenticate_http(self, headers: dict[str, str]) -> AuthState:
        """HTTP: Basic authorization header
        (ref: AuthenticationChannelHandler HTTP branch)."""
        import base64
        if not self._users:
            # AllowAllAuthenticatingAuthorizer parity: everything
            # passes, regardless of what headers are attached
            return AuthState("anonymous", AuthStatus.SUCCESS)
        raw = headers.get("authorization", "")
        if not raw:
            return AuthState("", AuthStatus.UNAUTHORIZED,
                             "missing Authorization header")
        scheme, _, payload = raw.partition(" ")
        if scheme.lower() != "basic":
            return AuthState("", AuthStatus.UNAUTHORIZED,
                             f"unsupported auth scheme {scheme!r}")
        try:
            user, _, password = base64.b64decode(payload.strip()) \
                .decode("utf-8").partition(":")
        except Exception:  # noqa: BLE001
            return AuthState("", AuthStatus.ERROR,
                             "malformed Basic credentials")
        return self.authenticate(user, password)
