"""Authentication / authorization (ref: ``src/auth/``).

ABI parity with ``Authentication.java:36`` / ``Authorization`` /
``AuthState`` / ``Permissions.java:25``: a pluggable authenticator
invoked as the first exchange on a connection (telnet ``auth`` command or
HTTP), plus a permission enum gating each RPC. The built-in
:class:`SimpleAuthentication` mirrors the reference's example
``AllowAllAuthenticatingAuthorizer`` unless users are configured.
"""

from __future__ import annotations

import hashlib
import hmac
import secrets
from enum import Enum, auto


class Permissions(Enum):
    """(ref: src/auth/Permissions.java:25)"""
    TELNET_PUT = auto()
    HTTP_PUT = auto()
    HTTP_QUERY = auto()
    CREATE_UID = auto()


class AuthStatus(Enum):
    SUCCESS = auto()
    UNAUTHORIZED = auto()
    FORBIDDEN = auto()
    REDIRECTED = auto()
    ERROR = auto()


class AuthState:
    """(ref: src/auth/AuthState.java)"""

    def __init__(self, user: str, status: AuthStatus,
                 message: str = "", roles: set[str] | None = None):
        self.user = user
        self.status = status
        self.message = message
        self.roles = roles or set()
        self.token: bytes | None = None

    def has_permission(self, perm: Permissions) -> bool:
        return self.status == AuthStatus.SUCCESS


class SimpleAuthentication:
    """Username/password authenticator.

    Users configured as ``tsd.core.authentication.users`` =
    ``user1:sha256hex,user2:sha256hex``; with no users configured every
    auth attempt succeeds (AllowAllAuthenticatingAuthorizer parity).
    """

    def __init__(self, config):
        self._users: dict[str, str] = {}
        spec = config.get_string("tsd.core.authentication.users", "")
        for entry in filter(None, (e.strip() for e in spec.split(","))):
            user, _, digest = entry.partition(":")
            self._users[user] = digest.lower()

    def authenticate(self, user: str, password: str) -> AuthState:
        if not self._users:
            return AuthState(user or "anonymous", AuthStatus.SUCCESS)
        digest = hashlib.sha256(password.encode()).hexdigest()
        expected = self._users.get(user)
        if expected is not None and hmac.compare_digest(digest, expected):
            state = AuthState(user, AuthStatus.SUCCESS)
            state.token = secrets.token_bytes(16)
            return state
        return AuthState(user, AuthStatus.UNAUTHORIZED,
                         "invalid credentials")

    def authenticate_telnet(self, command: list[str]) -> AuthState:
        """telnet: ``auth <user> <password>``
        (ref: AuthenticationChannelHandler.java:50)."""
        if len(command) < 3:
            return AuthState("", AuthStatus.ERROR,
                             "format: auth <user> <password>")
        return self.authenticate(command[1], command[2])

    def authenticate_http(self, headers: dict[str, str]) -> AuthState:
        """HTTP: Basic authorization header
        (ref: AuthenticationChannelHandler HTTP branch)."""
        import base64
        if not self._users:
            # AllowAllAuthenticatingAuthorizer parity: everything
            # passes, regardless of what headers are attached
            return AuthState("anonymous", AuthStatus.SUCCESS)
        raw = headers.get("authorization", "")
        if not raw:
            return AuthState("", AuthStatus.UNAUTHORIZED,
                             "missing Authorization header")
        scheme, _, payload = raw.partition(" ")
        if scheme.lower() != "basic":
            return AuthState("", AuthStatus.UNAUTHORIZED,
                             f"unsupported auth scheme {scheme!r}")
        try:
            user, _, password = base64.b64decode(payload.strip()) \
                .decode("utf-8").partition(":")
        except Exception:  # noqa: BLE001
            return AuthState("", AuthStatus.ERROR,
                             "malformed Basic credentials")
        return self.authenticate(user, password)
