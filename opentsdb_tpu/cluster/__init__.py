"""Fault-tolerant sharded cluster tier.

The reference scales horizontally as many stateless TSDs behind a load
balancer (SURVEY §L4, ``RpcManager``); its storage layer spreads row
keys over 20 salt buckets so scans fan out (``SaltScanner.java:70``).
This package builds the missing serving tier on the same idea: a
**router** mode of the TSDServer owns a consistent-hash series→shard
map (the salt computation lifted from the row key to the network), so

- writes forward as series-grouped columnar batches to the owning
  shard (one client body stays one WAL write + one fsync per shard,
  via the peer's ``/api/put`` → ``TSDB.add_point_groups`` path), and
- queries scatter to every shard and gather per-shard group
  *partials*, which merge exactly because sum/count/min/max decompose
  across shards like the rollup tiers (``avg`` = merged sum / merged
  count).

The headline is the failure semantics (Monarch's partial-result
pushdown, PAPERS.md): a dead, hanging or flapping peer never turns
into a 5xx. Reads get per-peer timeouts, circuit breakers
(:mod:`opentsdb_tpu.utils.faults`, fault site ``cluster.peer``) and
optional tail-latency hedging; a failed shard yields a 200 partial
carrying a ``shardsDegraded`` marker that the result cache refuses to
retain. Writes to an unreachable shard land in a per-peer durable
spool (framed like the WAL) that replays when the peer's breaker
half-opens — an acknowledged point is never lost to a peer outage.

With replication (``tsd.cluster.rf`` = 2/3,
:mod:`opentsdb_tpu.cluster.replica`) the tier survives outright:
writes fan out to every replica owner, reads take one replica per
set and fall back to the next (a single shard death answers a
COMPLETE marker-less 200), anti-entropy re-copies divergence windows
the spool lost, and :mod:`opentsdb_tpu.cluster.reshard` grows or
shrinks the ring online behind a fenced, persisted epoch.
"""

from opentsdb_tpu.cluster.hashring import HashRing, series_shard_key
from opentsdb_tpu.cluster.replica import DirtyTracker
from opentsdb_tpu.cluster.reshard import ReshardState
from opentsdb_tpu.cluster.router import ClusterRouter
from opentsdb_tpu.cluster.spool import PeerSpool

__all__ = ["ClusterRouter", "DirtyTracker", "HashRing", "PeerSpool",
           "ReshardState", "series_shard_key"]
