"""Blocking HTTP peer client for the cluster tier.

One short-lived ``http.client`` connection per request, carrying both
a connect and a read deadline — the router's failure semantics hang on
these timeouts (a hung peer must become a degraded partial, not a
stuck worker thread). Deliberately dependency-free and blocking: every
call runs on the router's dedicated fan-out pool, never on the server
event loop.
"""

from __future__ import annotations

import http.client
import socket


class PeerError(OSError):
    """Transport-level peer failure (connect/read/timeout/5xx): counts
    toward the peer's circuit breaker and degrades the request.
    Subclasses OSError so it rides the same retry ladders as disk
    faults (``utils.faults.call_with_retries`` defaults)."""


class PeerClient:
    """Address + deadlines of one peer TSD."""

    def __init__(self, host: str, port: int,
                 timeout_ms: float = 5000.0):
        self.host = host
        self.port = int(port)
        self.timeout_s = max(float(timeout_ms), 1.0) / 1000.0

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    def request(self, method: str, path: str,
                body: bytes | None = None,
                timeout_s: float | None = None,
                headers: dict[str, str] | None = None
                ) -> tuple[int, bytes]:
        """One request; returns ``(status, body)``. 5xx and every
        transport failure raise :class:`PeerError`; 2xx-4xx return —
        a 400 from a healthy peer is not peer damage. ``headers``
        are extras (e.g. the ``X-TSD-Trace`` propagation header)."""
        conn = http.client.HTTPConnection(
            self.host, self.port,
            timeout=timeout_s if timeout_s is not None
            else self.timeout_s)
        try:
            all_headers = {"Content-Type": "application/json",
                           "Connection": "close"}
            if headers:
                all_headers.update(headers)
            conn.request(method, path, body=body,
                         headers=all_headers)
            resp = conn.getresponse()
            data = resp.read()
            status = resp.status
        except (OSError, http.client.HTTPException, socket.timeout) \
                as exc:
            raise PeerError(
                f"peer {self.address}: {type(exc).__name__}: {exc}"
            ) from exc
        finally:
            try:
                conn.close()
            except Exception:  # noqa: BLE001
                # tsdlint: allow[swallow] teardown of an already-failed
                # or already-answered connection; nothing to report
                pass
        if status >= 500:
            raise PeerError(
                f"peer {self.address} answered {status}: "
                f"{data[:200]!r}")
        return status, data


def parse_peer_spec(spec: str) -> list[tuple[str, str, int]]:
    """Parse ``tsd.cluster.peers``: comma-separated
    ``[name=]host:port`` entries; the name defaults to ``host:port``.
    Returns ``[(name, host, port), ...]`` in config order."""
    out: list[tuple[str, str, int]] = []
    seen: set[str] = set()
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        name, _, addr = entry.rpartition("=")
        if not name:
            name = addr
        host, _, port_s = addr.rpartition(":")
        if not host or not port_s.isdigit():
            raise ValueError(
                f"bad tsd.cluster.peers entry {entry!r} "
                "(want [name=]host:port)")
        if name in seen:
            raise ValueError(
                f"duplicate cluster peer name {name!r}")
        seen.add(name)
        out.append((name, host, int(port_s)))
    return out


