"""Cross-shard federated continuous queries (router mode).

A single-node CQ folds every write of its metric into one shared
partial (:mod:`opentsdb_tpu.streaming`). Under the router each shard
sees only ITS series, so a standing query must become N standing
queries — one per shard, each folding the shard's local writes into
its own shared partial — with the router holding the merge view:

- **register** scatters the registration (with an explicit id) to
  every ring shard; any shard's 400 rolls the others back and
  surfaces verbatim (the shard registry stays the authority on what
  can stand). RF must be 1 — at RF > 1 every replica folds every
  write, so a cross-shard sum would count each point rf times.
- **pull** (``GET .../result``) fans out, strips each leg's trailing
  completeness marker, and folds the per-shard rows with the SAME
  dict-fold combine machinery the batch scatter uses
  (:mod:`opentsdb_tpu.cluster.merge`) — series never span shards, so
  ``none`` concatenates and decomposable aggregators combine, and an
  integer-valued workload merges bit-identically to the single-node
  oracle. Dead shards degrade into the merged marker
  (``shardsDegraded`` + ``complete: false``), never a 5xx.
- **push** (``GET .../stream``) duck-types the SSE contract
  (:func:`opentsdb_tpu.streaming.sse.sse_stream` pumps THIS registry):
  each pump drains every shard's dirty windows through the
  ``GET .../deltas`` surface and publishes ONE merged ``windows``
  frame. A router-registered CQ has no shard-local subscribers, so
  the per-shard dirty sets accumulate exclusively for this drain.
- **session windows** federate with a shard-affinity contract: one
  session key value's timeline is exact when every series carrying
  that value lands on one shard — true by construction for the
  canonical user-scale shape, where the session tag is the series'
  only tag (one ``user`` = one series = one ring position). A key
  whose member series span shards gets per-shard session timelines
  (each shard gap-closes over its own points); the merge groups rows
  by the session tag so such splits surface as per-shard rows of one
  key, never silently summed across different session boundaries.
- **transport**: ops ride the persistent binary wire (PR 17) as
  ``T_CQ``/``T_CQ_RES`` frames when the peer speaks it, falling back
  to JSON HTTP exactly like the write path (non-OSError reroutes);
  both paths replay through the shard's real HTTP handler, so fault
  sites and chaos hangs cover them identically.
- **restart survival**: every op that 404s ("no continuous query" —
  the shard restarted with an empty registry) re-registers from the
  stored body and retries once, so a router-registered CQ outlives
  any shard restart without operator action.

Fault site: ``cluster.cq`` (+ ``cluster.cq.<peer>``); trace spans:
``cluster.cq`` per exchange, ``cluster.cq.pump`` per merged drain.
"""

from __future__ import annotations

import itertools
import json
import logging
import math
import threading
import time
from typing import Any

from opentsdb_tpu.cluster import merge as merge_mod
from opentsdb_tpu.cluster import wire as wire_mod
from opentsdb_tpu.obs.trace import trace_begin, trace_end
from opentsdb_tpu.query.model import BadRequestError, TSQuery
from opentsdb_tpu.streaming import sse
from opentsdb_tpu.streaming.eventtime import WatermarkPolicy
from opentsdb_tpu.utils.faults import DegradedError

LOG = logging.getLogger("cluster.cq")

_CQ_BASE = "/api/query/continuous"


class FedCQ:
    """Router-side handle of one federated continuous query."""

    def __init__(self, cid: str, raw: dict, tsq: TSQuery,
                 policy: WatermarkPolicy | None,
                 sub_plans: list[tuple]):
        self.id = cid
        self.raw = raw            # registration body incl. explicit id
        self.tsq = tsq
        self.policy = policy
        #: per sub index: (plan, combine-or-None, group-by tag keys)
        self.sub_plans = sub_plans
        self.closed = False
        self.created = time.time()
        self.tenant: str | None = None
        self.lock = threading.Lock()
        self.subscribers: list[sse.Subscription] = []
        self.emit_seq = 0
        #: shards holding a live shard-local registration
        # tsdlint: allow[unbounded-growth] keyed by ring shard name
        self.shards: set[str] = set()
        #: per-shard resident ring bytes, from register/pull describes
        # tsdlint: allow[unbounded-growth] keyed by ring shard name
        self.shard_fold_bytes: dict[str, int] = {}

    def fold_bytes(self) -> int:
        return sum(self.shard_fold_bytes.values())

    def describe(self, verbose: bool = False) -> dict[str, Any]:
        out: dict[str, Any] = {
            "id": self.id,
            "query": self.tsq.to_json(),
            "federated": True,
            "shards": sorted(self.shards),
            "subscribers": len(self.subscribers),
            "emitSeq": self.emit_seq,
            "foldBytes": self.fold_bytes(),
        }
        if self.raw.get("window"):
            out["windowSpec"] = self.raw["window"]
        if self.policy is not None:
            out["watermark"] = self.policy.to_json()
        return out


class FederatedCQRegistry:
    """(see module docstring) Duck-types the surface
    :func:`~opentsdb_tpu.streaming.sse.sse_stream` and the HTTP
    handler consume: ``register/get/list/delete``, ``subscribe/pump/
    unsubscribe``, ``current_results``, ``heartbeat_s``."""

    def __init__(self, router):
        self.router = router
        self.tsdb = router.tsdb
        cfg = self.tsdb.config
        self.heartbeat_s = cfg.get_float("tsd.streaming.heartbeat_s",
                                         5.0)
        self.queue_events = cfg.get_int("tsd.streaming.queue_events",
                                        256)
        self._lock = threading.Lock()
        self._ids = itertools.count(1)
        self._queries: dict[str, FedCQ] = {}
        # counters (collect_stats + tests)
        self.registrations = 0
        self.deletes = 0
        self.pumps = 0
        self.merged_pulls = 0
        self.wire_ops = 0
        self.http_fallbacks = 0
        self.reregisters = 0
        self.sse_events = 0
        self.sse_shed = 0

    # -- shard transport -----------------------------------------------

    def _check_faults(self, peer) -> None:
        faults = getattr(self.tsdb, "faults", None)
        if faults is not None:
            faults.check("cluster.cq")
            faults.check(f"cluster.cq.{peer.name}")

    def _exchange(self, peer, method: str, path: str,
                  body: bytes = b"") -> tuple[int, bytes]:
        """One raw shard exchange: wire first (persistent framed
        transport), JSON HTTP on wire refusal; ``OSError`` means the
        shard is down (degrade territory)."""
        sp = trace_begin("cluster.cq", peer=peer.name, op=method)
        try:
            self._check_faults(peer)
            wire = self.router.wire
            sent = None
            if wire is not None and wire.usable(peer):
                try:
                    sent = wire.cq(peer, method, path, body)
                    self.wire_ops += 1
                except (wire_mod.WireUnsupported,
                        wire_mod.WireBacklogged,
                        wire_mod.WireEncodeError):
                    self.http_fallbacks += 1
            if sent is None:
                sent = peer.client.request(method, path,
                                           body or None)
        except BaseException as exc:
            trace_end(sp, error=exc)
            raise
        trace_end(sp)
        return sent

    def _cq_op(self, fcq: FedCQ, peer, method: str, path: str,
               body: bytes = b"") -> tuple[int, bytes]:
        """One shard op with restart survival: a 404 means the shard
        lost its registry (restart) — re-register from the stored
        body and retry the op once."""
        status, data = self._exchange(peer, method, path, body)
        if status == 404 and not fcq.closed:
            reg_status, reg_body = self._exchange(
                peer, "POST", _CQ_BASE,
                json.dumps(fcq.raw).encode())
            if reg_status == 200:
                self.reregisters += 1
                self._note_register(fcq, peer.name, reg_body)
                status, data = self._exchange(peer, method, path,
                                              body)
        return status, data

    def _note_register(self, fcq: FedCQ, name: str,
                       body: bytes) -> None:
        with fcq.lock:
            fcq.shards.add(name)
            try:
                fcq.shard_fold_bytes[name] = int(
                    json.loads(body).get("foldBytes", 0))
            except Exception:  # noqa: BLE001
                # tsdlint: allow[swallow] fold-byte accounting is
                # advisory (QoS scoring) — a torn describe body must
                # not fail the registration that carried it
                pass

    def _fan_out(self, op) -> list[tuple[str, Any]]:
        """Run ``op(peer)`` on every ring shard concurrently (the
        router's scatter pool); returns ``[(name, result-or-exc)]``
        in ring order."""
        peers = [self.router.peers[n] for n in self.router.ring.names]
        futs = [(p.name, self.router.pool.submit(op, p))
                for p in peers]
        out: list[tuple[str, Any]] = []
        for name, fut in futs:
            try:
                out.append((name, fut.result(
                    timeout=self.router.timeout_s * 2 + 1)))
            except Exception as exc:  # noqa: BLE001 - per-leg degrade
                out.append((name, exc))
        return out

    # -- registration lifecycle ----------------------------------------

    def register(self, obj: dict) -> FedCQ:
        if not isinstance(obj, dict):
            raise BadRequestError("continuous query body must be an "
                                  "object")
        if self.router.resharding:
            raise BadRequestError(
                "cannot register a continuous query while a reshard "
                "is in progress; retry after cutover")
        if self.router.rf > 1:
            raise BadRequestError(
                "federated continuous queries need tsd.cluster.rf=1: "
                "every replica folds every write, so a cross-shard "
                "merge would count each point rf times")
        cid = str(obj.get("id") or "")
        with self._lock:
            if not cid:
                cid = f"cq-{next(self._ids)}"
                while cid in self._queries:
                    cid = f"cq-{next(self._ids)}"
            elif cid in self._queries:
                raise BadRequestError(
                    f"continuous query id {cid!r} already registered")
        tsq = TSQuery.from_json(
            {k: v for k, v in obj.items()
             if k not in ("id", "window", "watermark")})
        tsq.validate()
        policy = WatermarkPolicy.from_json(obj.get("watermark"))
        win = obj.get("window")
        session_by = win.get("by") if isinstance(win, dict) else None
        sub_plans: list[tuple] = []
        for sub in tsq.queries:
            plan = merge_mod.decompose_plan(sub)
            if plan not in ("direct", "concat"):
                raise BadRequestError(
                    f"aggregator {sub.aggregator!r} does not merge "
                    "across shard partials incrementally (federated "
                    "CQs support none, sum, count, min, max, zimsum, "
                    "mimmin, mimmax)")
            combine = merge_mod._COMBINE.get(
                (sub.aggregator or "").lower())
            gbk = merge_mod.gb_tag_keys(sub)
            if session_by:
                # session rows are keyed by the session tag's value:
                # the merge must group per key value, never fold two
                # users' timelines into one (module docstring)
                gbk = sorted(set(gbk) | {str(session_by)})
            sub_plans.append((plan, combine, gbk))
        raw = dict(obj, id=cid)
        fcq = FedCQ(cid, raw, tsq, policy, sub_plans)
        body = json.dumps(raw).encode()
        legs = self._fan_out(
            lambda p: self._exchange(p, "POST", _CQ_BASE, body))
        refusal: tuple[str, bytes] | None = None
        for name, res in legs:
            if isinstance(res, Exception):
                # down shard: tolerated — the 404 path re-registers
                # on first contact after it returns
                continue
            status, data = res
            if status == 200:
                self._note_register(fcq, name, data)
            elif refusal is None:
                refusal = (name, data)
        if refusal is not None or not fcq.shards:
            # roll back the shards that accepted: a half-registered
            # standing query would silently fold a subset of writes
            for name in list(fcq.shards):
                try:
                    self._exchange(self.router.peers[name], "DELETE",
                                   f"{_CQ_BASE}/{cid}")
                except Exception:  # noqa: BLE001
                    # tsdlint: allow[swallow] best-effort rollback of
                    # a refused registration — an unreachable shard
                    # 404s the leftover on first contact anyway
                    pass
            if refusal is not None:
                name, data = refusal
                try:
                    msg = json.loads(data)["error"]["message"]
                except Exception:  # noqa: BLE001 - opaque shard body
                    msg = data.decode("utf-8", "replace")
                raise BadRequestError(f"shard {name}: {msg}")
            raise DegradedError(
                f"continuous query {cid!r}: no shard reachable to "
                "hold the registration; retry shortly")
        with self._lock:
            self._queries[cid] = fcq
        self.registrations += 1
        return fcq

    def get(self, cid: str) -> FedCQ | None:
        with self._lock:
            return self._queries.get(cid)

    def list(self) -> list[FedCQ]:
        with self._lock:
            return list(self._queries.values())

    def delete(self, cid: str) -> bool:
        with self._lock:
            fcq = self._queries.pop(cid, None)
        if fcq is None:
            return False
        fcq.closed = True
        self._fan_out(
            lambda p: self._exchange(p, "DELETE",
                                     f"{_CQ_BASE}/{cid}"))
        self.deletes += 1
        return True

    def close(self) -> None:
        """Router shutdown: drop local state only (shard-side
        registrations belong to explicit DELETEs; a restarting router
        must not tear down standing queries it will re-learn)."""
        with self._lock:
            queries = list(self._queries.values())
            self._queries.clear()
        for fcq in queries:
            fcq.closed = True

    # -- fold-budget surface (QoS duck-typing) -------------------------

    def tenant_fold_bytes(self, tenant: str) -> int:
        return sum(fcq.fold_bytes() for fcq in self.list()
                   if fcq.tenant == tenant)

    def projected_fold_bytes(self, obj: dict) -> int:
        reg = self.tsdb.streaming
        if reg is None:
            return 0
        return reg.projected_fold_bytes(obj)

    # -- merged pull ---------------------------------------------------

    @staticmethod
    def _split_marker(rows: list) -> tuple[list, dict | None]:
        """Strip one shard leg's trailing completeness marker row."""
        if rows and isinstance(rows[-1], dict) \
                and "completeness" in rows[-1] \
                and "metric" not in rows[-1]:
            return rows[:-1], rows[-1]["completeness"]
        return rows, None

    def _merge_rows(self, fcq: FedCQ,
                    legs: list[list[dict]]) -> list[dict]:
        """Fold per-shard row dicts into merged rows with the batch
        scatter's dict-fold machinery — the same pairwise combines in
        the same leg order, which is what makes an integer workload
        bit-identical to the single-node oracle. Output rows sort by
        (sub index, metric, tags) for a deterministic surface."""
        merged: dict[tuple, merge_mod.MergedGroup] = {}
        idx_of: dict[int, int] = {}
        concat: list[tuple[int, merge_mod.MergedGroup]] = []
        for rows in legs:
            for r in rows:
                idx = int(r.get("index") or 0)
                plan, combine, gbk = fcq.sub_plans[
                    min(idx, len(fcq.sub_plans) - 1)]
                dps = [(int(ts), (math.nan if v is None else v))
                       for ts, v in (r.get("dps") or {}).items()]
                dps.sort()
                if plan == "concat":
                    g = merge_mod.MergedGroup(r)
                    g.fold_dps(dps, merge_mod._COMBINE["sum"])
                    concat.append((idx, g))
                    continue
                key = (idx,) + merge_mod.group_key(r, gbk)
                g = merged.get(key)
                if g is None:
                    g = merged[key] = merge_mod.MergedGroup(r)
                    idx_of[id(g)] = idx
                else:
                    g.fold_tags(r)
                g.fold_dps(dps, combine)
        out = []
        for key, g in merged.items():
            out.append((key[0], g))
        out.extend(concat)
        rows_out = []
        for idx, g in out:
            rows_out.append({
                "metric": g.metric, "tags": g.tags,
                "aggregateTags": sorted(g.agg_tags), "index": idx,
                "dps": {str(ts): (None if v != v else v)
                        for ts, v in sorted(g.dps.items())}})
        rows_out.sort(key=lambda r: (r["index"], r["metric"],
                                     sorted(r["tags"].items())))
        return rows_out

    @staticmethod
    def _merge_markers(markers: list[dict],
                       degraded: list[str]) -> dict:
        """Join per-shard completeness markers: the merged range is
        only as final as the LEAST-advanced shard, counters sum, and
        a missing shard forces ``complete: false``."""
        out: dict[str, Any] = {
            "watermarkMs": min((m.get("watermarkMs", 0)
                                for m in markers), default=0),
            "lateRefolded": sum(m.get("lateRefolded", 0)
                                for m in markers),
            "lateDropped": sum(m.get("lateDropped", 0)
                               for m in markers),
            "complete": bool(markers)
            and all(m.get("complete") for m in markers)
            and not degraded,
        }
        lat = [m.get("latenessMs") for m in markers
               if m.get("latenessMs") is not None]
        if lat:
            out["latenessMs"] = lat[0]
        if any("sessionsOpen" in m for m in markers):
            out["sessionsOpen"] = sum(m.get("sessionsOpen", 0)
                                      for m in markers)
            out["sessionsClosed"] = sum(m.get("sessionsClosed", 0)
                                        for m in markers)
        if any(m.get("degraded") for m in markers):
            out["degraded"] = True
        if degraded:
            out["shardsDegraded"] = sorted(degraded)
        return out

    def current_results(self, fcq: FedCQ,
                        now_ms: int | None = None) -> list[dict]:
        """The merged pull: every reachable shard's current rows
        folded into one answer; unreachable shards degrade into the
        trailing marker (never a 5xx, the /api/query idiom)."""
        self.merged_pulls += 1
        path = f"{_CQ_BASE}/{fcq.id}/result"
        res = self._fan_out(
            lambda p: self._cq_op(fcq, p, "GET", path))
        legs: list[list[dict]] = []
        markers: list[dict] = []
        degraded: list[str] = []
        for name, r in res:
            if isinstance(r, Exception) or r[0] != 200:
                degraded.append(name)
                continue
            try:
                rows = json.loads(r[1])
            except Exception:  # noqa: BLE001 - torn shard body
                degraded.append(name)
                continue
            rows, marker = self._split_marker(rows)
            legs.append(rows)
            if marker is not None:
                markers.append(marker)
        if len(degraded) == len(res):
            raise DegradedError(
                f"continuous query {fcq.id!r}: every shard leg "
                "failed; retry shortly")
        rows_out = self._merge_rows(fcq, legs)
        if fcq.policy is not None or degraded:
            rows_out.append({"completeness": self._merge_markers(
                markers, degraded)})
        return rows_out

    # -- merged push (SSE duck-type surface) ---------------------------

    def subscribe(self, fcq: FedCQ,
                  last_event_id: int | None = None
                  ) -> sse.Subscription:
        sub = sse.Subscription(self.queue_events)
        with fcq.lock:
            fcq.subscribers.append(sub)
            seq = fcq.emit_seq
        # initial snapshot: the merged current rows (resume replay is
        # a shard-local luxury; federated reconnects re-snapshot)
        try:
            rows = self.current_results(fcq)
        except DegradedError:
            rows = [{"completeness": {
                "degraded": True, "complete": False}}]
        rows, marker = self._split_marker(rows)
        payload: dict[str, Any] = {
            "id": fcq.id, "seq": seq,
            "ts": int(time.time() * 1000),
            "updates": rows}
        if marker is not None:
            payload["completeness"] = marker
        sse.offer_frame(sub, sse.frame("snapshot", payload,
                                       event_id=seq))
        return sub

    def unsubscribe(self, fcq: FedCQ, sub: sse.Subscription) -> None:
        with fcq.lock:
            if sub in fcq.subscribers:
                fcq.subscribers.remove(sub)
                self.sse_events += sub.events

    def pump(self, fcq: FedCQ, force: bool = False) -> bool:
        """One merged delta drain: fan the dirty-window pull to every
        shard, fold the per-shard updates, publish one ``windows``
        frame to every subscriber. Called from the SSE generator's
        heartbeat loop (the shard-local registry's pump contract)."""
        sp = trace_begin("cluster.cq.pump", cq=fcq.id)
        try:
            self.pumps += 1
            path = f"{_CQ_BASE}/{fcq.id}/deltas"
            res = self._fan_out(
                lambda p: self._cq_op(fcq, p, "GET", path))
            legs: list[list[dict]] = []
            markers: list[dict] = []
            degraded: list[str] = []
            for name, r in res:
                if isinstance(r, Exception) or r[0] != 200:
                    degraded.append(name)
                    continue
                try:
                    doc = json.loads(r[1])
                except Exception:  # noqa: BLE001 - torn shard body
                    degraded.append(name)
                    continue
                legs.append(doc.get("updates") or [])
                if doc.get("completeness") is not None:
                    markers.append(doc["completeness"])
            updates = self._merge_rows(fcq, legs)
            if not updates and not force and not degraded:
                trace_end(sp)
                return False
            payload: dict[str, Any] = {
                "id": fcq.id, "ts": int(time.time() * 1000),
                "updates": updates}
            if fcq.policy is not None or degraded:
                payload["completeness"] = self._merge_markers(
                    markers, degraded)
            with fcq.lock:
                fcq.emit_seq += 1
                payload["seq"] = fcq.emit_seq
                targets = list(fcq.subscribers)
                fr = sse.frame("windows", payload,
                               event_id=fcq.emit_seq)
            shed = 0
            for s in targets:
                if not sse.offer_frame(s, fr):
                    shed += 1
                    with fcq.lock:
                        if s in fcq.subscribers:
                            fcq.subscribers.remove(s)
                            self.sse_events += s.events
            self.sse_shed += shed
            self.sse_events += len(targets) - shed
        except BaseException as exc:
            trace_end(sp, error=exc)
            raise
        trace_end(sp)
        return True

    # -- observability -------------------------------------------------

    def collect_stats(self, collector) -> None:
        with self._lock:
            n = len(self._queries)
        collector.record("cluster.cq.queries", n)
        collector.record("cluster.cq.registrations",
                         self.registrations)
        collector.record("cluster.cq.deletes", self.deletes)
        collector.record("cluster.cq.pumps", self.pumps)
        collector.record("cluster.cq.merged_pulls", self.merged_pulls)
        collector.record("cluster.cq.wire_ops", self.wire_ops)
        collector.record("cluster.cq.http_fallbacks",
                         self.http_fallbacks)
        collector.record("cluster.cq.reregisters", self.reregisters)
        collector.record("cluster.cq.sse_shed", self.sse_shed)


__all__ = ["FedCQ", "FederatedCQRegistry"]
