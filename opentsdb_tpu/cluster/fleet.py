"""Fleet-wide stats aggregation: the router as the one scrape point.

Per-node ``/api/stats`` counters multiply by shard count; nothing
aggregated them. This module scatters the per-node raw-stats document
(``GET /api/stats/raw`` — counters/gauges plus full-resolution
histogram snapshots) over the existing peer client with the same
failure discipline as a read scatter (breaker-aware, degraded peers
marked, never a 5xx) and merges:

- **counters** sum across nodes (a fleet total);
- **gauges** (levels — :func:`opentsdb_tpu.stats.stats.is_gauge`)
  list per-node values plus min/max/sum — summing a level is shown,
  never silently substituted for the distribution;
- **histograms** BUCKET-sum at full internal resolution
  (:func:`merge_histogram_snapshots`), so a fleet p99 is computed
  from the merged distribution — exact, not an average of per-node
  percentiles (averaging percentiles is the classic observability
  lie this module exists to avoid). Every snapshot also carries a
  DDSketch companion (``sketch`` field): when bucket tables differ
  across nodes (a mixed-build fleet) the bucket sum refuses, and the
  sketches — whose merge is exact regardless of each node's ladder —
  take over the percentile columns (``merge: "sketch"``). When the
  buckets DO merge, the sketch percentiles ride along under a
  ``sketch`` sub-document as the higher-resolution companion view
  (relative-error buckets vs the 1ms-linear ladder's absolute bins).

Also here: the consolidated operator progress surface behind
``GET /api/cluster/status`` — reshard epoch + backfill done-markers +
retire progress + per-peer spool backlog and dirty-debt AGE, with
coarse ETA estimates.
"""

from __future__ import annotations

import concurrent.futures
import json
import time
from typing import Any

from opentsdb_tpu.stats.stats import (LATENCY_PCTS, is_gauge,
                                      merge_histogram_snapshots,
                                      percentiles_from_buckets)


def _tag_suffix(tags: dict[str, Any]) -> str:
    if not tags:
        return ""
    return "{" + ",".join(f"{k}={v}" for k, v in
                          sorted(tags.items())) + "}"


def scatter_json(router, path: str
                 ) -> tuple[dict[str, dict], list[str]]:
    """One GET of ``path`` per reachable peer, JSON-object bodies
    only. Returns ``(name -> parsed doc, failed peer names)`` — a
    breaker-blocked peer fails WITHOUT being touched (same rule as a
    read scatter), and any per-peer trouble lands in the failed list,
    never out of this function."""
    futs: dict[str, Any] = {}
    failed: list[str] = []
    for name, peer in sorted(router.peers.items()):
        if peer.breaker.blocking():
            failed.append(name)
            continue
        futs[name] = router.pool.submit(
            router.fetch_guarded, peer, "GET", path)
    docs: dict[str, dict] = {}
    for name, fut in futs.items():
        try:
            status, data = fut.result(
                timeout=router.timeout_s * 2 + 5)
            if status != 200:
                raise OSError(f"{path} answered {status}")
            doc = json.loads(data)
            if not isinstance(doc, dict):
                raise OSError(f"{path} body is not an object")
        except (OSError, ValueError,
                concurrent.futures.TimeoutError):
            peer = router.peers.get(name)
            if peer is not None:
                peer.query_failures += 1
            failed.append(name)
            continue
        docs[name] = doc
    return docs, sorted(failed)


def _merge_snapshot_sketches(snaps: "list[dict]"):
    """Merge the base64 ``sketch`` companions of histogram snapshot
    documents. Returns the merged DDSketch only when EVERY snapshot
    carries a parseable, alpha-compatible sketch — a partial merge
    would silently drop some nodes' observations from the fleet
    distribution, which is exactly the lie this module refuses."""
    from opentsdb_tpu.sketch.ddsketch import DDSketch, SketchError
    merged = None
    for s in snaps:
        blob = s.get("sketch")
        if not isinstance(blob, str):
            return None
        try:
            sk = DDSketch.from_b64(blob)
        except (SketchError, ValueError):
            return None
        if merged is None:
            merged = sk
        else:
            try:
                merged.merge(sk)
            except SketchError:
                return None
    return merged


def merge_fleet(docs: dict[str, dict]) -> dict[str, Any]:
    """Merge per-node raw-stats documents into the fleet view."""
    counters: dict[str, float] = {}
    gauges: dict[str, dict[str, Any]] = {}
    hists: dict[str, dict[str, Any]] = {}
    for node, doc in sorted(docs.items()):
        for rec in doc.get("records") or []:
            name = str(rec.get("metric", ""))
            tags = rec.get("tags") or {}
            try:
                value = float(rec.get("value", 0.0))
            except (TypeError, ValueError):
                continue
            key = name + _tag_suffix(tags)
            bare = name.split(".", 1)[1] if "." in name else name
            if is_gauge(bare):
                g = gauges.setdefault(key, {"nodes": {}})
                g["nodes"][node] = value
            else:
                counters[key] = counters.get(key, 0.0) + value
        for h in doc.get("histograms") or []:
            name = str(h.get("name", ""))
            labels = h.get("labels") or {}
            key = name + _tag_suffix(labels)
            entry = hists.setdefault(
                key, {"name": name, "labels": dict(labels),
                      "snaps": [], "nodes": []})
            entry["snaps"].append(h)
            entry["nodes"].append(node)
    for g in gauges.values():
        vals = list(g["nodes"].values())
        g["min"] = min(vals)
        g["max"] = max(vals)
        g["sum"] = sum(vals)
    hist_out: dict[str, dict[str, Any]] = {}
    for key, entry in sorted(hists.items()):
        merged = merge_histogram_snapshots(entry["snaps"])
        sketch = _merge_snapshot_sketches(entry["snaps"])
        if merged is None and sketch is None:
            hist_out[key] = {"error": "bucket tables do not merge",
                             "nodes": entry["nodes"]}
            continue
        sk_pcts = None
        if sketch is not None:
            vals = (sketch.quantiles([q for _l, q in LATENCY_PCTS])
                    if sketch.count else [0.0] * len(LATENCY_PCTS))
            sk_pcts = {label: float(v)
                       for (label, _q), v in zip(LATENCY_PCTS, vals)}
        doc: dict[str, Any]
        if merged is not None:
            # bucket sum is the primary path: bit-identical to the
            # same observations landing in one histogram
            pcts = percentiles_from_buckets(
                merged["bounds"], merged["buckets"], merged["count"],
                [q for _l, q in LATENCY_PCTS])
            doc = {label: v
                   for (label, _q), v in zip(LATENCY_PCTS, pcts)}
            doc["count"] = merged["count"]
            doc["sum"] = round(merged["sum"], 3)
            doc["merge"] = "buckets"
            if sk_pcts is not None:
                doc["sketch"] = sk_pcts
        else:
            # mixed bucket ladders: the sketches still merge exactly,
            # so the fleet percentiles come from the merged sketch —
            # never from averaging per-node percentiles
            doc = dict(sk_pcts)
            doc["count"] = int(sketch.count)
            doc["sum"] = round(sum(float(s.get("sum") or 0.0)
                                   for s in entry["snaps"]), 3)
            doc["merge"] = "sketch"
        doc["nodes"] = entry["nodes"]
        hist_out[key] = doc
    return {"counters": {k: counters[k] for k in sorted(counters)},
            "gauges": {k: gauges[k] for k in sorted(gauges)},
            "histograms": hist_out}


def fleet_stats(router) -> dict[str, Any]:
    """The ``GET /api/stats/fleet`` document."""
    docs, degraded = scatter_json(router, "/api/stats/raw")
    out = merge_fleet(docs)
    out["nodes"] = {name: "ok" for name in sorted(docs)}
    out["nodes"].update({name: "degraded" for name in degraded})
    out["shardsDegraded"] = degraded
    out["ts"] = int(time.time())
    return out


def fleet_health(router) -> dict[str, Any]:
    """The ``fleet`` section of a router's ``/api/health``: one
    status line per shard (scattered ``/api/health``), never a 5xx —
    an unreachable shard is a ``"unreachable"`` row, not a failure."""
    docs, failed = scatter_json(router, "/api/health")
    nodes: dict[str, dict[str, Any]] = {
        name: {"status": "unreachable"} for name in failed}
    for name, doc in docs.items():
        nodes[name] = {
            "status": doc.get("status", "unknown"),
            "causes": doc.get("causes") or [],
            "uptime_seconds": doc.get("uptime_seconds"),
        }
    ok = sum(1 for n in nodes.values() if n["status"] == "ok")
    return {
        "shards": len(nodes),
        "ok": ok,
        "degraded": sorted(n for n, d in nodes.items()
                           if d["status"] != "ok"),
        "nodes": nodes,
    }


def cluster_status(router) -> dict[str, Any]:
    """The ``GET /api/cluster/status`` consolidated progress doc."""
    now_ms = int(time.time() * 1000)
    state = router.state
    doc: dict[str, Any] = {
        "epoch": state.epoch,
        "rf": router.rf,
        "ring": {"peers": list(router.ring.names),
                 "vnodes": router.ring.vnodes},
        "ts": now_ms // 1000,
    }
    # -- reshard / backfill window -------------------------------------
    reshard = state.describe()
    doc["reshard"] = reshard
    if reshard.get("active"):
        bf = router.backfiller.health_info()
        bf.update(router.backfiller.progress())
        done = bf.get("done_units") or 0
        total = bf.get("total_units") or 0
        fence = reshard.get("fence_ms") or 0
        if done and total and fence:
            elapsed_s = max((now_ms - fence) / 1000.0, 0.001)
            rate = done / elapsed_s
            bf["eta_s"] = round((total - done) / rate, 1) \
                if rate > 0 and total > done else 0.0
        else:
            bf["eta_s"] = None  # no progress yet: no honest estimate
        doc["backfill"] = bf
    doc["retire"] = router.retirer.health_info()
    # -- per-peer spool backlog + divergence debt ----------------------
    # drain floor: one replay batch per interval wake is the
    # guaranteed minimum (the catch-up drain usually clears faster),
    # so the ETA is an upper bound, not a promise
    drain_floor = router.replay_batch / max(
        router.replay_interval_s, 0.001)
    peers: dict[str, dict[str, Any]] = {}
    worst_age_s = 0.0
    backlog_total = 0
    for name, peer in sorted(router.peers.items()):
        pending = peer.spool.pending_records
        backlog_total += pending
        age = router.dirty.age_info(name, now_ms)
        if age["age_s"] > worst_age_s:
            worst_age_s = age["age_s"]
        peers[name] = {
            "breaker": peer.breaker.state,
            "spool_pending_records": pending,
            "spool_drain_eta_s": round(pending / drain_floor, 3)
            if pending else 0.0,
            "dirty_metrics": age["entries"],
            "dirty_oldest_age_s": age["age_s"],
        }
    doc["peers"] = peers
    doc["spool_backlog_records"] = backlog_total
    doc["dirty_oldest_age_s"] = worst_age_s
    # -- query-path read-repair (source-side work maybe_repair does
    # on behalf of reads — queue depth/shed/completions were
    # invisible here before) ------------------------------------------
    doc["read_repair"] = router.read_repair.health_info()
    # -- sibling-router gossip bus -------------------------------------
    if router.gossip is not None:
        doc["gossip"] = router.gossip.health_info()
    return doc


__all__ = ["cluster_status", "fleet_health", "fleet_stats",
           "merge_fleet", "scatter_json"]
