"""Router gossip/version bus: N front doors, one coherent cache.

A single router owns every invalidation signal its result cache
needs: its own ``_bump_versions`` calls happen-after the writes they
describe. Behind a load balancer that stops being true — a write (or
delete, or reshard) forwarded by router A changes shard data that
router B's epoch-qualified cache still considers current. The honest
deployment advice used to be "disable the router cache". This module
closes the gap: every router names its sibling routers
(``tsd.cluster.routers``) and exchanges **version deltas** — the
per-metric write-counter bumps and global bumps the local cache
machinery already produces — plus the reshard-epoch topology, so a
sibling's cache invalidates within one gossip interval of the write.

Delta semantics (why not merge counters by max): version counters are
LOCAL monotone clocks, not replicated state. Router A at version 5
for metric m must not ``max`` in router B's 1 — B's bump 0→1 names a
NEW write A has never seen, and max(5, 1) = 5 would leave A's cached
entry servable. Instead B ships the *event* ("m changed, my seq 41")
and A applies it by bumping A's OWN counter — strictly monotone, so
it always invalidates. Gossip-applied bumps are never re-logged
(``announce=False``), so a delta crosses each edge once and the
A↔B exchange cannot loop.

Failure discipline is the PR-1 idiom throughout:

- per-sibling :class:`CircuitBreaker` + the ``cluster.gossip`` fault
  site on every push;
- the delta log is bounded (``tsd.cluster.gossip.log_max``): a
  sibling that lagged past the trim sees a **seq gap** and covers the
  lost window with ONE conservative global bump (the bounded O(1)
  "anti-entropy full-sync" — every cached entry goes stale at once,
  which is exactly what an unknown invalidation window deserves);
- a restarted sibling arrives with a fresh instance **nonce**: the
  join is the same conservative bump, then deltas apply from the new
  position;
- a sibling unreachable past ``tsd.cluster.gossip.stale_ms``
  **degrades this router** — `degraded()` turns true and the router
  serves cache-bypassed (conservative: never a stale serve, never a
  5xx) until a push lands again. Heartbeats flow every interval even
  with no writes, so a healthy-but-idle fleet never degrades.

Topology rides the same bus: each push carries the persisted reshard
epoch + ring specs. A sibling seeing a HIGHER epoch (or the finalize
of its own open epoch) adopts it — creating peers, swapping rings,
persisting its own ``reshard.json`` and running its own idempotent
backfill — so killing the router that initiated a reshard leaves a
sibling that resumes and finalizes the cutover (duplicated copy units
dedupe last-write-wins on the shards).
"""

from __future__ import annotations

import collections
import json
import logging
import secrets
import threading
import time
from typing import Any

from opentsdb_tpu.cluster.client import PeerClient, parse_peer_spec
from opentsdb_tpu.obs import trace as trace_mod
from opentsdb_tpu.utils.faults import CircuitBreaker

LOG = logging.getLogger("cluster.gossip")


class Sibling:
    """One peer router on the gossip bus (NOT a shard: no spool — a
    missed delta is covered by the gap rule, never replayed)."""

    def __init__(self, name: str, host: str, port: int, config):
        self.name = name
        self.client = PeerClient(
            host, port,
            timeout_ms=config.get_float(
                "tsd.cluster.gossip.timeout_ms", 2000.0))
        self.breaker = CircuitBreaker(
            f"cluster.gossip.{name}",
            failure_threshold=config.get_int(
                "tsd.cluster.breaker.failure_threshold", 3),
            reset_timeout_ms=config.get_float(
                "tsd.cluster.breaker.reset_timeout_ms", 5000.0))
        # highest local seq this sibling has acknowledged
        self.acked_seq = 0
        # wall-clock of the last successful push (seed = construction:
        # a just-booted router gets one stale window of grace before
        # an unreachable sibling degrades it)
        self.last_ok = time.time()
        self.pushes = 0
        self.push_failures = 0
        self.deltas_sent = 0

    def health_info(self) -> dict[str, Any]:
        return {
            "address": self.client.address,
            "breaker": self.breaker.health_info(),
            "acked_seq": self.acked_seq,
            "last_ok_age_s": round(
                max(time.time() - self.last_ok, 0.0), 1),
            "pushes": self.pushes,
            "push_failures": self.push_failures,
            "deltas_sent": self.deltas_sent,
        }


class GossipBus:
    """The per-router delta log + push loop + receive/apply side."""

    def __init__(self, router, spec: str):
        self.router = router
        config = router.config
        self.siblings: dict[str, Sibling] = {}
        for name, host, port in parse_peer_spec(spec):
            self.siblings[name] = Sibling(name, host, port, config)
        if not self.siblings:
            raise ValueError(
                "tsd.cluster.routers parsed to no siblings")
        # instance identity: a restart mints a new nonce, and a
        # receiver treats the unknown nonce as a join (conservative
        # global bump) — no persisted gossip state to mis-trust
        self.nonce = secrets.token_hex(8)
        self._lock = threading.Lock()
        self._seq = 0
        # bounded delta log: (seq, frozenset-of-metrics | None) where
        # None = a global bump. Trimmed entries are covered by the
        # receiver's seq-gap rule.
        self._log: collections.deque = collections.deque()
        self.log_max = max(config.get_int(
            "tsd.cluster.gossip.log_max", 4096), 16)
        self.interval_s = config.get_float(
            "tsd.cluster.gossip.interval_ms", 250.0) / 1000.0
        self.stale_s = config.get_float(
            "tsd.cluster.gossip.stale_ms", 5000.0) / 1000.0
        # receive side: sender nonce -> applied seq, bounded (an
        # unknown nonce is a join; evicting a stale nonce merely
        # costs the evicted sender one conservative re-join)
        self._applied: collections.OrderedDict = \
            collections.OrderedDict()
        self._applied_max = 4 * max(len(self.siblings), 1) + 8
        # counters (health/stats/status surfaces)
        self.deltas_logged = 0
        self.deltas_applied = 0
        self.heartbeats_in = 0
        self.full_syncs = 0        # join/gap conservative bumps taken
        self.topology_adoptions = 0
        self.cache_bypasses = 0    # reads served around the cache
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    # -- lifecycle -----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None and self._thread.is_alive():
            return
        self._stop.clear()
        t = threading.Thread(target=self._push_loop,
                             name="cluster-gossip", daemon=True)
        self._thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5)

    # -- producer side (local bumps enter the log) ---------------------

    def record_writes(self, metrics) -> None:
        """Log one per-metric delta for a LOCAL version bump (called
        after ``_bump_versions``; never for gossip-applied bumps —
        that would loop the delta back forever)."""
        names = frozenset(m for m in metrics if m)
        if not names:
            return
        with self._lock:
            self._seq += 1
            self._log.append((self._seq, names))
            self.deltas_logged += 1
            self._trim_locked()
        self._wake.set()

    def record_global(self) -> None:
        """Log one global-bump delta (spool replay landed, repair
        completed, reshard epoch moved — any every-entry-stale
        event)."""
        with self._lock:
            self._seq += 1
            self._log.append((self._seq, None))
            self.deltas_logged += 1
            self._trim_locked()
        self._wake.set()

    def _trim_locked(self) -> None:
        # drop what every sibling acked; then enforce the hard cap
        # (a lagging sibling recovers via the seq-gap rule)
        min_acked = min((s.acked_seq for s in
                         self.siblings.values()), default=0)
        while self._log and self._log[0][0] <= min_acked:
            self._log.popleft()
        while len(self._log) > self.log_max:
            self._log.popleft()

    # -- degradation verdict -------------------------------------------

    def degraded(self) -> bool:
        """True while ANY sibling has not acknowledged a push within
        the stale window: a partitioned sibling may be forwarding
        writes this router cannot see, so serving from cache could be
        stale — the router serves cache-bypassed instead (conservative
        global-version semantics: correct, never a 5xx)."""
        now = time.time()
        return any(now - s.last_ok > self.stale_s
                   for s in self.siblings.values())

    def stale_siblings(self) -> list[str]:
        now = time.time()
        return sorted(n for n, s in self.siblings.items()
                      if now - s.last_ok > self.stale_s)

    # -- push loop ------------------------------------------------------

    def _push_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(self.interval_s)
            self._wake.clear()
            if self._stop.is_set():
                return
            try:
                self.push_once()
            except Exception:  # noqa: BLE001 - keep the loop alive
                LOG.exception("gossip push round failed")

    def _topology_doc(self) -> dict[str, Any]:
        state = self.router.state
        with state._lock:
            doc = {"epoch": state.epoch,
                   "peers": state.peers_spec,
                   "vnodes": state.vnodes,
                   "active": bool(state.old_spec),
                   "old_peers": state.old_spec,
                   "old_vnodes": state.old_vnodes,
                   "fence_ms": state.fence_ms}
        if not doc["peers"]:
            # epoch 0: config still names the ring — ship the spec a
            # sibling would need to adopt a LATER epoch against
            doc["peers"] = self.router.config.get_string(
                "tsd.cluster.peers", "")
            doc["vnodes"] = self.router.ring.vnodes
        return doc

    def push_once(self) -> int:
        """One push round to every sibling whose breaker admits a
        dispatch. Returns the number of siblings that acknowledged.
        Tests drive this directly for deterministic propagation.
        Rounds are high-frequency (every interval even when idle), so
        the background trace root takes the sampled retention."""
        topo = self._topology_doc()
        tracer = getattr(self.router.tsdb, "tracer", None)
        tctx = tracer.start_background("cluster.gossip.push",
                                       sample=True) \
            if tracer is not None and tracer.enabled else None
        ok = 0
        try:
            with trace_mod.use(tctx):
                for name in sorted(self.siblings):
                    if self._push_sibling(self.siblings[name], topo):
                        ok += 1
            if tctx is not None:
                tctx.tag(acked=ok, siblings=len(self.siblings))
        finally:
            if tracer is not None and tctx is not None:
                tracer.finish(tctx)
        return ok

    def _push_sibling(self, sib: Sibling, topo: dict) -> bool:
        if not sib.breaker.allow():
            return False
        with self._lock:
            seq = self._seq
            deltas = [{"seq": s,
                       **({"metrics": sorted(ms)} if ms is not None
                          else {"global": True})}
                      for s, ms in self._log
                      if s > sib.acked_seq]
        body = json.dumps({
            "nonce": self.nonce,
            "seq": seq,
            "deltas": deltas,
            "topology": topo,
        }).encode()
        sp = trace_mod.trace_begin("cluster.peer", peer=sib.name,
                                   deltas=len(deltas))
        try:
            faults = getattr(self.router.tsdb, "faults", None)
            if faults is not None:
                faults.check("cluster.gossip")
                faults.check(f"cluster.gossip.{sib.name}")
            status, data = sib.client.request(
                "POST", "/api/cluster/gossip", body)
            if status != 200:
                raise OSError(f"gossip answered {status}")
            ack = json.loads(data)
            if not isinstance(ack, dict):
                raise OSError("gossip ack is not an object")
        except (OSError, ValueError) as exc:
            sib.breaker.record_failure()
            sib.push_failures += 1
            trace_mod.trace_end(sp, error=exc)
            LOG.debug("gossip push to %s failed (%s)",
                      sib.name, exc)
            return False
        sib.breaker.record_success()
        sib.pushes += 1
        sib.deltas_sent += len(deltas)
        sib.last_ok = time.time()
        with self._lock:
            sib.acked_seq = max(sib.acked_seq, seq)
            self._trim_locked()
        trace_mod.trace_end(sp)
        return True

    # -- receive side ---------------------------------------------------

    def apply_remote(self, doc: dict) -> dict[str, Any]:
        """Apply one sibling's push (the ``POST /api/cluster/gossip``
        body). Bumps are applied with ``announce=False`` so they are
        never re-logged. Returns the ack document."""
        if not isinstance(doc, dict):
            raise ValueError("gossip body must be an object")
        nonce = str(doc.get("nonce", ""))
        seq = int(doc.get("seq", 0))
        deltas = doc.get("deltas") or []
        if not nonce or not isinstance(deltas, list):
            raise ValueError("gossip body needs nonce + deltas")
        router = self.router
        with self._lock:
            applied = self._applied.get(nonce)
            if applied is not None:
                self._applied.move_to_end(nonce)
        full_sync = False
        if applied is None:
            # unknown instance (sibling joined or restarted): one
            # conservative global bump covers every write it may have
            # forwarded before this exchange existed
            full_sync = True
            applied = seq - len(deltas)
        else:
            first = min((int(d.get("seq", 0)) for d in deltas
                         if isinstance(d, dict)), default=seq + 1)
            if first > applied + 1:
                # the sender trimmed deltas this router never saw
                # (lag past log_max): the lost window is unknowable —
                # cover it with one global bump
                full_sync = True
        metrics: set[str] = set()
        global_bumps = 0
        for d in deltas:
            if not isinstance(d, dict) or \
                    int(d.get("seq", 0)) <= applied:
                continue
            if d.get("global"):
                global_bumps += 1
            else:
                metrics.update(str(m) for m in
                               (d.get("metrics") or ()))
            self.deltas_applied += 1
        if full_sync:
            self.full_syncs += 1
            router._bump_global_version(announce=False)
        if metrics:
            router._bump_versions(metrics, announce=False)
        if global_bumps:
            router._bump_global_version(announce=False)
        if not deltas:
            self.heartbeats_in += 1
        with self._lock:
            self._applied[nonce] = max(
                seq, self._applied.get(nonce, 0))
            self._applied.move_to_end(nonce)
            while len(self._applied) > self._applied_max:
                self._applied.popitem(last=False)
        topo = doc.get("topology")
        if isinstance(topo, dict):
            try:
                if router.adopt_topology(topo):
                    self.topology_adoptions += 1
            except Exception:  # noqa: BLE001 - adoption must never 5xx
                LOG.exception("gossip topology adoption failed")
        return {"nonce": self.nonce, "applied_seq": seq,
                "epoch": router.state.epoch,
                "fullSync": full_sync}

    # -- observability --------------------------------------------------

    def health_info(self) -> dict[str, Any]:
        with self._lock:
            log_len = len(self._log)
            seq = self._seq
        return {
            "nonce": self.nonce,
            "seq": seq,
            "log_entries": log_len,
            "degraded": self.degraded(),
            "stale_siblings": self.stale_siblings(),
            "deltas_logged": self.deltas_logged,
            "deltas_applied": self.deltas_applied,
            "heartbeats_in": self.heartbeats_in,
            "full_syncs": self.full_syncs,
            "topology_adoptions": self.topology_adoptions,
            "cache_bypasses": self.cache_bypasses,
            "siblings": {n: s.health_info()
                         for n, s in sorted(self.siblings.items())},
        }

    def collect_stats(self, collector) -> None:
        collector.record("cluster.gossip.deltas_logged",
                         self.deltas_logged)
        collector.record("cluster.gossip.deltas_applied",
                         self.deltas_applied)
        collector.record("cluster.gossip.full_syncs",
                         self.full_syncs)
        collector.record("cluster.gossip.topology_adoptions",
                         self.topology_adoptions)
        collector.record("cluster.gossip.cache_bypasses",
                         self.cache_bypasses)
        collector.record("cluster.gossip.degraded",
                         1 if self.degraded() else 0)
        for name, s in sorted(self.siblings.items()):
            collector.record("cluster.gossip.pushes", s.pushes,
                             sibling=name)
            collector.record("cluster.gossip.push_failures",
                             s.push_failures, sibling=name)
            s.breaker.collect_stats(collector)


__all__ = ["GossipBus", "Sibling"]
