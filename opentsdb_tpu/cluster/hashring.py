"""Consistent-hash series→shard map.

The reference spreads series over storage by prefixing the row key
with ``hash(metric+tags) % 20`` salt buckets (``RowKey.java``,
``Const.SALT_BUCKETS``); this ring lifts the same key to the network
tier. Consistent hashing (vnodes on a ring) instead of plain modulo so
adding or removing a shard remaps only ``~1/N`` of the series — the
property that makes rolling a new shard into a live cluster sane.

Hashes are MD5 of the key bytes: deterministic across processes and
restarts (Python's ``hash()`` is seed-randomized per process, which
would scatter a router restart's writes onto different shards than
the data it already routed).
"""

from __future__ import annotations

import bisect
import hashlib

import numpy as np


def _hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.md5(data).digest()[:8], "big")


def series_shard_key(metric: str, tags: dict[str, str]) -> bytes:
    """The shard key of one series: the reference's salt input —
    metric + sorted tag pairs (``RowKey.prefixKeyWithSalt`` hashes the
    metric+tags portion of the row key). Sorted so ``{a:1,b:2}`` and
    ``{b:2,a:1}`` land on the same shard."""
    parts = [metric]
    for k in sorted(tags):
        parts.append(f"{k}={tags[k]}")
    return "\x00".join(parts).encode("utf-8", "surrogatepass")


class HashRing:
    """Consistent-hash ring over named shards with ``vnodes`` virtual
    points per shard (more vnodes = smoother key distribution).

    Replication (RF ≥ 2) walks the ring clockwise from the key's hash
    point and collects the next R *distinct* shards — the Dynamo
    preference-list construction, which Monarch mirrors by assigning
    each target to 2-3 leaves. The ordered tuple is a series'
    **replica set**: ``[0]`` is the primary, the rest are fallbacks,
    and the set changes for only ``~1/N`` of series when a shard
    joins or leaves (the same property single ownership had)."""

    def __init__(self, names: list[str], vnodes: int = 64):
        if not names:
            raise ValueError("hash ring needs at least one shard")
        self.names = list(names)
        self.vnodes = max(int(vnodes), 1)
        points: list[tuple[int, str]] = []
        for name in self.names:
            for i in range(self.vnodes):
                points.append((_hash64(f"{name}#{i}".encode()), name))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [n for _, n in points]
        # replica tuples are pure functions of (segment start, rf):
        # memoized per rf because reads recompute them per series
        # tsdlint: allow[unbounded-growth] keyspace is (vnode segment,
        # rf) — at most names*vnodes*rf entries, fixed at construction
        self._sets_cache: dict[int, tuple] = {}
        self._points_arr = np.asarray(self._points, dtype=np.uint64)
        # (rf, vnode idx) -> replica tuple, same bound as _sets_cache
        # tsdlint: allow[unbounded-growth] keyspace fixed at construction
        self._walk_cache: dict[tuple[int, int], tuple[str, ...]] = {}

    def _walk(self, idx: int, rf: int) -> tuple[str, ...]:
        """Ordered next-``rf``-distinct owners clockwise from vnode
        position ``idx`` (the key's successor point)."""
        out: list[str] = []
        n = len(self._owners)
        for step in range(n):
            owner = self._owners[(idx + step) % n]
            if owner not in out:
                out.append(owner)
                if len(out) == rf:
                    break
        return tuple(out)

    def shards_for_key(self, key: bytes, rf: int = 1
                       ) -> tuple[str, ...]:
        """Ordered replica set (primary first) of one series key,
        clamped to the shard count."""
        rf = max(1, min(int(rf), len(self.names)))
        h = _hash64(key)
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._points):
            idx = 0  # wrap: the ring is circular
        return self._walk(idx, rf)

    def shards_for_keys(self, keys: list[bytes], rf: int = 1
                        ) -> list[tuple[str, ...]]:
        """Batched :meth:`shards_for_key`: one ``searchsorted`` over
        the vnode array for the whole batch instead of a bisect per
        key, with the clockwise walk memoized per (rf, segment) —
        there are only ``names*vnodes`` segments, so a large put
        batch's walks collapse to dict hits."""
        rf = max(1, min(int(rf), len(self.names)))
        if not keys:
            return []
        hs = np.fromiter((_hash64(k) for k in keys),
                         dtype=np.uint64, count=len(keys))
        idxs = np.searchsorted(self._points_arr, hs, side="right")
        idxs[idxs == len(self._points)] = 0  # wrap: ring is circular
        out: list[tuple[str, ...]] = []
        cache = self._walk_cache
        for idx in idxs.tolist():
            ck = (rf, idx)
            owners = cache.get(ck)
            if owners is None:
                owners = self._walk(idx, rf)
                cache[ck] = owners
            out.append(owners)
        return out

    def shard_for_key(self, key: bytes) -> str:
        """Owning (primary) shard of one pre-computed series key."""
        return self.shards_for_key(key, 1)[0]

    def shard_for(self, metric: str, tags: dict[str, str]) -> str:
        return self.shard_for_key(series_shard_key(metric, tags))

    def shards_for(self, metric: str, tags: dict[str, str],
                   rf: int = 1) -> tuple[str, ...]:
        return self.shards_for_key(series_shard_key(metric, tags), rf)

    def replica_sets(self, rf: int) -> tuple[tuple[str, ...], ...]:
        """Every distinct ordered replica set this ring can assign at
        ``rf`` — one candidate per vnode segment, deduplicated. The
        router's read plan assigns each set to exactly one member, so
        a scatter covers every series exactly once."""
        rf = max(1, min(int(rf), len(self.names)))
        cached = self._sets_cache.get(rf)
        if cached is None:
            seen: dict[tuple[str, ...], None] = {}
            for idx in range(len(self._points)):
                seen.setdefault(self._walk(idx, rf))
            cached = tuple(seen)
            self._sets_cache[rf] = cached
        return cached

    def distribution(self, keys) -> dict[str, int]:
        """Shard -> key count for a sample of keys (tests/ops)."""
        out = {n: 0 for n in self.names}
        for key in keys:
            out[self.shard_for_key(key)] += 1
        return out
