"""Consistent-hash series→shard map.

The reference spreads series over storage by prefixing the row key
with ``hash(metric+tags) % 20`` salt buckets (``RowKey.java``,
``Const.SALT_BUCKETS``); this ring lifts the same key to the network
tier. Consistent hashing (vnodes on a ring) instead of plain modulo so
adding or removing a shard remaps only ``~1/N`` of the series — the
property that makes rolling a new shard into a live cluster sane.

Hashes are MD5 of the key bytes: deterministic across processes and
restarts (Python's ``hash()`` is seed-randomized per process, which
would scatter a router restart's writes onto different shards than
the data it already routed).
"""

from __future__ import annotations

import bisect
import hashlib


def _hash64(data: bytes) -> int:
    return int.from_bytes(hashlib.md5(data).digest()[:8], "big")


def series_shard_key(metric: str, tags: dict[str, str]) -> bytes:
    """The shard key of one series: the reference's salt input —
    metric + sorted tag pairs (``RowKey.prefixKeyWithSalt`` hashes the
    metric+tags portion of the row key). Sorted so ``{a:1,b:2}`` and
    ``{b:2,a:1}`` land on the same shard."""
    parts = [metric]
    for k in sorted(tags):
        parts.append(f"{k}={tags[k]}")
    return "\x00".join(parts).encode("utf-8", "surrogatepass")


class HashRing:
    """Consistent-hash ring over named shards with ``vnodes`` virtual
    points per shard (more vnodes = smoother key distribution)."""

    def __init__(self, names: list[str], vnodes: int = 64):
        if not names:
            raise ValueError("hash ring needs at least one shard")
        self.names = list(names)
        self.vnodes = max(int(vnodes), 1)
        points: list[tuple[int, str]] = []
        for name in self.names:
            for i in range(self.vnodes):
                points.append((_hash64(f"{name}#{i}".encode()), name))
        points.sort()
        self._points = [p for p, _ in points]
        self._owners = [n for _, n in points]

    def shard_for_key(self, key: bytes) -> str:
        """Owning shard of one pre-computed series key."""
        h = _hash64(key)
        idx = bisect.bisect_right(self._points, h)
        if idx == len(self._points):
            idx = 0  # wrap: the ring is circular
        return self._owners[idx]

    def shard_for(self, metric: str, tags: dict[str, str]) -> str:
        return self.shard_for_key(series_shard_key(metric, tags))

    def distribution(self, keys) -> dict[str, int]:
        """Shard -> key count for a sample of keys (tests/ops)."""
        out = {n: 0 for n in self.names}
        for key in keys:
            out[self.shard_for_key(key)] += 1
        return out
