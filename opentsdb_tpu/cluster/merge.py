"""Scatter-gather partial merging.

A SERIES lives wholly on one shard (the hash key is the series
identity), so everything per-series — downsampling, rate, counter
resets, interpolation fills — is computed exactly by the owning shard.
Only the cross-series *group aggregation* spans shards, and it merges
exactly when the aggregator decomposes, the same property the rollup
tiers rely on (``rollup/job.py``): sum/count partials add, min/max
partials min/max, and ``avg`` = merged sum / merged count (the
``RollupSpan`` sum+count qualifier trick lifted to the network).
Quantile shapes merge through DDSketches instead of refusing:
``percentiles`` sub-queries scatter as SKETCH PARTIALS (each shard
returns its per-(group, bucket) serialized sketches, the router
merges them — canonical sketch state is merge-order independent, so
the merged sketch is bit-equal to a single node folding every
shard's points — and extracts quantiles once), and the exact
percentile aggregators (p50..p999, median) scatter as ``none``
clones whose per-series downsampled values the router folds into
per-(group, bucket) sketches as legs arrive, never an average of
percentiles. ``dev`` and the estimated ``ep..r3/r7`` variants stay
a clean 400 — a sketch answers quantiles, not variance, and the
estimated variants promise a specific interpolation a sketch cannot
reproduce; a silently-wrong merge would be worse than no answer.

Timestamp grids: peers are queried with ``msResolution`` forced and an
ABSOLUTE window (the router resolves relative times once), so
downsampled sub-queries produce identical bucket timestamps on every
shard and partials align exactly. No-downsample (union-grid) queries
merge at the union of the shards' emitted timestamps — documented as
value-equal only when series don't need cross-shard interpolation
(each timestamp's merged value combines the shards that emitted it).

Group identity across shards is the group-by tag values: every member
of a group shares them, so each shard's partial carries them in its
``tags`` map. SpanGroup tag semantics compose across partials the
same way they compose across series: common tags survive only where
every partial agrees, keys that differ (or were already aggregated on
some shard) become ``aggregateTags``, keys missing from a partial's
tags+aggregateTags vanish.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable

from opentsdb_tpu.query.model import BadRequestError


def _add(a: float, b: float) -> float:
    return a + b


def _min(a: float, b: float) -> float:
    return a if a <= b else b


def _max(a: float, b: float) -> float:
    return a if a >= b else b


# group aggregators whose per-shard partials merge exactly; count
# partials are per-shard COUNTS, so they add
_COMBINE: dict[str, Callable[[float, float], float]] = {
    "sum": _add, "zimsum": _add, "pfsum": _add, "count": _add,
    "min": _min, "mimmin": _min, "max": _max, "mimmax": _max,
}


def sketch_agg_quantile(name: str) -> float | None:
    """The quantile a non-decomposable aggregator answers through the
    cross-shard sketch merge, or None. Exact percentile aggregators
    (p50..p999) and ``median`` qualify; the estimated ``ep..r3/r7``
    variants do not (they promise a specific rank interpolation) and
    ``dev`` is not a quantile at all."""
    if name == "median":
        return 50.0
    from opentsdb_tpu.ops import aggregators as aggs_mod
    if not aggs_mod.exists(name):
        return None
    agg = aggs_mod.get(name)
    if agg.is_percentile and agg.estimation == "legacy":
        return float(agg.percentile)
    return None


def decompose_plan(sub) -> str:
    """How one sub-query's partials merge across shards:
    ``"direct"`` (combine op exists), ``"concat"`` (emit-raw: groups
    are single series, no cross-shard combining), ``"avg"``
    (rewritten into sum+count twins), ``"sketch"`` (``percentiles``
    sub: shards return serialized per-bucket sketch partials), or
    ``"sketch_agg"`` (exact percentile aggregator: shards run a
    ``none`` clone, the router folds per-series values into
    sketches). Raises ``BadRequestError`` for aggregators that do
    not decompose."""
    if sub.percentiles:
        return "sketch"
    name = (sub.aggregator or "").lower()
    if name == "none":
        return "concat"
    if name in _COMBINE:
        return "direct"
    if name == "avg":
        return "avg"
    if sketch_agg_quantile(name) is not None:
        return "sketch_agg"
    raise BadRequestError(
        f"aggregator {sub.aggregator!r} does not decompose across "
        "shards (supported: sum, count, min, max, zimsum, mimmin, "
        "mimmax, avg, none, median, p50..p999)")


def group_key(result: dict, gb_keys: list[str]) -> tuple:
    """Cross-shard identity of one partial group."""
    tags = result.get("tags") or {}
    return (result.get("metric"),
            tuple((k, tags.get(k)) for k in gb_keys))


class MergedGroup:
    """One logical group being accumulated across shard partials."""

    __slots__ = ("metric", "tags", "agg_tags", "dps", "_cols",
                 "tsuids", "annotations", "global_annotations")

    def __init__(self, result: dict):
        self.metric = result.get("metric", "")
        self.tags = dict(result.get("tags") or {})
        self.agg_tags = set(result.get("aggregateTags") or ())
        self.dps: dict[int, float] = {}
        # lazy first-contribution columns (wire transport): series
        # never span shards, so most groups see exactly one leg —
        # its (ts, values) arrays pass straight through to the
        # QueryResult unless a second leg collides (then they
        # materialize into the dict fold)
        self._cols: tuple | None = None
        self.tsuids: list[str] = list(result.get("tsuids") or ())
        self.annotations: list[dict] = list(
            result.get("annotations") or ())
        self.global_annotations: list[dict] = list(
            result.get("globalAnnotations") or ())

    def fold_tags(self, result: dict) -> None:
        """SpanGroup semantics across partials (module docstring)."""
        r_tags = result.get("tags") or {}
        r_agg = set(result.get("aggregateTags") or ())
        new_tags: dict[str, str] = {}
        new_agg: set[str] = set()
        for k, v in self.tags.items():
            if k in r_tags:
                if r_tags[k] == v:
                    new_tags[k] = v
                else:
                    new_agg.add(k)
            elif k in r_agg:
                new_agg.add(k)
            # else: absent on some member of that shard -> vanishes
        for k in self.agg_tags:
            if k in r_tags or k in r_agg:
                new_agg.add(k)
        self.tags = new_tags
        self.agg_tags = new_agg
        self.tsuids.extend(result.get("tsuids") or ())
        self.annotations.extend(result.get("annotations") or ())
        self.global_annotations.extend(
            result.get("globalAnnotations") or ())

    def fold_dps(self, dps: Iterable, combine) -> None:
        """Combine one partial's ``[[ts, value], ...]`` rows. NaN is
        "this shard's members contributed nothing here" (fill-policy
        emission), so it is the combine identity; both sides NaN
        keeps the NaN — all members absent emits a gap, exactly what
        the single-node grid does."""
        if self._cols is not None:
            self._materialize()
        elif not self.dps:
            ts_col = getattr(dps, "ts", None)
            if ts_col is not None:
                self._cols = (ts_col, dps.values)
                return
        mine = self.dps
        for ts, val in dps:
            v = float(val)
            cur = mine.get(ts)
            if cur is None:
                mine[ts] = v
            elif math.isnan(cur):
                mine[ts] = v
            elif not math.isnan(v):
                mine[ts] = combine(cur, v)

    def _materialize(self) -> None:
        """Columnar first leg -> the dict fold (a second leg arrived
        for this group, or avg finishing needs keyed lookups). Values
        land exactly as the row-iteration path would have stored them
        (same f8 bits; ``float(int(v)) == v`` for masked ints)."""
        if self._cols is None:
            return
        ts_col, vals = self._cols
        self._cols = None
        mine = self.dps
        for t, v in zip(ts_col.tolist(), vals.tolist()):
            mine[t] = v

    def to_query_result(self, sub_index: int):
        import numpy as np

        from opentsdb_tpu.query.engine import QueryResult
        if self._cols is not None:
            # single-leg group: the engine's grid is already
            # ts-ascending — pass the columns through untouched
            ts_arr = np.asarray(self._cols[0], dtype=np.int64)
            vals = np.asarray(self._cols[1], dtype=np.float64)
            return QueryResult(
                metric=self.metric, tags=self.tags,
                aggregated_tags=sorted(self.agg_tags),
                tsuids=self.tsuids,
                annotations=_to_annotations(self.annotations),
                global_annotations=_to_annotations(
                    self.global_annotations),
                sub_query_index=sub_index,
                dps_arrays=(ts_arr, vals))
        ts_sorted = sorted(self.dps)
        ts_arr = np.asarray(ts_sorted, dtype=np.int64)
        vals = np.asarray([self.dps[t] for t in ts_sorted],
                          dtype=np.float64)
        return QueryResult(
            metric=self.metric, tags=self.tags,
            aggregated_tags=sorted(self.agg_tags),
            tsuids=self.tsuids,
            annotations=_to_annotations(self.annotations),
            global_annotations=_to_annotations(
                self.global_annotations),
            sub_query_index=sub_index,
            dps_arrays=(ts_arr, vals))


def _to_annotations(docs: list[dict]) -> list:
    """Peer-JSON annotation docs -> Annotation objects, deduped on
    (tsuid, start) so overlapping global ranges don't double-emit."""
    if not docs:
        return []
    from opentsdb_tpu.meta.annotation import Annotation
    seen: set[tuple] = set()
    out = []
    for doc in docs:
        note = Annotation.from_json(doc)
        key = (note.tsuid, note.start_time)
        if key in seen:
            continue
        seen.add(key)
        out.append(note)
    return out


def merge_partials(peer_results: list[list[dict]], gb_keys: list[str],
                   combine) -> dict[tuple, MergedGroup]:
    """Fold every shard's partial groups into merged groups keyed by
    cross-shard group identity. Insertion order follows the first
    shard that reported each group (then ring order), stable for
    tests."""
    groups: dict[tuple, MergedGroup] = {}
    for results in peer_results:
        for r in results:
            key = group_key(r, gb_keys)
            g = groups.get(key)
            if g is None:
                g = groups[key] = MergedGroup(r)
            else:
                g.fold_tags(r)
            g.fold_dps(r.get("dps") or (), combine)
    return groups


def merge_direct(peer_results: list[list[dict]], sub,
                 gb_keys: list[str]) -> list:
    combine = _COMBINE[(sub.aggregator or "").lower()]
    groups = merge_partials(peer_results, gb_keys, combine)
    return [g.to_query_result(sub.index) for g in groups.values()]


def merge_concat(peer_results: list[list[dict]], sub) -> list:
    """Emit-raw ("none" aggregator): every partial is one whole series
    (series never span shards) — concatenate, no combining."""
    out = []
    for results in peer_results:
        for r in results:
            g = MergedGroup(r)
            g.fold_dps(r.get("dps") or (), _add)
            out.append(g.to_query_result(sub.index))
    return out


def _avg_results(sums: dict[tuple, MergedGroup],
                 counts: dict[tuple, MergedGroup], sub) -> list:
    """Finish an ``avg`` merge from its folded sum+count twins:
    merged group sums / merged group counts (the rollup-tier avg
    decomposition; engine ``_avg_rollup_pipeline`` is the
    storage-side twin)."""
    out = []
    for key, gs in sums.items():
        gc = counts.get(key)
        if gc is None:
            continue
        gs._materialize()  # keyed lookups need the dict form
        gc._materialize()
        dps: dict[int, float] = {}
        for ts, s in gs.dps.items():
            c = gc.dps.get(ts)
            if c is None or math.isnan(c) or c == 0:
                if math.isnan(s):
                    dps[ts] = s  # all-absent gap survives
                continue
            dps[ts] = s / c
        gs.dps = dps
        out.append(gs.to_query_result(sub.index))
    return out


def merge_avg(sum_peer_results: list[list[dict]],
              count_peer_results: list[list[dict]], sub,
              gb_keys: list[str]) -> list:
    """``avg`` across shards (see :func:`_avg_results`)."""
    sums = merge_partials(sum_peer_results, gb_keys, _add)
    counts = merge_partials(count_peer_results, gb_keys, _add)
    return _avg_results(sums, counts, sub)


def merge_sub(sub, gb_keys: list[str], plan: str,
              primary: list[list[dict]],
              secondary: list[list[dict]] | None = None) -> list:
    if plan == "concat":
        return merge_concat(primary, sub)
    if plan == "avg":
        return merge_avg(primary, secondary or [], sub, gb_keys)
    return merge_direct(primary, sub, gb_keys)


def gb_tag_keys(sub) -> list[str]:
    """The group-by tag keys of one sub-query, sorted — the engine
    groups on exactly this set (``QueryEngine._run_sub``)."""
    return sorted({f.tagk for f in sub.filters if f.group_by})


class StreamMerger:
    """Incremental scatter merge: fold each shard's partial grids the
    moment its leg COMPLETES instead of gather-then-merge, so router
    merge work overlaps the slow shards' network time (the wire
    transport additionally decodes each leg's grids as frames arrive).

    Equivalence with the batch path (``merge_sub`` over a gathered
    ``partials`` list) is exact by construction: the same dict-fold
    ``MergedGroup`` machinery runs over the same rows in the same
    order — leg arrival order here IS the partials-list append order
    there, group insertion order follows the first leg reporting each
    group, and every pairwise float combine happens in the identical
    sequence. That bit-identity (against the single-node oracle) is
    why this stays a dict fold rather than a vectorized scatter.

    A leg must be COMPLETE and SUCCESSFUL before :meth:`add_leg` —
    partial folding of a leg that later dies would poison the
    accumulators, and ``avg``'s sum+count twins must land together."""

    def __init__(self, subs, plans: list[str],
                 slots: list[tuple[int, int | None]],
                 sketch_alpha: float | None = None):
        self.subs = list(subs)
        self.plans = plans
        self.slots = slots
        self.legs = 0  # completed legs folded (incl. empty 400 legs)
        # expanded-sub index -> accumulator: a list for concat subs
        # (every partial row is one whole series), a key->MergedGroup
        # dict for folding subs
        self._concat: dict[int, list[MergedGroup]] = {}
        self._folded: dict[int, dict[tuple, MergedGroup]] = {}
        self._combine: dict[int, Callable[[float, float], float]] = {}
        self._gbk: dict[int, list[str]] = {}
        # sketch plans: group identity (tags fold) lives in
        # _sk_groups, the per-(group, bucket) quantile state in
        # _sk_cells. "sketch_agg" subs additionally record the
        # quantile their aggregator names (_sk_q) — their legs carry
        # plain per-series dps that the router folds itself, with
        # sketch_alpha as the relative-error bound (router config;
        # "sketch" legs carry sketches built at the SHARD's alpha).
        self._sk_groups: dict[int, dict[tuple, MergedGroup]] = {}
        self._sk_cells: dict[int, dict[tuple, dict]] = {}
        self._sk_q: dict[int, float] = {}
        self._sk_alpha = sketch_alpha
        for sub, plan, (p_idx, s_idx) in zip(self.subs, plans, slots):
            gbk = gb_tag_keys(sub)
            if plan == "concat":
                self._concat[p_idx] = []
            elif plan == "avg":
                # sum+count twins both fold with _add
                for idx in (p_idx, s_idx):
                    self._folded[idx] = {}
                    self._combine[idx] = _add
                    self._gbk[idx] = gbk
            elif plan in ("sketch", "sketch_agg"):
                self._sk_groups[p_idx] = {}
                self._sk_cells[p_idx] = {}
                self._gbk[p_idx] = gbk
                if plan == "sketch_agg":
                    q = sketch_agg_quantile(
                        (sub.aggregator or "").lower())
                    self._sk_q[p_idx] = q if q is not None else 50.0
            else:
                self._folded[p_idx] = {}
                self._combine[p_idx] = \
                    _COMBINE[(sub.aggregator or "").lower()]
                self._gbk[p_idx] = gbk

    def add_leg(self, rows: list[dict]) -> None:
        """Fold one shard's complete partial list (``showQuery`` rows:
        each names its expanded sub index)."""
        self.legs += 1
        for r in rows:
            idx = (r.get("query") or {}).get("index")
            if idx in self._sk_cells:
                self._fold_sketch_row(idx, r)
                continue
            folded = self._folded.get(idx)
            if folded is not None:
                key = group_key(r, self._gbk[idx])
                g = folded.get(key)
                if g is None:
                    g = folded[key] = MergedGroup(r)
                else:
                    g.fold_tags(r)
                g.fold_dps(r.get("dps") or (), self._combine[idx])
                continue
            concat = self._concat.get(idx)
            if concat is not None:
                g = MergedGroup(r)
                g.fold_dps(r.get("dps") or (), _add)
                concat.append(g)
            # else: a row naming no known sub index — dropped, exactly
            # as the batch path's _sub_results filter dropped it

    def _fold_sketch_row(self, idx: int, r: dict) -> None:
        """One sketch-plan partial row. ``"sketch"`` rows carry
        ``sketchDps`` ([[bucket_ts, b64 sketch], ...]) — merge each
        bucket's sketch into the group's accumulator (canonical state
        makes the merge order-independent). ``"sketch_agg"`` rows are
        one whole series' downsampled values (``none`` clone) — fold
        each value into the (group, bucket) sketch; NaN is the
        fill-policy "no data here" emission and is skipped, matching
        the single-node percentile reduction's missing-value mask."""
        from opentsdb_tpu.sketch.ddsketch import (DDSketch,
                                                  SketchError)
        key = group_key(r, self._gbk[idx])
        groups = self._sk_groups[idx]
        g = groups.get(key)
        if g is None:
            groups[key] = MergedGroup(r)
        else:
            g.fold_tags(r)
        cells = self._sk_cells[idx].setdefault(key, {})
        if idx in self._sk_q:
            alpha = self._sk_alpha
            for ts, val in (r.get("dps") or ()):
                v = float(val)
                if math.isnan(v):
                    continue
                sk = cells.get(ts)
                if sk is None:
                    sk = cells[ts] = DDSketch(alpha) \
                        if alpha is not None else DDSketch()
                sk.add(v)
            return
        for ts, blob in (r.get("sketchDps") or ()):
            try:
                sk = DDSketch.from_b64(blob) if isinstance(blob, str) \
                    else DDSketch.from_bytes(blob)
            except (SketchError, ValueError):
                continue  # undecodable partial: serve the rest
            cur = cells.get(int(ts))
            if cur is None:
                cells[int(ts)] = sk
            else:
                try:
                    cur.merge(sk)
                except SketchError:
                    pass  # alpha mismatch across shards: config skew

    def _sketch_results(self, sub, plan: str, p_idx: int) -> list:
        """Extract quantiles from the folded sketch state. "sketch"
        emits the single-node percentile row shape (one row per
        (group, q), metric suffixed ``_pct_{q}``); "sketch_agg" emits
        one row per group under the base metric, its aggregator's
        quantile per bucket. Bucket timestamps stay in ms — the
        serializer applies the client's second-vs-ms convention, the
        same way every other merged plan's rows are emitted."""
        out = []
        for key, g in self._sk_groups[p_idx].items():
            cells = self._sk_cells[p_idx].get(key) or {}
            slots = sorted((t, sk) for t, sk in cells.items()
                           if sk.count)
            if not slots:
                continue
            base = g.metric
            if plan == "sketch_agg":
                g.dps = {t: float(sk.quantile(self._sk_q[p_idx]))
                         for t, sk in slots}
                out.append(g.to_query_result(sub.index))
                continue
            for q in (sub.percentiles or ()):
                g.metric = f"{base}_pct_{q:g}"
                g.dps = {t: float(sk.quantile(q)) for t, sk in slots}
                out.append(g.to_query_result(sub.index))
            g.metric = base
        return out

    def results(self) -> list:
        """Finish every sub's merge, in sub order."""
        out: list = []
        for sub, plan, (p_idx, s_idx) in zip(self.subs, self.plans,
                                             self.slots):
            if plan == "concat":
                out.extend(g.to_query_result(sub.index)
                           for g in self._concat[p_idx])
            elif plan == "avg":
                out.extend(_avg_results(self._folded[p_idx],
                                        self._folded[s_idx], sub))
            elif plan in ("sketch", "sketch_agg"):
                out.extend(self._sketch_results(sub, plan, p_idx))
            else:
                out.extend(g.to_query_result(sub.index)
                           for g in self._folded[p_idx].values())
        return out


__all__ = ["decompose_plan", "gb_tag_keys", "group_key",
           "merge_partials", "merge_sub", "MergedGroup",
           "sketch_agg_quantile", "StreamMerger"]
