"""Scatter-gather partial merging.

A SERIES lives wholly on one shard (the hash key is the series
identity), so everything per-series — downsampling, rate, counter
resets, interpolation fills — is computed exactly by the owning shard.
Only the cross-series *group aggregation* spans shards, and it merges
exactly when the aggregator decomposes, the same property the rollup
tiers rely on (``rollup/job.py``): sum/count partials add, min/max
partials min/max, and ``avg`` = merged sum / merged count (the
``RollupSpan`` sum+count qualifier trick lifted to the network).
Non-decomposable aggregators (dev, median, percentiles) are a clean
400 at the router — a silently-wrong merge would be worse than no
answer.

Timestamp grids: peers are queried with ``msResolution`` forced and an
ABSOLUTE window (the router resolves relative times once), so
downsampled sub-queries produce identical bucket timestamps on every
shard and partials align exactly. No-downsample (union-grid) queries
merge at the union of the shards' emitted timestamps — documented as
value-equal only when series don't need cross-shard interpolation
(each timestamp's merged value combines the shards that emitted it).

Group identity across shards is the group-by tag values: every member
of a group shares them, so each shard's partial carries them in its
``tags`` map. SpanGroup tag semantics compose across partials the
same way they compose across series: common tags survive only where
every partial agrees, keys that differ (or were already aggregated on
some shard) become ``aggregateTags``, keys missing from a partial's
tags+aggregateTags vanish.
"""

from __future__ import annotations

import math
from typing import Any, Callable, Iterable

from opentsdb_tpu.query.model import BadRequestError


def _add(a: float, b: float) -> float:
    return a + b


def _min(a: float, b: float) -> float:
    return a if a <= b else b


def _max(a: float, b: float) -> float:
    return a if a >= b else b


# group aggregators whose per-shard partials merge exactly; count
# partials are per-shard COUNTS, so they add
_COMBINE: dict[str, Callable[[float, float], float]] = {
    "sum": _add, "zimsum": _add, "pfsum": _add, "count": _add,
    "min": _min, "mimmin": _min, "max": _max, "mimmax": _max,
}


def decompose_plan(sub) -> str:
    """How one sub-query's partials merge across shards:
    ``"direct"`` (combine op exists), ``"concat"`` (emit-raw: groups
    are single series, no cross-shard combining), or ``"avg"``
    (rewritten into sum+count twins). Raises ``BadRequestError`` for
    aggregators that do not decompose."""
    if sub.percentiles:
        raise BadRequestError(
            "histogram percentile queries are not supported through "
            "a cluster router (mergeable sketches are ROADMAP item 2)")
    name = (sub.aggregator or "").lower()
    if name == "none":
        return "concat"
    if name in _COMBINE:
        return "direct"
    if name == "avg":
        return "avg"
    raise BadRequestError(
        f"aggregator {sub.aggregator!r} does not decompose across "
        "shards (supported: sum, count, min, max, zimsum, mimmin, "
        "mimmax, avg, none)")


def group_key(result: dict, gb_keys: list[str]) -> tuple:
    """Cross-shard identity of one partial group."""
    tags = result.get("tags") or {}
    return (result.get("metric"),
            tuple((k, tags.get(k)) for k in gb_keys))


class MergedGroup:
    """One logical group being accumulated across shard partials."""

    __slots__ = ("metric", "tags", "agg_tags", "dps", "tsuids",
                 "annotations", "global_annotations")

    def __init__(self, result: dict):
        self.metric = result.get("metric", "")
        self.tags = dict(result.get("tags") or {})
        self.agg_tags = set(result.get("aggregateTags") or ())
        self.dps: dict[int, float] = {}
        self.tsuids: list[str] = list(result.get("tsuids") or ())
        self.annotations: list[dict] = list(
            result.get("annotations") or ())
        self.global_annotations: list[dict] = list(
            result.get("globalAnnotations") or ())

    def fold_tags(self, result: dict) -> None:
        """SpanGroup semantics across partials (module docstring)."""
        r_tags = result.get("tags") or {}
        r_agg = set(result.get("aggregateTags") or ())
        new_tags: dict[str, str] = {}
        new_agg: set[str] = set()
        for k, v in self.tags.items():
            if k in r_tags:
                if r_tags[k] == v:
                    new_tags[k] = v
                else:
                    new_agg.add(k)
            elif k in r_agg:
                new_agg.add(k)
            # else: absent on some member of that shard -> vanishes
        for k in self.agg_tags:
            if k in r_tags or k in r_agg:
                new_agg.add(k)
        self.tags = new_tags
        self.agg_tags = new_agg
        self.tsuids.extend(result.get("tsuids") or ())
        self.annotations.extend(result.get("annotations") or ())
        self.global_annotations.extend(
            result.get("globalAnnotations") or ())

    def fold_dps(self, dps: Iterable, combine) -> None:
        """Combine one partial's ``[[ts, value], ...]`` rows. NaN is
        "this shard's members contributed nothing here" (fill-policy
        emission), so it is the combine identity; both sides NaN
        keeps the NaN — all members absent emits a gap, exactly what
        the single-node grid does."""
        mine = self.dps
        for ts, val in dps:
            v = float(val)
            cur = mine.get(ts)
            if cur is None:
                mine[ts] = v
            elif math.isnan(cur):
                mine[ts] = v
            elif not math.isnan(v):
                mine[ts] = combine(cur, v)

    def to_query_result(self, sub_index: int):
        import numpy as np

        from opentsdb_tpu.query.engine import QueryResult
        ts_sorted = sorted(self.dps)
        ts_arr = np.asarray(ts_sorted, dtype=np.int64)
        vals = np.asarray([self.dps[t] for t in ts_sorted],
                          dtype=np.float64)
        return QueryResult(
            metric=self.metric, tags=self.tags,
            aggregated_tags=sorted(self.agg_tags),
            tsuids=self.tsuids,
            annotations=_to_annotations(self.annotations),
            global_annotations=_to_annotations(
                self.global_annotations),
            sub_query_index=sub_index,
            dps_arrays=(ts_arr, vals))


def _to_annotations(docs: list[dict]) -> list:
    """Peer-JSON annotation docs -> Annotation objects, deduped on
    (tsuid, start) so overlapping global ranges don't double-emit."""
    if not docs:
        return []
    from opentsdb_tpu.meta.annotation import Annotation
    seen: set[tuple] = set()
    out = []
    for doc in docs:
        note = Annotation.from_json(doc)
        key = (note.tsuid, note.start_time)
        if key in seen:
            continue
        seen.add(key)
        out.append(note)
    return out


def merge_partials(peer_results: list[list[dict]], gb_keys: list[str],
                   combine) -> dict[tuple, MergedGroup]:
    """Fold every shard's partial groups into merged groups keyed by
    cross-shard group identity. Insertion order follows the first
    shard that reported each group (then ring order), stable for
    tests."""
    groups: dict[tuple, MergedGroup] = {}
    for results in peer_results:
        for r in results:
            key = group_key(r, gb_keys)
            g = groups.get(key)
            if g is None:
                g = groups[key] = MergedGroup(r)
            else:
                g.fold_tags(r)
            g.fold_dps(r.get("dps") or (), combine)
    return groups


def merge_direct(peer_results: list[list[dict]], sub,
                 gb_keys: list[str]) -> list:
    combine = _COMBINE[(sub.aggregator or "").lower()]
    groups = merge_partials(peer_results, gb_keys, combine)
    return [g.to_query_result(sub.index) for g in groups.values()]


def merge_concat(peer_results: list[list[dict]], sub) -> list:
    """Emit-raw ("none" aggregator): every partial is one whole series
    (series never span shards) — concatenate, no combining."""
    out = []
    for results in peer_results:
        for r in results:
            g = MergedGroup(r)
            g.fold_dps(r.get("dps") or (), _add)
            out.append(g.to_query_result(sub.index))
    return out


def merge_avg(sum_peer_results: list[list[dict]],
              count_peer_results: list[list[dict]], sub,
              gb_keys: list[str]) -> list:
    """``avg`` across shards: merged group sums / merged group counts
    (the rollup-tier avg decomposition; engine
    ``_avg_rollup_pipeline`` is the storage-side twin)."""
    sums = merge_partials(sum_peer_results, gb_keys, _add)
    counts = merge_partials(count_peer_results, gb_keys, _add)
    out = []
    for key, gs in sums.items():
        gc = counts.get(key)
        if gc is None:
            continue
        dps: dict[int, float] = {}
        for ts, s in gs.dps.items():
            c = gc.dps.get(ts)
            if c is None or math.isnan(c) or c == 0:
                if math.isnan(s):
                    dps[ts] = s  # all-absent gap survives
                continue
            dps[ts] = s / c
        gs.dps = dps
        out.append(gs.to_query_result(sub.index))
    return out


def merge_sub(sub, gb_keys: list[str], plan: str,
              primary: list[list[dict]],
              secondary: list[list[dict]] | None = None) -> list:
    if plan == "concat":
        return merge_concat(primary, sub)
    if plan == "avg":
        return merge_avg(primary, secondary or [], sub, gb_keys)
    return merge_direct(primary, sub, gb_keys)


def gb_tag_keys(sub) -> list[str]:
    """The group-by tag keys of one sub-query, sorted — the engine
    groups on exactly this set (``QueryEngine._run_sub``)."""
    return sorted({f.tagk for f in sub.filters if f.group_by})


__all__ = ["decompose_plan", "gb_tag_keys", "group_key",
           "merge_partials", "merge_sub", "MergedGroup"]
