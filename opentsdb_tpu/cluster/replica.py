"""Replica selection + anti-entropy for the replicated cluster tier.

Two halves of the RF ≥ 2 story live here, shared by the router (which
*builds* read plans) and the shard engine (which *applies* them):

**Replica selection** (``replicaSel``). With replication, a plain
scatter would double-count: every series exists on RF shards, and each
shard's group partial folds every series it holds. The router instead
assigns each distinct ordered replica set (:meth:`HashRing.
replica_sets`) to exactly ONE member and sends that member the
assignment inside the query body::

    "replicaSel": {"peers": [...], "vnodes": 64, "rf": 2,
                   "sets": [["s0", "s1"], ["s2", "s0"]]}

The shard rebuilds the same ring (``peers``/``vnodes`` pin it — MD5
hashing makes it identical across processes), computes each candidate
series' replica set, and keeps the series only when its set is among
the ones assigned to this request. Every series is therefore read
exactly once cluster-wide, and a failed reader's sets re-assign to the
next replica (the router's fallback rounds) without re-reading what
already answered.

**Anti-entropy** (:class:`DirtyTracker`). The durable spool already
replays every acked write to a returned peer — it IS the first line of
anti-entropy. What it cannot cover is the window where the spool
itself failed (append error, ``SpoolFull`` refusal after a replica
already stored the point, an in-memory spool lost to a router
restart): the replicas have then *diverged* — one holds points the
other will never receive. The tracker records a per-(peer, metric)
dirty-epoch (earliest wall-clock ms the divergence could have begun,
persisted next to the spool) and, when the peer returns, the router
re-reads the dirty window from a surviving replica and re-forwards
the healed peer's share (duplicates dedupe last-write-wins on the
shard, so repair is idempotent).
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Any, Iterable

from opentsdb_tpu.cluster.hashring import HashRing, series_shard_key
from opentsdb_tpu.query.model import BadRequestError

LOG = logging.getLogger("cluster.replica")

# process-wide ring memo: shards rebuild the router's ring from the
# replicaSel spec on every filtered query — construction hashes
# names x vnodes, so identical specs share one instance
_ring_lock = threading.Lock()
_ring_cache: dict[tuple, HashRing] = {}


def ring_for(peers: Iterable[str], vnodes: int) -> HashRing:
    key = (tuple(peers), int(vnodes))
    with _ring_lock:
        ring = _ring_cache.get(key)
        if ring is None:
            if len(_ring_cache) > 64:
                # reshards retire specs; don't hoard dead rings
                _ring_cache.clear()
            ring = _ring_cache[key] = HashRing(list(key[0]), key[1])
        return ring


def sel_doc(peers: list[str], vnodes: int, rf: int,
            sets: Iterable[tuple[str, ...]],
            invert: bool = False) -> dict[str, Any]:
    """The wire form of one request's replica assignment.

    ``invert=True`` flips the mask: the shard keeps only series whose
    replica set is NOT among ``sets``. The one caller is the stale-
    copy retire pass — a delete scoped to "every series this shard no
    longer owns" (``sets`` = all tuples containing the shard), which
    no positive selector can express."""
    out = {"peers": list(peers), "vnodes": int(vnodes),
           "rf": int(rf), "sets": [list(t) for t in sets]}
    if invert:
        out["invert"] = True
    return out


def parse_sel(obj: Any) -> dict[str, Any] | None:
    """Validate a ``replicaSel`` body value (the shard side of the
    contract). Returns the normalized dict, or raises
    ``BadRequestError`` — a malformed selector must 400, not 500."""
    if obj is None:
        return None
    if not isinstance(obj, dict):
        raise BadRequestError("replicaSel must be an object")
    peers = obj.get("peers")
    sets = obj.get("sets")
    if not isinstance(peers, list) or not peers or not all(
            isinstance(p, str) and p for p in peers):
        raise BadRequestError(
            "replicaSel.peers must be a list of shard names")
    if not isinstance(sets, list) or not all(
            isinstance(t, list) and t and all(
                isinstance(n, str) for n in t) for t in sets):
        raise BadRequestError(
            "replicaSel.sets must be a list of shard-name lists")
    try:
        vnodes = int(obj.get("vnodes", 64))
        rf = int(obj.get("rf", 1))
    except (TypeError, ValueError):
        raise BadRequestError(
            "replicaSel.vnodes/rf must be integers") from None
    if rf < 1 or vnodes < 1:
        raise BadRequestError("replicaSel.vnodes/rf must be >= 1")
    unknown = {n for t in sets for n in t} - set(peers)
    if unknown:
        raise BadRequestError(
            f"replicaSel.sets name shards not in peers: "
            f"{sorted(unknown)}")
    return {"peers": [str(p) for p in peers], "vnodes": vnodes,
            "rf": rf, "sets": [tuple(t) for t in sets],
            "invert": bool(obj.get("invert", False))}


def sel_cache_key(sel: dict[str, Any] | None) -> tuple:
    """Canonical tuple of one selector for result-cache keys: two
    requests reading DIFFERENT replica assignments of the same query
    return different partials and must never share an entry."""
    if not sel:
        return ()
    return (tuple(sel["peers"]), sel["vnodes"], sel["rf"],
            bool(sel.get("invert")),
            tuple(sorted(tuple(t) for t in sel["sets"])))


def series_mask(sel: dict[str, Any], metric: str, series_tags,
                name_of_kid, name_of_vid):
    """Shard-side filter: which of this store's candidate series this
    request is assigned to read. ``series_tags`` yields one
    ``[(kid, vid), ...]`` list per series; the name resolvers map tag
    UID ints to strings (the ring hashes NAMES, the one spelling that
    is identical on every shard — UID ints are per-shard)."""
    ring = ring_for(sel["peers"], sel["vnodes"])
    assigned = {tuple(t) for t in sel["sets"]}
    rf = sel["rf"]
    want = not sel.get("invert", False)
    out = []
    for pairs in series_tags:
        tags = {name_of_kid(int(k)): name_of_vid(int(v))
                for k, v in pairs}
        key = series_shard_key(metric, tags)
        out.append((ring.shards_for_key(key, rf) in assigned)
                   is want)
    return out


class DirtyTracker:
    """Per-(peer, metric) divergence windows, persisted as one JSON
    sidecar per router (``<dir>/replica_dirty.json``). An entry maps
    ``peer -> metric -> earliest-dirty wall-clock ms``; repair reads
    the surviving replica from that stamp forward (minus a safety
    margin) and clears the entry on success."""

    def __init__(self, directory: str | None):
        self._lock = threading.Lock()
        self._dirty: dict[str, dict[str, int]] = {}
        self.path = os.path.join(directory, "replica_dirty.json") \
            if directory else ""
        self.marks = 0
        if self.path:
            try:
                with open(self.path, "r", encoding="utf-8") as fh:
                    doc = json.load(fh)
                if isinstance(doc, dict):
                    self._dirty = {
                        str(p): {str(m): int(s)
                                 for m, s in v.items()}
                        for p, v in doc.items()
                        if isinstance(v, dict)}
            except (OSError, ValueError):
                self._dirty = {}

    def _save_locked(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(self._dirty, fh)
                fh.flush()
                # tsdlint: allow[lock-blocking] the dirty mark must be
                # durable before the divergence window it names can be
                # forgotten; the lock serializes mark-vs-clear and the
                # doc is a few KB
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError:  # pragma: no cover - disk trouble
            LOG.exception("cannot persist dirty marks to %s",
                          self.path)

    def mark(self, peer: str, metrics: Iterable[str],
             since_ms: int) -> None:
        """Record that ``peer`` may be missing writes of ``metrics``
        from ``since_ms`` on (earliest stamp wins)."""
        with self._lock:
            per = self._dirty.setdefault(peer, {})
            changed = False
            for m in metrics:
                cur = per.get(m)
                if cur is None or since_ms < cur:
                    per[m] = int(since_ms)
                    changed = True
            if changed:
                self.marks += 1
                self._save_locked()

    def peek(self, peer: str) -> dict[str, int]:
        with self._lock:
            return dict(self._dirty.get(peer, ()))

    def clear(self, peer: str, metrics: Iterable[str] | None = None
              ) -> None:
        with self._lock:
            per = self._dirty.get(peer)
            if per is None:
                return
            if metrics is None:
                per.clear()
            else:
                for m in metrics:
                    per.pop(m, None)
            if not per:
                self._dirty.pop(peer, None)
            self._save_locked()

    def drop_peer(self, peer: str) -> None:
        """A peer left the ring (reshard finalize): its debt is void."""
        with self._lock:
            if self._dirty.pop(peer, None) is not None:
                self._save_locked()

    @property
    def total_entries(self) -> int:
        with self._lock:
            return sum(len(v) for v in self._dirty.values())

    def age_info(self, peer: str, now_ms: int | None = None
                 ) -> dict[str, Any]:
        """This peer's divergence-debt AGE: the oldest unpaired dirty
        epoch as a staleness gauge. A week-old divergence and a
        seconds-old blip carry the same entry COUNT — the age is what
        distinguishes "anti-entropy is keeping up" from "this replica
        has silently diverged for days"."""
        now = int(now_ms if now_ms is not None
                  else time.time() * 1000)
        with self._lock:
            per = self._dirty.get(peer) or {}
            oldest = min(per.values()) if per else 0
            return {
                "entries": len(per),
                "oldest_ms": oldest,
                "age_s": round(max(now - oldest, 0) / 1000.0, 1)
                if oldest else 0.0,
            }

    def health_info(self) -> dict[str, Any]:
        now_ms = int(time.time() * 1000)
        with self._lock:
            peers = sorted(self._dirty)
            entries = sum(len(v) for v in self._dirty.values())
            marks = self.marks
        ages = {p: self.age_info(p, now_ms) for p in peers}
        return {
            "entries": entries,
            "peers": peers,
            "marks": marks,
            # per-peer staleness: oldest unpaired dirty epoch + age
            "ages": ages,
            "oldest_age_s": max(
                (a["age_s"] for a in ages.values()), default=0.0),
        }


class ReadRepairQueue:
    """Query-path read-repair staging: divergence observed BY A READ
    (a fallback round re-read a failed reader's sets; replica answers
    disagreed about a metric's existence) enqueues here, and the
    router's replay loop drains entries into :class:`DirtyTracker` /
    ``maybe_repair`` off the read path — ``DirtyTracker.mark`` fsyncs
    under its lock, which a serve path must never wait on.

    Bounded and dedicated to staging, not truth: the queue dedupes on
    (peer, metric) keeping the EARLIEST suspicion stamp, sheds-and-
    counts past ``max_pending`` (a shed entry is a lost repair hint,
    not a lost write — the next read of the same divergence re-
    enqueues), and tracks drained-but-unrepaired keys in an inflight
    set so ``oldest_pending_age_s`` spans the whole mark→repair
    pipeline, not just the staging dict. False-positive enqueues are
    harmless: repair is idempotent and a clean window clears to a
    no-op."""

    def __init__(self, max_pending: int = 1024):
        self._lock = threading.Lock()
        self.max_pending = max(int(max_pending), 1)
        # (peer, metric) -> (since_ms, enqueued_monotonic)
        self._pending: dict[tuple[str, str], tuple[int, float]] = {}
        # drained into the DirtyTracker but not yet repaired
        self._inflight: dict[tuple[str, str], float] = {}
        self.enqueued = 0
        self.shed = 0
        self.completed = 0

    def enqueue(self, peer: str, metrics: Iterable[str],
                since_ms: int) -> int:
        """Stage suspicion windows; returns how many were accepted
        (the rest shed). Lock-cheap: dict ops only, no IO."""
        accepted = 0
        now = time.monotonic()
        with self._lock:
            for m in metrics:
                key = (peer, m)
                cur = self._pending.get(key)
                if cur is not None:
                    if since_ms < cur[0]:
                        self._pending[key] = (int(since_ms), cur[1])
                    continue
                if key in self._inflight:
                    continue  # already marked; repair will cover it
                if len(self._pending) >= self.max_pending:
                    self.shed += 1
                    continue
                self._pending[key] = (int(since_ms), now)
                self.enqueued += 1
                accepted += 1
        return accepted

    def drain(self) -> list[tuple[str, str, int]]:
        """Move every staged entry to inflight and return
        ``[(peer, metric, since_ms), ...]`` for the caller to mark
        dirty (off the read path)."""
        with self._lock:
            out = [(p, m, s) for (p, m), (s, _) in
                   self._pending.items()]
            for key, (_, stamp) in self._pending.items():
                self._inflight.setdefault(key, stamp)
            self._pending.clear()
        return out

    def note_repaired(self, peer: str, metrics: Iterable[str]
                      ) -> None:
        """The repair pass cleared these dirty windows — retire their
        inflight stamps and count completions."""
        with self._lock:
            for m in metrics:
                if self._inflight.pop((peer, m), None) is not None:
                    self.completed += 1

    def drop_peer(self, peer: str) -> None:
        """The peer left the ring; its staged/inflight debt is void."""
        with self._lock:
            for d in (self._pending, self._inflight):
                for key in [k for k in d if k[0] == peer]:
                    del d[key]

    def oldest_pending_age_s(self) -> float:
        now = time.monotonic()
        with self._lock:
            stamps = [t for _, t in self._pending.values()]
            stamps.extend(self._inflight.values())
        if not stamps:
            return 0.0
        return round(max(now - min(stamps), 0.0), 1)

    def health_info(self) -> dict[str, Any]:
        with self._lock:
            depth = len(self._pending)
            inflight = len(self._inflight)
            enqueued, shed, completed = \
                self.enqueued, self.shed, self.completed
        return {
            "depth": depth,
            "inflight": inflight,
            "enqueued": enqueued,
            "shed": shed,
            "completed": completed,
            "oldest_pending_age_s": self.oldest_pending_age_s(),
        }


__all__ = ["DirtyTracker", "ReadRepairQueue", "parse_sel",
           "ring_for", "sel_cache_key", "sel_doc", "series_mask"]
