"""Online resharding: ring-change epochs, dual-write cutover, backfill.

``POST /api/cluster/reshard`` installs a NEW consistent-hash ring at a
fenced epoch. The cutover protocol is the classic live-migration
triple, chosen so the existing scatter/merge machinery stays exactly
correct (no point is ever double-counted, no acked point is ever
lost):

1. **Dual-write.** While the window is open every accepted point is
   delivered to the union of its OLD-ring and NEW-ring replica sets
   (unmoved series: same set, zero extra cost). Unreachable owners
   spool durably exactly like steady-state writes.
2. **Read-old.** Reads keep scattering over the OLD ring: its owners
   hold complete history *and* (via dual-write) every in-window
   write, so answers are complete without cross-ring merging — the
   one shape where merging two copies of a moved series could
   double-sum.
3. **Backfill.** A background pass streams moved keyspace from old
   owners to their new owners through the normal forward/spool path
   (duplicates dedupe last-write-wins on the shard). Progress is
   persisted per (old shard, metric) next to the spool, so a router
   killed mid-reshard resumes where it stopped instead of restarting
   the copy.

When every (old shard, metric) unit is marked done the epoch
**finalizes**: reads and writes flip to the new ring, shards that
left the ring are dropped (their spools closed — dual-write already
placed everything they were owed on the new owners), and the epoch
survives in ``reshard.json`` so result-cache versions stay
epoch-qualified across restarts.
"""

from __future__ import annotations

import json
import logging
import math
import os
import threading
import time
from typing import Any

LOG = logging.getLogger("cluster.reshard")

#: backfill/repair read-window end: far enough past the fence that
#: points written with future timestamps (forecast/capacity series)
#: still move — both copy paths share it so neither silently drops a
#: horizon the other covers
HORIZON_MS = 10 * 366 * 24 * 3600 * 1000


class ReshardState:
    """Persisted cluster-topology state (``<dir>/reshard.json``): the
    installed ring epoch, the current peer spec (overrides config
    after a finalized reshard — config still names the boot-time
    ring), and during a cutover the old spec + fence + backfill
    done-markers."""

    FILE = "reshard.json"

    def __init__(self, directory: str | None):
        self._lock = threading.Lock()
        self.path = os.path.join(directory, self.FILE) \
            if directory else ""
        self.epoch = 0
        self.peers_spec = ""     # "" = use tsd.cluster.peers
        self.vnodes = 0          # 0 = use tsd.cluster.vnodes
        self.old_spec = ""       # non-empty => cutover window open
        self.old_vnodes = 0
        self.fence_ms = 0
        # highest epoch whose stale-copy retire pass COMPLETED: while
        # retired_epoch < epoch (and no cutover is open), former
        # owners may still hold moved series that replicaSel hides —
        # the retire pass deletes them and then marks the epoch
        self.retired_epoch = 0
        # old-shard name -> metrics whose moved keyspace fully copied
        self.done: dict[str, list[str]] = {}
        if self.path:
            try:
                try:
                    fh = open(self.path, "r", encoding="utf-8")
                except FileNotFoundError:
                    return  # first boot: epoch 0, no cutover
                with fh:
                    doc = json.load(fh)
                self.epoch = int(doc.get("epoch", 0))
                self.peers_spec = str(doc.get("peers", "") or "")
                self.vnodes = int(doc.get("vnodes", 0) or 0)
                self.retired_epoch = int(
                    doc.get("retired_epoch", 0) or 0)
                rs = doc.get("reshard") or {}
                self.old_spec = str(rs.get("old_peers", "") or "")
                self.old_vnodes = int(rs.get("old_vnodes", 0) or 0)
                self.fence_ms = int(rs.get("fence_ms", 0) or 0)
                done = rs.get("done") or {}
                if isinstance(done, dict):
                    self.done = {str(k): [str(m) for m in v]
                                 for k, v in done.items()
                                 if isinstance(v, list)}
            except (OSError, ValueError):
                LOG.exception("cannot load reshard state %s; "
                              "starting at epoch 0", self.path)

    # -- persistence ---------------------------------------------------

    def _save_locked(self) -> None:
        if not self.path:
            return
        doc: dict[str, Any] = {"epoch": self.epoch,
                               "peers": self.peers_spec,
                               "vnodes": self.vnodes,
                               "retired_epoch": self.retired_epoch}
        if self.old_spec:
            doc["reshard"] = {"old_peers": self.old_spec,
                              "old_vnodes": self.old_vnodes,
                              "fence_ms": self.fence_ms,
                              "done": self.done}
        tmp = self.path + ".tmp"
        try:
            with open(tmp, "w", encoding="utf-8") as fh:
                json.dump(doc, fh)
                fh.flush()
                # tsdlint: allow[lock-blocking] the epoch fence and
                # backfill progress must be durable before the install
                # (or a done-marker) is acted on — kill-during-reshard
                # recovery hangs on this file; the doc is tiny
                os.fsync(fh.fileno())
            os.replace(tmp, self.path)
        except OSError:  # pragma: no cover - disk trouble
            LOG.exception("cannot persist reshard state to %s",
                          self.path)

    # -- transitions ---------------------------------------------------

    @property
    def active(self) -> bool:
        with self._lock:
            return bool(self.old_spec)

    def begin(self, new_spec: str, new_vnodes: int, old_spec: str,
              old_vnodes: int) -> int:
        """Open the cutover window; returns the new epoch."""
        with self._lock:
            self.epoch += 1
            self.peers_spec = new_spec
            self.vnodes = int(new_vnodes)
            self.old_spec = old_spec
            self.old_vnodes = int(old_vnodes)
            self.fence_ms = int(time.time() * 1000)
            self.done = {}
            self._save_locked()
            return self.epoch

    def finish(self) -> None:
        """Close the window: the new ring is the only ring."""
        with self._lock:
            self.old_spec = ""
            self.old_vnodes = 0
            self.fence_ms = 0
            self.done = {}
            self._save_locked()

    def adopt(self, epoch: int, new_spec: str, new_vnodes: int,
              old_spec: str, old_vnodes: int, fence_ms: int) -> bool:
        """Adopt a sibling router's OPEN cutover window at ``epoch``
        (gossip topology hand-off). Unlike :meth:`begin` the epoch and
        fence come from the initiator — every router must agree on
        them or their epoch-qualified caches diverge. Done-markers
        start empty: this router runs its own backfill (idempotent —
        duplicated copy units dedupe last-write-wins on the shards),
        which is exactly what lets a sibling resume a reshard whose
        initiator died mid-flight. Returns False when ``epoch`` is not
        ahead of the local one."""
        with self._lock:
            if epoch <= self.epoch:
                return False
            self.epoch = int(epoch)
            self.peers_spec = new_spec
            self.vnodes = int(new_vnodes)
            self.old_spec = old_spec
            self.old_vnodes = int(old_vnodes)
            self.fence_ms = int(fence_ms)
            self.done = {}
            self._save_locked()
            return True

    def adopt_final(self, epoch: int, spec: str, vnodes: int) -> bool:
        """Adopt a sibling's FINALIZED ring: either the close of this
        router's own open window at the same epoch, or a whole
        already-finalized epoch this router never saw begin. Returns
        False when nothing changed."""
        with self._lock:
            if epoch < self.epoch or (
                    epoch == self.epoch and not self.old_spec):
                return False
            self.epoch = int(epoch)
            self.peers_spec = spec
            self.vnodes = int(vnodes)
            self.old_spec = ""
            self.old_vnodes = 0
            self.fence_ms = 0
            self.done = {}
            self._save_locked()
            return True

    def mark_done(self, old_peer: str, metric: str) -> None:
        with self._lock:
            per = self.done.setdefault(old_peer, [])
            if metric not in per:
                per.append(metric)
                self._save_locked()

    def mark_retired(self, epoch: int) -> None:
        """The stale-copy retire pass that ran against ``epoch``
        finished: no former owner still holds a moved series.
        Compare-and-set on purpose — if a NEWER reshard began while
        the pass was finishing, stamping the current epoch would
        silently skip that epoch's reclaim forever; the stale mark is
        simply dropped and the re-armed pass covers the new epoch.
        Persisted so a router restart doesn't re-run a completed pass
        (re-running is harmless — the deletes match nothing — just
        wasted scans)."""
        with self._lock:
            if self.epoch == epoch and self.retired_epoch != epoch:
                self.retired_epoch = epoch
                self._save_locked()

    def reset_done(self) -> None:
        """Invalidate every done-marker: the responsibility snapshot
        changed (a shard was declared dead), so completed passes may
        have skipped series they must now claim. Re-copies are
        duplicates, and duplicates dedupe."""
        with self._lock:
            if self.done:
                self.done = {}
                self._save_locked()

    def is_done(self, old_peer: str, metric: str) -> bool:
        with self._lock:
            return metric in self.done.get(old_peer, ())

    def describe(self) -> dict[str, Any]:
        with self._lock:
            out: dict[str, Any] = {"epoch": self.epoch,
                                   "active": bool(self.old_spec),
                                   "retired_epoch": self.retired_epoch}
            if self.old_spec:
                out["fence_ms"] = self.fence_ms
                out["old_peers"] = self.old_spec
                out["new_peers"] = self.peers_spec
                out["backfilled_metrics"] = sum(
                    len(v) for v in self.done.values())
            return out


class Backfiller:
    """Streams moved keyspace old → new owners, one (old shard,
    metric) unit per :meth:`step` — small enough that kill-during-
    reshard loses at most one unit of progress (the unit re-copies on
    resume; duplicates dedupe on the shard)."""

    def __init__(self, router):
        self.router = router
        # per-old-peer metric lists, fetched lazily per cutover (NOT
        # persisted: a resumed backfill re-asks, so metrics created
        # moments before the kill are still enumerated)
        self._metrics: dict[str, list[str]] = {}
        # old shards declared DEAD for this cutover: the deterministic
        # responsibility snapshot (first old replica NOT in this set
        # copies a series). Entering the set resets every done-marker
        # — completed passes skipped series the dead shard was
        # responsible for and must re-claim them. Leaving it (the
        # shard answered again) needs no reset: its own units then
        # copy, and any double-claimed series dedupe.
        self.dead: set[str] = set()
        self._scanning = ""   # old shard whose pass is in flight
        self._moved_last = 0  # series moved by the last page
        self.backfilled_points = 0
        self.backfilled_series = 0
        self.failed_steps = 0

    def reset(self) -> None:
        self._metrics = {}
        self.dead = set()

    def _declare_dead(self, old_name: str) -> None:
        if old_name not in self.dead:
            self.dead.add(old_name)
            LOG.warning(
                "backfill: old shard %s is unreachable; its series "
                "re-assign to their surviving replicas (done-markers "
                "reset)", old_name)
            self.router.state.reset_done()

    def _revive(self, old_name: str) -> None:
        self.dead.discard(old_name)

    # -- enumeration ---------------------------------------------------

    def _metrics_of(self, old_name: str) -> list[str] | None:
        """This old shard's metric names (suggest with a huge max), or
        None while the shard can't answer (retry next pass)."""
        got = self._metrics.get(old_name)
        if got is not None:
            return got
        router = self.router
        peer = router.peers[old_name]
        try:
            status, data = router.fetch_guarded(
                peer, "GET", "/api/suggest?type=metrics&max=1000000")
            if status != 200:
                raise OSError(f"suggest answered {status}")
            names = json.loads(data)
            if not isinstance(names, list):
                raise OSError("suggest body is not a list")
        except (OSError, ValueError) as exc:
            LOG.info("backfill: cannot enumerate metrics on %s (%s)",
                     old_name, exc)
            return None
        got = sorted(str(n) for n in names)
        self._metrics[old_name] = got
        return got

    def next_unit(self) -> tuple[str, str] | None | str:
        """The next pending (old shard, metric) unit, ``"blocked"``
        when a remaining unit's shard is unreachable, or None when
        the backfill is complete.

        At RF >= 2 an unreachable old shard does NOT block: it is
        declared dead (resetting every done-marker, so completed
        passes re-run) and the deterministic responsibility rule in
        ``_copy_metric`` hands its series to their first surviving
        replica. Shrinking a ring to drop a dead shard — the
        canonical reason to shrink — therefore still finalizes. At
        RF = 1 the dead shard's series exist nowhere else, so the
        window stays open (visible via ``failed_steps`` and the
        reshard status) until it returns."""
        router = self.router
        state = router.state
        blocked = False
        for old_name in sorted(router.old_ring.names):
            metrics = self._metrics_of(old_name)
            if metrics is None:
                if router.rf > 1:
                    self._declare_dead(old_name)
                    continue
                blocked = True
                continue
            self._revive(old_name)
            for metric in metrics:
                if not state.is_done(old_name, metric):
                    return old_name, metric
        return "blocked" if blocked else None

    # -- one unit ------------------------------------------------------

    def step(self) -> dict[str, Any]:
        """Copy one (old shard, metric) unit's moved series. Returns a
        progress doc; ``phase`` is ``copied`` / ``blocked`` / ``done``.
        """
        router = self.router
        unit = self.next_unit()
        if unit is None:
            return {"phase": "done"}
        if unit == "blocked":
            return {"phase": "blocked"}
        old_name, metric = unit
        faults = getattr(router.tsdb, "faults", None)
        if faults is not None:
            faults.check("cluster.reshard")
        try:
            moved = self._copy_metric(old_name, metric)
        except (OSError, ValueError) as exc:
            self.failed_steps += 1
            peer = self.router.peers.get(old_name)
            if self.router.rf > 1 and peer is not None \
                    and peer.breaker.blocking():
                # the shard died mid-pass: drop its cached metric
                # list (revival requires a FRESH enumeration) and
                # hand its series to their surviving replicas
                self._metrics.pop(old_name, None)
                self._declare_dead(old_name)
            LOG.info("backfill of %r from %s failed (%s); will retry",
                     metric, old_name, exc)
            return {"phase": "blocked", "peer": old_name,
                    "metric": metric, "error": str(exc)}
        router.state.mark_done(old_name, metric)
        return {"phase": "copied", "peer": old_name, "metric": metric,
                "series": moved}

    def _copy_metric(self, old_name: str, metric: str) -> int:
        """Scan one old shard's series of one metric and forward the
        rows it is responsible for to their new owners. Raises on a
        transport failure (the unit stays pending)."""
        router = self.router
        state = router.state
        peer = router.peers[old_name]
        batch_size = router.backfill_batch
        self._scanning = old_name
        moved = 0
        per_target: dict[str, list[dict]] = {}

        def flush(target: str) -> None:
            dps = per_target.pop(target, None)
            if dps:
                router.deliver_backfill(router.peers[target], dps)

        # page-wise: scan_series_pages bisects on 413 (a scan-
        # budgeted shard refuses a whole history in one piece, and
        # without paging the copy would block forever) and each page
        # forwards before the next is fetched, so the router never
        # materializes a metric's whole history
        for rows in router.scan_series_pages(
                peer, metric, 1, state.fence_ms + HORIZON_MS):
            self._copy_rows(rows, metric, per_target, flush,
                            batch_size)
            moved += self._moved_last
        for target in list(per_target):
            flush(target)
        self.backfilled_series += moved
        return moved

    def _copy_rows(self, rows, metric, per_target, flush,
                   batch_size) -> None:
        router = self.router
        old_ring, new_ring = router.old_ring, router.ring
        rf = router.rf
        self._moved_last = 0
        for row in rows:
            tags = row.get("tags") or {}
            old_set = old_ring.shards_for(metric, tags, rf)
            # deterministic responsibility: the first old replica NOT
            # declared dead copies the series. The snapshot is the
            # sticky ``self.dead`` set — never the racy instantaneous
            # breaker state, which could let two passes EACH believe
            # the other was responsible and mark their units done
            # with the series never copied. All replicas dead → no
            # source exists; the row waits for a revival.
            responsible = next(
                (n for n in old_set if n not in self.dead), None)
            if responsible != self._scanning:
                continue
            new_set = new_ring.shards_for(metric, tags, rf)
            targets = [n for n in new_set if n not in old_set]
            if not targets:
                continue
            self._moved_last += 1
            for ts, val in (row.get("dps") or ()):
                v = float(val)
                if math.isnan(v):
                    continue  # raw rows carry no fill; be defensive
                dp = {"metric": metric, "timestamp": int(ts),
                      "value": val, "tags": tags}
                self.backfilled_points += 1
                for target in targets:
                    per_target.setdefault(target, []).append(dp)
                    if len(per_target[target]) >= batch_size:
                        flush(target)

    def health_info(self) -> dict[str, Any]:
        return {
            "backfilled_series": self.backfilled_series,
            "backfilled_points": self.backfilled_points,
            "failed_steps": self.failed_steps,
            "dead_old_shards": sorted(self.dead),
        }

    def progress(self) -> dict[str, Any]:
        """Done-marker progress for /api/cluster/status:
        ``total_units`` counts (old shard, metric) units over the
        metric lists enumerated SO FAR (lists are fetched lazily per
        old shard, so early in a pass the total can still grow —
        ``total_known`` says whether every old shard has answered)."""
        state = self.router.state
        with state._lock:
            done_units = sum(len(v) for v in state.done.values())
        old_ring = self.router.old_ring
        names = list(old_ring.names) if old_ring is not None else []
        total = sum(len(self._metrics.get(n, ())) for n in names)
        return {
            "done_units": done_units,
            "total_units": total,
            "total_known": all(n in self._metrics for n in names),
        }


__all__ = ["Backfiller", "ReshardState"]
