"""Stale-copy retire pass: reclaim the bytes ``replicaSel`` hides.

A finalized reshard leaves MOVED series on their former owners:
backfill copies keyspace to the new owners, it never purges the old
ones — reads stay correct because every post-reshard scatter carries
a ``replicaSel`` that keeps only currently-assigned series, but the
stale copies' RAM/WAL/cold bytes linger forever (ROADMAP item 2(d)).

This pass deletes them with a small bounded background job on the
router, one ``(shard, metric)`` unit per step, the Backfiller's
shape. The delete itself is one query per unit with an **inverted**
replica selector::

    replicaSel = {peers, vnodes, rf,
                  sets: [every replica tuple containing this shard],
                  invert: true}

so the shard's engine keeps — and, with ``delete=true``, purges —
exactly the series whose current replica set does NOT include the
shard: the stale copies, and nothing else. No router-side series
enumeration, no per-series requests, and the ownership decision runs
on the shard with the same MD5 ring reads use, so retire can never
delete a series a read could still be assigned.

Lifecycle/safety rules:

- runs only while ``retired_epoch < epoch`` and NO cutover is open
  (during dual-write the "former owner" set is not final); a reshard
  finalize re-arms it for the new epoch;
- one unit per wake (``tsd.cluster.retire.interval_ms``), breaker-
  gated per peer like every dispatch, ``cluster.retire`` fault site,
  ``cluster.retire`` background trace root;
- an unreachable shard leaves its units pending — the pass retries on
  later wakes and only marks ``retired_epoch`` (persisted in
  ``reshard.json``) when EVERY unit completed, so a router restart
  resumes (idempotently — re-deletes match nothing) instead of
  forgetting;
- written under the PR-13 gates: the retire thread is joined by
  ``ClusterRouter.stop`` (thread-lifecycle pass), its per-pass state
  resets every epoch (unbounded-growth pass), and the cluster
  battery runs it under the thread/fd leak witness.
"""

from __future__ import annotations

import json
import logging
import time
from typing import Any

from opentsdb_tpu.cluster import replica as replica_mod
from opentsdb_tpu.cluster.reshard import HORIZON_MS

LOG = logging.getLogger("cluster.retire")


class RetireDisabled(Exception):
    """A shard refused the delete because ``tsd.http.query.
    allow_delete`` is off there — a config condition, not an outage:
    the pass parks (phase ``disabled``) instead of hammering the
    shard with doomed deletes every wake."""


class StaleCopyRetirer:
    """One (shard, metric) delete unit per :meth:`step`."""

    def __init__(self, router):
        self.router = router
        # per-pass state, reset() per epoch: pending metric lists per
        # shard (None = enumeration failed, retry) and finished units
        self._metrics: dict[str, list[str] | None] = {}
        self._done: set[tuple[str, str]] = set()
        self.retired_series = 0
        self.retire_queries = 0
        self.failed_steps = 0
        self.passes = 0

    def reset(self) -> None:
        """A new epoch finalized: the ownership map changed, every
        completed unit must re-check (re-deletes match nothing)."""
        self._metrics = {}
        self._done = set()

    # -- scheduling ----------------------------------------------------

    def pending(self) -> bool:
        """Whether stale copies may exist: a finalized epoch newer
        than the last completed retire pass, with no cutover open."""
        router = self.router
        return (router.old_ring is None
                and router.state.epoch > router.state.retired_epoch)

    # -- one unit ------------------------------------------------------

    def _metrics_of(self, name: str) -> list[str] | None:
        got = self._metrics.get(name)
        if got is not None:
            return got
        router = self.router
        peer = router.peers.get(name)
        if peer is None:
            return []
        try:
            status, data = router.fetch_guarded(
                peer, "GET", "/api/suggest?type=metrics&max=1000000")
            if status != 200:
                raise OSError(f"suggest answered {status}")
            names = json.loads(data)
            if not isinstance(names, list):
                raise OSError("suggest body is not a list")
        except (OSError, ValueError) as exc:
            LOG.info("retire: cannot enumerate metrics on %s (%s)",
                     name, exc)
            return None
        got = sorted(str(n) for n in names)
        self._metrics[name] = got
        return got

    def next_unit(self, ring) -> tuple[str, str] | None | str:
        """The next pending (shard, metric) unit, ``"blocked"`` while
        some shard cannot enumerate, or None when the pass is done."""
        blocked = False
        for name in sorted(ring.names):
            metrics = self._metrics_of(name)
            if metrics is None:
                blocked = True
                continue
            for metric in metrics:
                if (name, metric) not in self._done:
                    return name, metric
        return "blocked" if blocked else None

    def step(self) -> dict[str, Any]:
        """Retire one unit. Returns a progress doc; ``phase`` is
        ``retired`` / ``blocked`` / ``done`` / ``idle``.

        Racing an admin ``begin_reshard`` is the one hazard: a delete
        computed against the NEW ring during a cutover window could
        purge a moved series from its only pre-backfill holder. The
        ring is therefore SNAPSHOT before the cutover check —
        ``begin_reshard`` stores ``old_ring`` before swapping
        ``ring`` (its documented write order), so a ring read that
        still sees ``old_ring is None`` afterwards is provably the
        pre-install ring; a delete built against it only ever names
        copies that were already stale (and replicaSel-hidden) at
        that epoch. The completion mark is epoch-CAS'd for the same
        race (see ``ReshardState.mark_retired``)."""
        router = self.router
        ring = router.ring          # snapshot BEFORE the checks
        epoch = router.state.epoch  # the epoch this pass runs for
        if not self.pending():
            return {"phase": "idle"}
        unit = self.next_unit(ring)
        if unit is None:
            if any(p.spool.pending_records
                   for p in router.peers.values()):
                # an undrained spool can re-materialize a moved
                # series on its former owner (dual-write spooled to
                # old∪new owners) — marking now would leak those
                # bytes forever; let replay drain and retry
                return {"phase": "blocked",
                        "error": "spool backlog pending"}
            # every (shard, metric) unit deleted its stale copies:
            # the epoch is clean — persist so restarts don't re-scan
            router.state.mark_retired(epoch)
            self.passes += 1
            LOG.info("stale-copy retire pass complete at epoch %d "
                     "(%d series reclaimed)", epoch,
                     self.retired_series)
            return {"phase": "done"}
        if unit == "blocked":
            return {"phase": "blocked"}
        name, metric = unit
        faults = getattr(router.tsdb, "faults", None)
        if faults is not None:
            faults.check("cluster.retire")
        try:
            gone = self._retire_unit(ring, name, metric)
        except RetireDisabled as exc:
            self.failed_steps += 1
            LOG.warning(
                "stale-copy retire is parked: %s — set tsd.http."
                "query.allow_delete=true on every shard to let the "
                "router reclaim moved series (epoch %d stays "
                "pending)", exc, router.state.epoch)
            return {"phase": "disabled", "peer": name,
                    "metric": metric, "error": str(exc)}
        except (OSError, ValueError) as exc:
            self.failed_steps += 1
            LOG.info("retire of %r on %s failed (%s); will retry",
                     metric, name, exc)
            return {"phase": "blocked", "peer": name,
                    "metric": metric, "error": str(exc)}
        self._done.add((name, metric))
        return {"phase": "retired", "peer": name, "metric": metric,
                "series": gone}

    def _retire_unit(self, ring, name: str, metric: str) -> int:
        """Delete one metric's stale series on one shard via the
        inverted selector, against the caller's ring SNAPSHOT (see
        :meth:`step` on the begin_reshard race). Raises on transport
        trouble (the unit stays pending); an unknown-metric 400 is a
        clean zero."""
        router = self.router
        peer = router.peers.get(name)
        if peer is None:
            return 0  # popped by a concurrent reshard: next epoch's
            # pass (re-armed by finalize) covers the survivor set
        rf = min(router.rf, len(ring.names))
        owned = [t for t in ring.replica_sets(rf) if name in t]
        end_ms = int(time.time() * 1000) + HORIZON_MS
        body = json.dumps({
            # explicit ms suffixes, like the copy scans: a bare small
            # number would parse as SECONDS and shrink the window,
            # and the far-future horizon covers forecast series like
            # the backfill/repair scans do
            "start": "1ms", "end": f"{end_ms}ms",
            "msResolution": True,
            "delete": True,
            "queries": [{"metric": metric, "aggregator": "none"}],
            "replicaSel": replica_mod.sel_doc(
                list(ring.names), ring.vnodes, rf, owned,
                invert=True),
        }).encode()
        self.retire_queries += 1
        status, data = router._query_peer(peer, body)
        if status == 400 and b"no such name" in data.lower():
            return 0  # the metric has no series here at all
        if status == 400 and b"allow_delete" in data:
            raise RetireDisabled(
                f"shard {name} runs without "
                f"tsd.http.query.allow_delete")
        if status != 200:
            raise OSError(
                f"peer {name} answered {status} to a retire delete")
        try:
            # a wire leg arrives already decoded (list of rows)
            rows = data if isinstance(data, list) else json.loads(data)
        except ValueError as exc:
            raise OSError(
                f"peer {name} sent an unparseable retire body"
            ) from exc
        gone = len(rows) if isinstance(rows, list) else 0
        if gone:
            self.retired_series += gone
            LOG.info("retired %d stale series of %r from %s",
                     gone, metric, name)
        return gone

    # -- observability -------------------------------------------------

    def health_info(self) -> dict[str, Any]:
        return {
            "pending": self.pending(),
            "retired_series": self.retired_series,
            "retire_queries": self.retire_queries,
            "failed_steps": self.failed_steps,
            "passes": self.passes,
        }


__all__ = ["StaleCopyRetirer"]
