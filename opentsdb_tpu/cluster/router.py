"""The cluster router: consistent-hash writes, scatter-gather reads.

``tsd.cluster.role = router`` turns a TSDServer into a stateless
serving tier in front of ``tsd.cluster.peers`` shard TSDs (the
reference's "many TSDs behind a load balancer", SURVEY §L4, with the
salt-bucket fan-out of ``SaltScanner.java:70`` lifted to the network):

- **writes** partition by the consistent-hash series key and forward
  one series-grouped body per shard — the peer's ``/api/put`` commits
  it through ``TSDB.add_point_groups`` as ONE WAL write + one
  group-committed fsync (PR 6), so a client body costs one fsync per
  shard, not per point. An unreachable shard's batches land in its
  durable spool (:mod:`opentsdb_tpu.cluster.spool`) and the client is
  still acknowledged: no acknowledged point is ever lost to a peer
  outage. Replay drains in FIFO order when the peer's breaker lets a
  probe through.
- **reads** scatter the (absolutized, ms-resolution) TSQuery to every
  shard and merge per-shard group partials
  (:mod:`opentsdb_tpu.cluster.merge`). Failures flow through the
  PR-1 idiom — per-peer :class:`CircuitBreaker`, per-peer timeouts,
  the ``cluster.peer`` fault site, optional tail-latency hedging —
  and a dead/hung/tripped peer yields a **200 partial** carrying a
  ``shardsDegraded`` marker (never a 5xx). Degraded partials are
  never retained by the result cache; a later complete answer
  repopulates the entry.
"""

from __future__ import annotations

import collections
import concurrent.futures
import json
import logging
import queue as queue_mod
import re
import threading
import time
from typing import Any

from opentsdb_tpu.cluster import merge as merge_mod
from opentsdb_tpu.cluster import replica as replica_mod
from opentsdb_tpu.cluster import wire as wire_mod
from opentsdb_tpu.obs import trace as trace_mod
from opentsdb_tpu.obs.trace import (TRACE_HEADER, trace_begin,
                                    trace_end)
from opentsdb_tpu.cluster.client import (PeerClient, PeerError,
                                         parse_peer_spec)
from opentsdb_tpu.cluster.hashring import HashRing, series_shard_key
from opentsdb_tpu.cluster.reshard import (HORIZON_MS, Backfiller,
                                          ReshardState)
from opentsdb_tpu.cluster.spool import PeerSpool, SpoolFull
from opentsdb_tpu.core.tags import (check_metric_and_tags,
                                    check_metric_and_tags_batch,
                                    parse_put_value)
from opentsdb_tpu.query.model import BadRequestError
from opentsdb_tpu.utils.faults import (CircuitBreaker, DegradedError,
                                       RetryPolicy, call_with_retries)

import numpy as np

LOG = logging.getLogger("cluster.router")


class PeerUnavailable(OSError):
    """The peer's breaker refused the dispatch (open, or half-open
    with the probe already in flight): degrade WITHOUT touching the
    peer — and without recording a failure the peer didn't commit."""


class Peer:
    """One shard TSD: address, health machinery, handoff spool."""

    def __init__(self, name: str, host: str, port: int, config,
                 spool_dir: str | None):
        self.name = name
        self.client = PeerClient(
            host, port,
            timeout_ms=config.get_float("tsd.cluster.timeout_ms",
                                        5000.0))
        self.breaker = CircuitBreaker(
            f"cluster.peer.{name}",
            failure_threshold=config.get_int(
                "tsd.cluster.breaker.failure_threshold", 3),
            reset_timeout_ms=config.get_float(
                "tsd.cluster.breaker.reset_timeout_ms", 5000.0))
        self.spool = PeerSpool(
            spool_dir, name,
            max_bytes=config.get_int("tsd.cluster.spool.max_mb",
                                     256) << 20,
            compact_bytes=config.get_int(
                "tsd.cluster.spool.compact_mb", 4) << 20)
        self.lock = threading.Lock()  # FIFO spool-vs-forward decision
        # counters (exported via /api/stats + /api/health)
        self.forwarded_batches = 0
        self.forwarded_points = 0
        self.spooled_batches = 0
        self.spooled_points = 0
        self.replayed_batches = 0
        self.replay_point_errors = 0
        self.query_failures = 0
        self.hedges = 0
        # binary wire transport counters (cluster/wire.py): frames
        # and bytes on the persistent links, pipelining depth, and
        # how often this peer fell back to JSON HTTP / shed into the
        # spool under pipeline backpressure
        self.wire_connects = 0
        self.wire_frames_out = 0
        self.wire_frames_in = 0
        self.wire_bytes_out = 0
        self.wire_bytes_in = 0
        self.wire_pipeline_depth = 0   # gauge: acks in flight now
        self.wire_pipeline_max = 0
        self.wire_fallbacks = 0        # negotiation said HTTP
        self.wire_backpressure_sheds = 0
        # (best-effort, in-memory) trace ids of recently spooled
        # batches, FIFO-aligned with the spool: a later replay root
        # links back to the writes it finally delivered. Lost on
        # restart — the durable spool format stays trace-agnostic.
        self.spool_trace_links: collections.deque = \
            collections.deque(maxlen=512)

    def health_info(self) -> dict[str, Any]:
        return {
            "address": self.client.address,
            "breaker": self.breaker.health_info(),
            "spool": self.spool.health_info(),
            "forwarded_batches": self.forwarded_batches,
            "forwarded_points": self.forwarded_points,
            "spooled_batches": self.spooled_batches,
            "spooled_points": self.spooled_points,
            "replayed_batches": self.replayed_batches,
            "replay_point_errors": self.replay_point_errors,
            "query_failures": self.query_failures,
            "hedges": self.hedges,
            "wire": {
                "connects": self.wire_connects,
                "frames_out": self.wire_frames_out,
                "frames_in": self.wire_frames_in,
                "bytes_out": self.wire_bytes_out,
                "bytes_in": self.wire_bytes_in,
                "pipeline_depth": self.wire_pipeline_depth,
                "pipeline_max": self.wire_pipeline_max,
                "fallbacks": self.wire_fallbacks,
                "backpressure_sheds": self.wire_backpressure_sheds,
            },
        }


class ClusterRouter:
    """Owns the shard map, the peers and the failure machinery."""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        config = tsdb.config
        self.config = config
        config_spec = config.get_string("tsd.cluster.peers", "")
        if not parse_peer_spec(config_spec):
            raise ValueError(
                "tsd.cluster.role=router needs tsd.cluster.peers")
        spool_dir = config.get_string("tsd.cluster.spool.dir", "")
        if not spool_dir and getattr(tsdb, "data_dir", ""):
            import os
            spool_dir = os.path.join(tsdb.data_dir, "cluster_spool")
        # persisted topology: after a finalized reshard the INSTALLED
        # ring differs from config (which still names the boot ring);
        # a mid-reshard kill additionally restores the old ring +
        # backfill progress so recovery resumes the cutover
        self.state = ReshardState(spool_dir or None)
        spec_str = self.state.peers_spec or config_spec
        vnodes = self.state.vnodes \
            or config.get_int("tsd.cluster.vnodes", 64)
        specs = parse_peer_spec(spec_str)
        self.rf = max(config.get_int("tsd.cluster.rf", 1), 1)
        self.peers: dict[str, Peer] = {}
        for name, host, port in specs:
            self.peers[name] = Peer(name, host, port, config,
                                    spool_dir or None)
        self.ring = HashRing([name for name, _, _ in specs],
                             vnodes=vnodes)
        self.old_ring: HashRing | None = None
        if self.state.active:
            old_specs = parse_peer_spec(self.state.old_spec)
            for name, host, port in old_specs:
                if name not in self.peers:
                    self.peers[name] = Peer(name, host, port, config,
                                            spool_dir or None)
            self.old_ring = HashRing(
                [name for name, _, _ in old_specs],
                vnodes=self.state.old_vnodes or vnodes)
        # anti-entropy: per-(peer, metric) divergence windows the
        # spool cannot replay (lost/refused records) — repaired from a
        # surviving replica when the peer returns
        self.dirty = replica_mod.DirtyTracker(spool_dir or None)
        self.repair_enabled = config.get_bool(
            "tsd.cluster.replica.repair", True)
        self.backfiller = Backfiller(self)
        self.backfill_batch = config.get_int(
            "tsd.cluster.reshard.backfill_batch", 4000)
        self.reshard_interval_s = config.get_float(
            "tsd.cluster.reshard.interval_ms", 250.0) / 1000.0
        # stale-copy retire pass (cluster/retire.py): after a
        # finalized reshard, delete the moved series backfill left on
        # former owners (reads already hide them via replicaSel —
        # this reclaims the bytes). One bounded unit per wake.
        from opentsdb_tpu.cluster.retire import StaleCopyRetirer
        self.retirer = StaleCopyRetirer(self)
        self.retire_enabled = config.get_bool(
            "tsd.cluster.retire.enable", True)
        self.retire_interval_s = config.get_float(
            "tsd.cluster.retire.interval_ms", 1000.0) / 1000.0
        self._spool_dir = spool_dir or None
        workers = config.get_int("tsd.cluster.fanout_workers", 0) \
            or max(2 * len(self.peers), 4)
        self.pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="tsd-cluster")
        self.retry = RetryPolicy.from_config(
            config, "tsd.cluster.retry", attempts=2, base_ms=25,
            deadline_ms=2000)
        self.timeout_s = config.get_float("tsd.cluster.timeout_ms",
                                          5000.0) / 1000.0
        self.hedge_after_s = config.get_float(
            "tsd.cluster.hedge_after_ms", 0.0) / 1000.0
        self.replay_interval_s = config.get_float(
            "tsd.cluster.spool.replay_interval_ms", 500.0) / 1000.0
        self.replay_batch = config.get_int(
            "tsd.cluster.spool.replay_batch", 64)
        # binary columnar wire transport (cluster/wire.py): persistent
        # framed links per peer, JSON HTTP as negotiated fallback
        self.wire = wire_mod.WireManager(self)
        # federated continuous queries (cluster/cq.py): per-shard
        # shared partials, router-held merge view
        from opentsdb_tpu.cluster.cq import FederatedCQRegistry
        self.cqs = FederatedCQRegistry(self)
        # per-sub retry amplification bound: a multi-sub 400 re-asks
        # per rejected metric — cap how many of those singles run
        # concurrently against ONE peer so a wide dashboard query
        # can't monopolize the fan-out pool on a partially-known shard
        self.sub_retry_max_concurrent = max(config.get_int(
            "tsd.cluster.sub_retry.max_concurrent", 4), 1)
        self.sub_retry_rounds = 0    # metric-elimination rounds run
        self.sub_retry_singles = 0   # single-sub re-asks dispatched
        self.sub_retry_capped = 0    # dispatches that hit the cap
        # router-level counters
        self.queries = 0
        self.degraded_queries = 0
        self.cache_hits = 0
        self.cache_stores = 0
        self.cache_degraded_skips = 0
        self.read_fallbacks = 0      # tuples re-read from a fallback
        self.repairs = 0             # completed anti-entropy passes
        self.repair_points = 0       # points re-forwarded by repair
        self.scatter_name_queries = 0  # suggest/search fan-outs
        # per-(peer, metric) known/unknown memo: a shard that 400'd
        # "no such name" for a metric is not re-asked about it on
        # every later query — its sub is pre-filtered out of the
        # scatter (and of the per-sub retry), so the steady state for
        # a multi-sub query over partially-known shards is ONE
        # request per shard. Invalidated when a write for the metric
        # is forwarded to that peer (UID creation happens on the
        # shard's write path) and peer-wide when a spool replay lands
        # (spooled writes create UIDs long after their ack); a TTL
        # knob covers deployments where writes can bypass this router.
        self._sub_memo_lock = threading.Lock()
        # (peer, metric) -> (cached no-such-name 400 body, stamp);
        # holds ONLY unknown outcomes — absence means "known or
        # never asked", so the dict is bounded by actual negative
        # knowledge, not by peers x all metrics. Negative knowledge
        # still grows without bound under a probing workload (every
        # typo'd dashboard metric mints an entry that nothing ever
        # reads again — TTL eviction used to run only on a re-read of
        # the SAME key), so the replay loop sweeps expired entries
        # and a hard cap drops the oldest stamps first.
        self._sub_memo: dict[tuple[str, str], tuple] = {}
        self.sub_memo_ttl_s = config.get_float(
            "tsd.cluster.sub_memo.ttl_ms", 0.0) / 1000.0
        self.sub_memo_max = max(config.get_int(
            "tsd.cluster.sub_memo.max_entries", 4096), 1)
        self.sub_memo_skips = 0        # subs pre-filtered from scatters
        self.sub_memo_invalidations = 0
        self.sub_memo_evictions = 0    # TTL sweeps + cap overflow
        # per-metric invalidation versions for the result cache (see
        # write_version): bumped AFTER a write/delete lands so a
        # racing query can never cache pre-write data under the
        # post-write version
        self._version_lock = threading.Lock()
        # bounded: past max_entries the whole map folds into ONE
        # global bump (conservative — every cached entry goes stale
        # at once) and restarts empty, so an ever-new-metrics ingest
        # stream cannot grow router memory without bound
        self._metric_versions: dict[str, int] = {}
        self.metric_versions_max = max(config.get_int(
            "tsd.cluster.metric_versions.max_entries", 100000), 1)
        self._global_version = 0
        # query-path read-repair: divergence a READ observed (failed
        # reader re-covered by a fallback round; replicas disagreeing
        # whether a metric exists) stages here and drains into the
        # DirtyTracker off the read path — mark() fsyncs under its
        # lock, which a serve path must never wait on
        self.read_repair_enabled = config.get_bool(
            "tsd.cluster.read_repair.enable", True)
        self.read_repair = replica_mod.ReadRepairQueue(
            config.get_int("tsd.cluster.read_repair.max_pending",
                           1024))
        # multi-router version bus (cluster/gossip.py): sibling
        # routers named by tsd.cluster.routers exchange write-version
        # + reshard-epoch deltas so every front door's epoch-qualified
        # result cache invalidates on writes ANY of them forwarded
        self.gossip = None
        routers_spec = config.get_string("tsd.cluster.routers", "")
        if routers_spec.strip():
            from opentsdb_tpu.cluster.gossip import GossipBus
            self.gossip = GossipBus(self, routers_spec)
        # TTL cache for the /api/health fleet section (see
        # fleet_health): (doc, monotonic stamp)
        self._fleet_health_lock = threading.Lock()
        self._fleet_health_cache: tuple = (None, 0.0)
        self._stop = threading.Event()
        self._replay_thread: threading.Thread | None = None
        self._backfill_thread: threading.Thread | None = None
        self._retire_thread: threading.Thread | None = None
        self._reshard_lock = threading.Lock()  # begin/finalize fence
        self._started = False

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def start(self) -> None:
        """Start the spool replay thread, and — when a persisted
        cutover is still open — resume its backfill (idempotent)."""
        if self._started:
            return
        self._started = True
        t = threading.Thread(target=self._replay_loop,
                             name="cluster-replay", daemon=True)
        self._replay_thread = t
        t.start()
        if self.gossip is not None:
            self.gossip.start()
        if self.state.active:
            self._start_backfill()
        elif self.retire_enabled and self.retirer.pending():
            # a restart across an un-retired epoch resumes the pass
            self._start_retire()

    def _start_backfill(self) -> None:
        t = self._backfill_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=self._backfill_loop,
                             name="cluster-backfill", daemon=True)
        self._backfill_thread = t
        t.start()

    def _start_retire(self) -> None:
        t = self._retire_thread
        if t is not None and t.is_alive():
            return
        t = threading.Thread(target=self._retire_loop,
                             name="cluster-retire", daemon=True)
        self._retire_thread = t
        t.start()

    def stop(self) -> None:
        self._stop.set()
        if self.gossip is not None:
            self.gossip.stop()
        for t in (self._replay_thread, self._backfill_thread,
                  self._retire_thread):
            if t is not None and t.is_alive():
                t.join(timeout=5)
        self.pool.shutdown(wait=False)
        for peer in self.peers.values():
            peer.spool.close()
        self.cqs.close()
        self.wire.close_all()

    # ------------------------------------------------------------------
    # shared peer dispatch (fault site + breaker + retry)
    # ------------------------------------------------------------------

    def _check_faults(self, peer: Peer) -> None:
        faults = getattr(self.tsdb, "faults", None)
        if faults is not None:
            faults.check("cluster.peer")
            faults.check(f"cluster.peer.{peer.name}")

    def _fetch(self, peer: Peer, method: str, path: str,
               body: bytes | None,
               headers: dict[str, str] | None = None
               ) -> tuple[int, bytes]:
        """One request with optional tail-latency hedging: after
        ``tsd.cluster.hedge_after_ms`` without an answer, a duplicate
        request races the first — first completion wins (Monarch /
        Dean & Barroso "The Tail at Scale"). Hedge threads are
        bounded by the peer socket timeout."""
        if self.hedge_after_s <= 0:
            return peer.client.request(method, path, body,
                                       headers=headers)
        results: queue_mod.Queue = queue_mod.Queue()

        def attempt() -> None:
            try:
                results.put(("ok",
                             peer.client.request(method, path, body,
                                                 headers=headers)))
            except Exception as exc:  # noqa: BLE001 - carried across
                results.put(("err", exc))

        # tsdlint: allow[thread-lifecycle] hedge attempt: lifetime is
        # bounded by the peer client's socket timeout — the request
        # call cannot outlive timeout_s, so no join handle is kept
        threading.Thread(target=attempt, daemon=True).start()
        deadline = time.monotonic() + self.timeout_s + 1.0
        launched = 1
        errors = 0
        first_err: Exception | None = None
        wait_s = self.hedge_after_s
        while True:
            try:
                kind, payload = results.get(
                    timeout=max(min(wait_s,
                                    deadline - time.monotonic()),
                                0.001))
            except queue_mod.Empty:
                if launched == 1 and time.monotonic() < deadline:
                    peer.hedges += 1
                    # tsdlint: allow[thread-lifecycle] hedge twin —
                    # socket-timeout-bounded like the primary above
                    threading.Thread(target=attempt,
                                     daemon=True).start()
                    launched = 2
                    wait_s = deadline - time.monotonic()
                    continue
                raise PeerError(
                    f"peer {peer.name}: hedged request timed out"
                ) from first_err
            if kind == "ok":
                return payload
            errors += 1
            first_err = first_err or payload
            if errors >= launched and launched == 2:
                raise payload
            if errors >= launched:
                # primary failed before the hedge fired: launch the
                # backup immediately, it is the only hope left
                peer.hedges += 1
                # tsdlint: allow[thread-lifecycle] hedge backup —
                # socket-timeout-bounded like the primary above
                threading.Thread(target=attempt, daemon=True).start()
                launched = 2
                wait_s = deadline - time.monotonic()

    def fetch_guarded(self, peer: Peer, method: str, path: str,
                      body: bytes | None = None) -> tuple[int, bytes]:
        """One breaker-guarded exchange on an arbitrary path (suggest/
        search scatter, backfill enumeration): same failure accounting
        as a query leg — a refusal or transport failure raises."""
        if not peer.breaker.allow():
            raise PeerUnavailable(
                f"breaker for {peer.name} is {peer.breaker.state}")
        try:
            self._check_faults(peer)
            status, data = self._fetch(peer, method, path, body)
        except OSError:
            peer.breaker.record_failure()
            raise
        peer.breaker.record_success()
        return status, data

    def scan_series_pages(self, peer: Peer, metric: str,
                          start_ms: int, end_ms: int,
                          sel: dict | None = None,
                          _depth: int = 0):
        """Yield pages of raw per-series rows (aggregator ``none``,
        ms resolution) of one metric's window on one peer — the
        backfill/repair copy source, with the same breaker +
        fault-site discipline as a scatter leg. A **413 scan-budget
        refusal bisects the window**: a budgeted shard refuses a
        whole history in one piece, and without paging the copy
        would retry the identical over-budget query forever. A
        generator so callers forward each slice as it arrives
        instead of materializing the whole history (one page is
        bounded by the shard's scan budget when one is configured).
        Unknown metric yields nothing; any other failure raises
        ``OSError`` (the caller retries the unit later)."""
        obj = {
            # explicit ms suffix: a bare sub-13-digit number parses
            # as SECONDS (reference numeric heuristic), which would
            # silently widen early bisect slices to contain all data
            "start": f"{max(start_ms, 1)}ms", "end": f"{end_ms}ms",
            "msResolution": True,
            "queries": [{"metric": metric, "aggregator": "none"}],
        }
        if sel is not None:
            obj["replicaSel"] = sel
        status, data = self._query_peer(peer,
                                        json.dumps(obj).encode())
        if status == 400 and b"no such name" in data.lower():
            return
        # depth 48 halves any ms window down to ~1s slices — the
        # copy scans start at epoch-begin, so ~25 levels are routine
        if status == 413 and _depth < 48 \
                and end_ms - max(start_ms, 1) > 1000:
            mid = (max(start_ms, 1) + end_ms) // 2
            yield from self.scan_series_pages(peer, metric, start_ms,
                                              mid, sel, _depth + 1)
            yield from self.scan_series_pages(peer, metric, mid + 1,
                                              end_ms, sel,
                                              _depth + 1)
            return
        if status != 200:
            raise PeerUnavailable(
                f"peer {peer.name} answered {status} to a "
                f"{metric!r} copy scan")
        try:
            yield data if isinstance(data, list) else json.loads(data)
        except ValueError as exc:
            raise PeerUnavailable(
                f"peer {peer.name} sent an unparseable copy-scan "
                f"body") from exc

    def scan_series_rows(self, peer: Peer, metric: str,
                         start_ms: int, end_ms: int,
                         sel: dict | None = None) -> list[dict]:
        """All pages of :meth:`scan_series_pages` concatenated (small
        windows / tests; the copy paths iterate pages)."""
        return [row for page in self.scan_series_pages(
                    peer, metric, start_ms, end_ms, sel)
                for row in page]

    def deliver_backfill(self, peer: Peer, dps: list[dict]) -> None:
        """Forward one backfill batch through the normal deliver/spool
        path: an unreachable new owner spools and the moved keyspace
        still lands — kill-during-reshard loses nothing."""
        self._deliver(peer, dps)

    # ------------------------------------------------------------------
    # per-(peer, metric) known/unknown memo (see __init__)
    # ------------------------------------------------------------------

    def _memo_lookup(self, peer_name: str, metric: str):
        """The cached no-such-name 400 body for (peer, metric), or
        None when the peer is not known-unknown for it. The memo
        holds ONLY unknown entries (a known metric simply has no
        entry — storing positives would grow the dict by peers x
        all-metrics with nothing ever reading them); expired entries
        evict on read when a TTL is configured."""
        key = (peer_name, metric)
        with self._sub_memo_lock:
            ent = self._sub_memo.get(key)
            if ent is None:
                return None
            body, stamp = ent
            if self.sub_memo_ttl_s > 0 and \
                    time.monotonic() - stamp > self.sub_memo_ttl_s:
                del self._sub_memo[key]
                return None
            return body

    def _memo_known(self, peer_name: str, metrics) -> None:
        """A definite 200 disproves any cached unknown — drop it
        (no positive entry is stored; absence IS 'known')."""
        with self._sub_memo_lock:
            for m in metrics:
                self._sub_memo.pop((peer_name, m), None)

    def _memo_unknown(self, peer_name: str, metric: str,
                      body: bytes) -> None:
        """Cache one peer's metric-unknown 400 — ONLY when the body
        is the engine's no-such-name rejection: any other 400 is a
        query-shape error that must not poison later,
        differently-shaped queries over the same metric."""
        if not metric or b"no such name" not in body.lower():
            return
        with self._sub_memo_lock:
            self._sub_memo[(peer_name, metric)] = \
                (body, time.monotonic())

    def sweep_sub_memo(self) -> int:
        """Evict expired and over-cap memo entries (called from the
        replay loop each wake, and directly by tests/ops). Read-time
        eviction alone only covers keys that are probed AGAIN — a
        typo'd metric nobody re-queries would pin its entry forever.
        Over the cap, oldest stamps evict first (they are the least
        likely to be re-probed). Returns entries dropped."""
        now = time.monotonic()
        dropped = 0
        with self._sub_memo_lock:
            if self.sub_memo_ttl_s > 0:
                stale = [k for k, (_b, stamp)
                         in self._sub_memo.items()
                         if now - stamp > self.sub_memo_ttl_s]
                for k in stale:
                    del self._sub_memo[k]
                dropped += len(stale)
            over = len(self._sub_memo) - self.sub_memo_max
            if over > 0:
                oldest = sorted(self._sub_memo,
                                key=lambda k:
                                self._sub_memo[k][1])[:over]
                for k in oldest:
                    del self._sub_memo[k]
                dropped += over
            self.sub_memo_evictions += dropped
        return dropped

    def invalidate_sub_memo(self, peer_name: str,
                            metrics=None) -> None:
        """Drop UNKNOWN entries for a peer (all of them, or just the
        given metrics): called when a write batch is dispatched to
        the peer (the shard's write path mints the UID — the metric
        is about to be known) and peer-wide when a spool replay
        lands (spooled writes create UIDs long after their ack).
        Known entries never invalidate — a metric that vanishes
        server-side (UID reclamation) re-404s through the normal
        per-sub retry and re-memoizes."""
        with self._sub_memo_lock:
            if metrics is not None:
                stale = [(peer_name, m) for m in set(metrics)
                         if (peer_name, m) in self._sub_memo]
            else:
                stale = [k for k in self._sub_memo
                         if k[0] == peer_name]
            for k in stale:
                del self._sub_memo[k]
            self.sub_memo_invalidations += len(stale)

    # ------------------------------------------------------------------
    # write path
    # ------------------------------------------------------------------

    def write_owners(self, metric: str, tags: dict[str, str]
                     ) -> tuple[str, ...]:
        """Every shard one point must reach: the current ring's
        replica set (RF distinct owners), plus — while a reshard
        cutover is open — the OLD ring's set (dual-write: reads stay
        on the old ring during the window, so its owners must keep
        seeing every accepted write; unmoved series resolve to the
        same set and pay nothing)."""
        owners = list(self.ring.shards_for(metric, tags, self.rf))
        old_ring = self.old_ring
        if old_ring is not None:
            for n in old_ring.shards_for(metric, tags, self.rf):
                if n not in owners:
                    owners.append(n)
        return tuple(owners)

    @staticmethod
    def _dp_key(dp: dict) -> tuple:
        """Content identity of one datapoint, stable across the JSON
        round-trip through a peer's error echo — replica deliveries
        report per-point outcomes against parsed copies, not the
        router's original objects."""
        tags = dp.get("tags") or {}
        return (dp.get("metric"), str(dp.get("timestamp")),
                str(dp.get("value")),
                tuple(sorted((str(k), str(v))
                             for k, v in tags.items())))

    def partition_points(self, points: list[dict]
                         ) -> tuple[dict[str, list[dict]],
                                    list[dict], list[dict]]:
        """Shard each datapoint by its series key onto EVERY replica
        owner. Returns (shard -> points, local error entries for
        unshardable dps, valid dps in input order) — at RF > 1 (or
        during a reshard window) the same dp object appears in
        several shards' batches.

        The per-point validation mirrors the peer's write path BEFORE
        acking: a bad point bound for a dead shard would be acked into
        the spool now and rejected at replay — the same body a
        HEALTHY shard 400s, so ack semantics would depend on peer
        liveness. Same helpers the shard's write path calls, so the
        accept sets cannot drift. Checks keep the scalar loop's
        precedence per point (timestamp, then metric/tags, then
        value), but the timestamp range check runs as ONE vectorized
        pass over the numeric common case, metric/tag validation runs
        as one columnar charset pass over the batch's distinct series
        (``check_metric_and_tags_batch``), and ring ownership resolves
        through one ``searchsorted`` over all series keys — a bulk put
        of many points on few series hashes the ring once per series,
        not once per point."""
        n = len(points)
        # index -> error entry; None = accepted (or still undecided).
        # Assembling errors from this at the end preserves the scalar
        # loop's input-order interleaving of structural and
        # validation failures.
        entries: list[dict | None] = [None] * n
        batches: dict[str, list[dict]] = {}
        valid: list[dict] = []

        # pass 1 — structural shape (pure python object dispatch) +
        # timestamp extraction for the vector check
        cand: list[tuple[int, dict, str, dict]] = []
        ts_idx: list[int] = []
        ts_orig: list[Any] = []
        for i, dp in enumerate(points):
            if not isinstance(dp, dict):
                entries[i] = {"datapoint": dp,
                              "error": "not a datapoint object"}
                continue
            metric = dp.get("metric")
            tags = dp.get("tags") or {}
            if not isinstance(metric, str) or not metric or \
                    not isinstance(tags, dict):
                entries[i] = {"datapoint": dp,
                              "error": "missing metric or tags"}
                continue
            cand.append((i, dp, metric, tags))
            ts = dp.get("timestamp")
            if isinstance(ts, (int, float)):
                ts_idx.append(i)
                ts_orig.append(ts)

        # vectorized timestamp verdicts for numeric timestamps.
        # int(ts) truncates toward zero — np.trunc matches. Anything
        # past 2**47 (or non-finite) needs _check_timestamp's exact
        # bit test and falls back to the scalar path; below that the
        # range check is just 0 < ts <= 2**47.
        ts_ok: set[int] = set()
        ts_err: dict[int, str] = {}
        if ts_idx:
            t = np.trunc(np.asarray(ts_orig, dtype=np.float64))
            hi = float(1 << 47)
            ok = (t > 0.0) & (t <= hi)
            bad = np.isfinite(t) & (t <= 0.0)
            for j in np.nonzero(ok)[0]:
                ts_ok.add(ts_idx[j])
            for j in np.nonzero(bad)[0]:
                # format from the ORIGINAL value: the float64 trunc
                # of a huge int is approximate, int() is not
                ts_err[ts_idx[j]] = \
                    f"invalid timestamp {int(ts_orig[j])}"

        # distinct-series batch: validate every hashable series in
        # one columnar charset pass and hash the ring once per series
        # via searchsorted over the whole batch — pass 2 below then
        # reduces to memo lookups for the common case. Unhashable tag
        # values (TypeError on the key) keep the scalar path.
        series_memo: dict[Any, tuple[str, Any]] = {}
        for i, dp, metric, tags in cand:
            try:
                series_memo.setdefault((metric, tuple(tags.items())),
                                       (metric, tags))
            except TypeError:
                pass
        if series_memo:
            skeys = list(series_memo)
            pairs = list(series_memo.values())
            verrs = check_metric_and_tags_batch(pairs)
            ok_pos = [j for j, e in enumerate(verrs) if e is None]
            ring_keys = [series_shard_key(pairs[j][0], pairs[j][1])
                         for j in ok_pos]
            new_sets = self.ring.shards_for_keys(ring_keys, self.rf)
            old_ring = self.old_ring
            old_sets = old_ring.shards_for_keys(ring_keys, self.rf) \
                if old_ring is not None else None
            for slot, j in enumerate(ok_pos):
                owners = list(new_sets[slot])
                if old_sets is not None:
                    for nm in old_sets[slot]:
                        if nm not in owners:
                            owners.append(nm)
                series_memo[skeys[j]] = ("ok", tuple(owners))
            for j, e in enumerate(verrs):
                if e is not None:
                    series_memo[skeys[j]] = ("err", e)

        # pass 2 — per-point verdicts in input order, series-memoized
        for i, dp, metric, tags in cand:
            if i in ts_err:
                entries[i] = {"datapoint": dp, "error": ts_err[i]}
                continue
            if i not in ts_ok:
                # non-numeric, non-finite or >2**47: exact scalar
                # check (missing key raises the same KeyError the
                # scalar loop reported)
                try:
                    self.tsdb._check_timestamp(int(dp["timestamp"]))
                except (KeyError, TypeError, ValueError) as exc:
                    entries[i] = {"datapoint": dp, "error": str(exc)}
                    continue
            try:
                # insertion-ordered items: validate_string reports
                # the FIRST offending tag, so two dps with the same
                # tag set in different orders stay distinct entries
                skey = (metric, tuple(tags.items()))
                cached = series_memo.get(skey)
            except TypeError:  # unhashable tag value: no memo
                skey = None
                cached = None
            if cached is None:
                try:
                    check_metric_and_tags(metric, tags)
                except (KeyError, TypeError, ValueError) as exc:
                    cached = ("err", str(exc))
                else:
                    cached = ("ok", self.write_owners(metric, tags))
                if skey is not None:
                    series_memo[skey] = cached
            if cached[0] == "err":
                entries[i] = {"datapoint": dp, "error": cached[1]}
                continue
            value = dp.get("value")
            if isinstance(value, str):
                try:
                    parse_put_value(value)
                except (KeyError, TypeError, ValueError) as exc:
                    entries[i] = {"datapoint": dp, "error": str(exc)}
                    continue
            elif value is None or isinstance(value, bool) or \
                    not isinstance(value, (int, float)):
                entries[i] = {"datapoint": dp,
                              "error": f"invalid value: {value!r}"}
                continue
            valid.append(dp)
            for shard in cached[1]:
                batches.setdefault(shard, []).append(dp)
        errors = [e for e in entries if e is not None]
        return batches, errors, valid

    def forward_writes(self, points: list[dict]
                       ) -> tuple[int, int, list[dict]]:
        """Partition + deliver one put body to every replica owner.
        Returns (success, failed, error entries). Spooled points count
        as success — they are durably accepted and will replay; a
        point is acked only when EVERY owner accepted (forwarded or
        spooled) its copy, so an ack always implies eventual presence
        on the full replica set.

        At-least-once, never at-most-once: a delivery that outlives
        the ``fut.result`` cap below is reported failed even though
        the in-flight worker may still land (or spool) it — the safe
        direction, since a re-sent point dedupes last-write-wins on
        the shard, while the reverse (acking a loss) cannot be
        repaired. The same rule covers a replica split (one owner
        stored, another refused): reported failed, and the divergence
        is marked dirty for anti-entropy."""
        batches, errors, valid = self.partition_points(points)
        tctx = trace_mod.current()
        # .get: a reshard finalize may pop a departed old owner
        # between partitioning and here — skipping its batch IS the
        # post-finalize write plan (the union included the new
        # owners, which still receive their copies)
        futures = {
            self.pool.submit(self._deliver_traced, tctx, peer, dps):
            (name, dps) for name, dps in batches.items()
            if (peer := self.peers.get(name)) is not None}
        # per-point outcomes merge across replica deliveries by
        # CONTENT key: the first error entry per failed point is
        # reported; a point missing from every delivery's error set
        # was accepted by all its owners
        failed_entries: dict[tuple, dict] = {}
        unattributed = 0
        for fut, (name, dps) in futures.items():
            try:
                _ok, bad, errs = fut.result(
                    timeout=self.timeout_s * 4 + 5)
            except Exception as exc:  # noqa: BLE001 - per-shard
                LOG.exception("forward to %s failed unexpectedly",
                              name)
                bad = len(dps)
                errs = [{"datapoint": dp, "error": str(exc)}
                        for dp in dps]
            attributed = 0
            refused_dps: list[dict] = []
            for e in errs:
                dp = e.get("datapoint")
                if isinstance(dp, dict):
                    failed_entries.setdefault(self._dp_key(dp), e)
                    refused_dps.append(dp)
                    attributed += 1
            if refused_dps and (self.rf > 1
                                or self.old_ring is not None):
                # a point one replica refused may have landed on its
                # siblings (a replica SPLIT): mark the window dirty so
                # anti-entropy re-levels it when the peer is willing —
                # a refusal that was identical everywhere repairs to a
                # no-op and clears
                self.dirty.mark(
                    name,
                    {dp.get("metric") for dp in refused_dps
                     if dp.get("metric")},
                    self._min_ts_ms(refused_dps))
            # a peer that counted failures it did not echo (odd
            # summary body): charge them without attribution — the
            # over-report direction is the safe one
            unattributed += max(int(bad) - attributed, 0)
        failed_keys = set(failed_entries)
        success = sum(1 for dp in valid
                      if self._dp_key(dp) not in failed_keys)
        success = max(success - unattributed, 0)
        failed = len(errors) + (len(valid) - success)
        errors.extend(failed_entries.values())
        # AFTER delivery/spool: a racing query that read the new
        # version has already seen (or will re-read) the landed data
        self._bump_versions(dp["metric"] for dp in valid)
        return success, failed, errors

    def _deliver_traced(self, tctx, peer: Peer, dps: list[dict]
                        ) -> tuple[int, int, list[dict]]:
        """One shard's write leg under its ``cluster.forward`` span
        (pool thread): the context re-binds thread-locally so the
        spool handoff inside records its ``cluster.spool.append``
        span, and the trace header lets the shard root its ingest
        subtree under this leg."""
        if tctx is None:
            return self._deliver(peer, dps)
        sp = trace_begin("cluster.forward", ctx=tctx,
                         peer=peer.name, points=len(dps))
        headers = {TRACE_HEADER: tctx.tracer.header_for(tctx, sp)} \
            if sp is not None else None
        try:
            with trace_mod.use(tctx):
                out = self._deliver(peer, dps, headers=headers)
        except BaseException as exc:
            trace_end(sp, error=exc)
            raise
        trace_end(sp)
        return out

    def _deliver(self, peer: Peer, dps: list[dict],
                 headers: dict[str, str] | None = None
                 ) -> tuple[int, int, list[dict]]:
        """One shard's batch: forward, or spool when the peer is
        backlogged/unhealthy (FIFO: a non-empty spool means new
        writes enqueue BEHIND it, so replayed history and causally
        LATER traffic keep arrival order — an ack always precedes
        the next dependent write's dispatch; batches concurrently in
        flight during the failure window are unordered, as
        concurrent writes always are)."""
        # whether this batch forwards or spools, the shard's write
        # path will mint these metrics' UIDs (now, or at replay —
        # which invalidates peer-wide again): the scatter may ask
        # about them from here on
        self.invalidate_sub_memo(peer.name,
                                 {dp["metric"] for dp in dps})
        # the wire path never materializes a JSON body at all — that
        # deferral IS much of the ingest win. Spool records stay JSON
        # (the durable format is transport-agnostic), built lazily
        # only when a batch actually sheds.
        use_wire = self.wire.usable(peer)
        body: bytes | None = None if use_wire \
            else json.dumps(dps).encode()

        def spool_body() -> bytes:
            nonlocal body
            if body is None:
                body = json.dumps(dps).encode()
            return body

        with peer.lock:
            direct = (peer.spool.pending_records == 0
                      and peer.breaker.state == CircuitBreaker.CLOSED)
            if not direct:
                return self._spool_batch(peer, spool_body(), dps)
        try:
            self._check_faults(peer)
            if use_wire:
                try:
                    status, data = call_with_retries(
                        lambda: self.wire.put_batch(
                            peer, dps=dps, headers=headers),
                        self.retry, retryable=(OSError,))
                except (wire_mod.WireUnsupported,
                        wire_mod.WireEncodeError):
                    # negotiation said HTTP, or the batch is not
                    # canonically columnar: same delivery, JSON body
                    use_wire = False
            if not use_wire:
                status, data = call_with_retries(
                    lambda: self._fetch(
                        peer, "POST",
                        "/api/put?summary=true&details=true",
                        spool_body(), headers=headers),
                    self.retry, retryable=(OSError,))
        except wire_mod.WireBacklogged:
            # pipeline at max_inflight: shed to the durable spool
            # WITHOUT touching the breaker — backpressure is not
            # peer damage, and the spool replay drains in FIFO order
            peer.wire_backpressure_sheds += 1
            with peer.lock:
                return self._spool_batch(peer, spool_body(), dps)
        except OSError as exc:
            peer.breaker.record_failure()
            LOG.warning("shard %s unreachable (%s); spooling %d "
                        "point(s)", peer.name, exc, len(dps))
            with peer.lock:
                return self._spool_batch(peer, spool_body(), dps)
        doc = self._put_summary_doc(data)
        if doc is None and not 200 <= status < 300:
            # a 4xx with no put summary did NOT come from a TSD put
            # handler (reverse proxy, auth wall, wrong address):
            # nothing was stored, so acking here would lose the batch
            peer.breaker.record_failure()
            LOG.warning("shard %s answered %d without a put summary; "
                        "spooling %d point(s)", peer.name, status,
                        len(dps))
            with peer.lock:
                return self._spool_batch(peer, body, dps)
        peer.breaker.record_success()
        peer.forwarded_batches += 1
        if doc is None:  # 2xx with an odd body: stored per the status
            ok, bad, errs = len(dps), 0, []
        else:
            ok = int(doc.get("success", 0))
            bad = int(doc.get("failed", 0))
            errs = list(doc.get("errors") or ())
        peer.forwarded_points += ok
        return ok, bad, errs

    @staticmethod
    def _put_summary_doc(data: bytes) -> dict | None:
        """The peer's ``/api/put?summary`` body, or None when the
        response is not a put summary at all."""
        try:
            doc = json.loads(data)
        except Exception:  # noqa: BLE001 - defensive: odd peer body
            return None
        if isinstance(doc, dict) and ("success" in doc
                                      or "failed" in doc):
            return doc
        return None

    @staticmethod
    def _min_ts_ms(dps: list[dict]) -> int:
        """Earliest DATA timestamp of one batch in ms (the dirty-epoch
        a later anti-entropy repair reads the replica from)."""
        out = 0
        for dp in dps:
            try:
                ts = int(dp["timestamp"])
            except (KeyError, TypeError, ValueError):
                continue
            ms = ts * 1000 if ts < 10 ** 11 else ts
            if out == 0 or ms < out:
                out = ms
        return out

    def _spool_batch(self, peer: Peer, body: bytes, dps: list[dict]
                     ) -> tuple[int, int, list[dict]]:
        """Durable handoff (caller holds ``peer.lock``): the ack
        rides on the spool fsync. A FULL spool refuses the points
        loudly (per-point errors) — dropping the oldest record would
        break the no-loss guarantee. The trace records the handoff
        as a ``cluster.spool.append`` span, and the trace id is
        remembered so the eventual replay root links back to it.

        Divergence bookkeeping: a handoff the spool cannot replay
        durably (refused full, or an in-memory spool a router restart
        would lose) marks the (peer, metric) window dirty — when the
        peer returns, anti-entropy re-copies it from a surviving
        replica instead of trusting records that may be gone."""
        sp = trace_begin("cluster.spool.append", peer=peer.name,
                         points=len(dps))
        try:
            peer.spool.append(body)
        except SpoolFull as exc:
            trace_end(sp, error=exc)
            self.dirty.mark(peer.name, {dp["metric"] for dp in dps},
                            self._min_ts_ms(dps))
            return 0, len(dps), [
                {"datapoint": dp,
                 "error": f"shard {peer.name} unreachable and its "
                          f"spool is full: {exc}"} for dp in dps]
        tctx = trace_mod.current()
        if tctx is not None:
            peer.spool_trace_links.append(tctx.trace_id)
        trace_end(sp)
        if not peer.spool.durable:
            # the ack is only as durable as this process: mark the
            # window so a restart that loses the queue still heals
            self.dirty.mark(peer.name, {dp["metric"] for dp in dps},
                            self._min_ts_ms(dps))
        peer.spooled_batches += 1
        peer.spooled_points += len(dps)
        return len(dps), 0, []

    # ------------------------------------------------------------------
    # spool replay
    # ------------------------------------------------------------------

    def _replay_loop(self) -> None:
        while not self._stop.wait(self.replay_interval_s):
            self.sweep_sub_memo()
            try:
                self.drain_read_repair()
            except Exception:  # noqa: BLE001 - keep the loop alive
                LOG.exception("read-repair drain failed")
            for peer in list(self.peers.values()):
                try:
                    self.drain_spool(peer)
                except Exception:  # noqa: BLE001 - keep the loop alive
                    LOG.exception("spool replay for %s failed",
                                  peer.name)
                try:
                    self.maybe_repair(peer)
                except Exception:  # noqa: BLE001 - keep the loop alive
                    LOG.exception("replica repair for %s failed",
                                  peer.name)

    def drain_spool(self, peer: Peer) -> int:
        """Catch-up drain: keep replaying batches while progress is
        made. One fixed-size batch per wake would cap the drain at
        replay_batch/interval records per second — sustained ingest
        above that rate (new writes enqueue FIFO behind a non-empty
        spool) would grow a recovering peer's backlog to SpoolFull
        even though the peer is healthy. Stops on the first
        zero-progress pass (drained, breaker refused, or a failure
        re-opened the breaker)."""
        if peer.spool.pending_records == 0:
            return 0
        # one background trace roots the catch-up drain; it links
        # back to the (still-remembered) traces whose writes were
        # spooled, so "where did my acked write actually land" is
        # answerable end to end
        tracer = getattr(self.tsdb, "tracer", None)
        tctx = tracer.start_background("cluster.spool.replay",
                                       peer=peer.name) \
            if tracer is not None and tracer.enabled else None
        total = 0
        links: list[str] = []
        try:
            with trace_mod.use(tctx):
                while not self._stop.is_set():
                    n = self.try_replay(peer, links_out=links)
                    total += n
                    if n == 0:
                        break
            if tctx is not None:
                tctx.tag(batches=total,
                         pending=peer.spool.pending_records,
                         trace_links=links)
        finally:
            if tracer is not None and tctx is not None:
                if total == 0:
                    # a zero-progress probe is not worth a retained
                    # trace; mark it sampled-out
                    tctx.sampled = False
                tracer.finish(tctx)
        return total

    def try_replay(self, peer: Peer, max_records: int = 0,
                   links_out: list | None = None) -> int:
        """Drain up to ``max_records`` (0 = one configured batch) of
        the peer's spool if its breaker admits a dispatch. The replay
        IS the half-open probe: first success closes the breaker,
        failure re-opens it and keeps the spool position.

        Trace links are consumed per APPLIED record (inside the
        callback) so a pass that fails partway keeps the unapplied
        records' links aligned with the spool — popping by the pass
        total would desynchronize forever after one partial failure.
        """
        if peer.spool.pending_records == 0:
            return 0
        if not peer.breaker.allow():
            return 0
        limit = max_records or self.replay_batch

        def apply(body: bytes) -> None:
            self._replay_one(peer, body)
            # this record is delivered: retire its (best-effort,
            # FIFO-aligned) trace link
            if peer.spool_trace_links:
                link = peer.spool_trace_links.popleft()
                if links_out is not None:
                    links_out.append(link)

        before = peer.spool.replayed_records
        try:
            n = peer.spool.replay(apply, limit)
        except OSError as exc:
            if peer.spool.replayed_records > before:
                # the records applied BEFORE the failure are readable
                # on the shard now: cached entries must go stale even
                # though this pass did not finish
                self._bump_global_version()
            peer.breaker.record_failure()
            LOG.info("spool replay to %s stopped (%s); %d record(s) "
                     "still pending", peer.name, exc,
                     peer.spool.pending_records)
            return 0
        if n:
            peer.breaker.record_success()
            # replayed history just LANDED on the shard, long after
            # its ack: a complete answer cached while the backlog was
            # pending is stale NOW (the write-time bump happened at
            # spool time, before this data was readable) — and the
            # shard may know metrics it 400'd while the backlog was
            # pending (replay-created UIDs), so unknown memos go too
            self._bump_global_version()
            self.invalidate_sub_memo(peer.name)
            LOG.info("replayed %d spooled batch(es) to %s (%d "
                     "pending)", n, peer.name,
                     peer.spool.pending_records)
        elif peer.breaker.state != CircuitBreaker.CLOSED:
            # zero records applied WITHOUT touching the peer (the
            # spool head was unreadable and got dropped): no evidence
            # of peer health, so the half-open probe this call
            # consumed must not close the breaker — release it as a
            # failure and let the next reset window retry
            peer.breaker.record_failure()
        return n

    def _replay_one(self, peer: Peer, body: bytes) -> None:
        self._check_faults(peer)
        status = None
        if self.wire.usable(peer):
            try:
                status, data = self.wire.put_batch(peer, body=body)
            except (wire_mod.WireUnsupported,
                    wire_mod.WireEncodeError,
                    wire_mod.WireBacklogged):
                # replay traffic never waits on pipeline room and
                # never re-spools (it IS the spool): deliver this
                # record over plain HTTP instead
                status = None
        if status is None:
            status, data = self._fetch(
                peer, "POST", "/api/put?summary=true&details=true",
                body)
        doc = self._put_summary_doc(data)
        if doc is None and not 200 <= status < 300:
            # not a TSD put answer: the record was NOT applied — keep
            # it spooled (raising stops the replay pass and records a
            # breaker failure in try_replay)
            raise PeerUnavailable(
                f"peer {peer.name} answered {status} without a put "
                f"summary during replay")
        peer.replayed_batches += 1
        bad = int(doc.get("failed", 0)) if doc else 0
        if bad:
            # per-point rejections (bad data) are terminal: the peer
            # is healthy and will reject them identically forever —
            # count them loudly instead of wedging the spool
            peer.replay_point_errors += bad
            LOG.warning("spool replay to %s: peer rejected %d "
                        "point(s): %s", peer.name, bad, data[:200])

    # ------------------------------------------------------------------
    # anti-entropy: repair a returned replica from a surviving one
    # ------------------------------------------------------------------

    def drain_read_repair(self) -> int:
        """Move read-observed divergence hints from the bounded
        staging queue into the :class:`DirtyTracker` (whose ``mark``
        fsyncs — never acceptable on the read path that staged them).
        The marked windows then heal through the normal
        ``maybe_repair`` machinery in this same loop; completion is
        counted back via ``read_repair.note_repaired``. Returns how
        many hints were marked."""
        staged = self.read_repair.drain()
        if not staged:
            return 0
        tracer = getattr(self.tsdb, "tracer", None)
        tctx = tracer.start_background("cluster.read_repair",
                                       entries=len(staged)) \
            if tracer is not None and tracer.enabled else None
        marked = 0
        try:
            with trace_mod.use(tctx):
                for peer_name, metric, since_ms in staged:
                    if peer_name in self.peers:
                        self.dirty.mark(peer_name, [metric],
                                        since_ms)
                        marked += 1
                    else:
                        # the peer left the ring between the read and
                        # this drain: its debt is void
                        self.read_repair.drop_peer(peer_name)
        finally:
            if tracer is not None and tctx is not None:
                tracer.finish(tctx)
        return marked

    def maybe_repair(self, peer: Peer) -> bool:
        """Run one anti-entropy pass for a peer with dirty windows,
        once its spool is drained (replay covers everything the spool
        still holds — repair exists for what it lost). Gated by the
        peer's breaker like any dispatch: on a non-closed breaker the
        repair IS the half-open probe. Returns True when the peer has
        no remaining debt."""
        if not self.repair_enabled:
            return False
        if not self.dirty.peek(peer.name):
            return True
        if peer.spool.pending_records:
            return False  # replay first; repair covers the remainder
        if peer.breaker.state != CircuitBreaker.CLOSED:
            if not peer.breaker.allow():
                return False
            probe = True
        else:
            probe = False
        tracer = getattr(self.tsdb, "tracer", None)
        tctx = tracer.start_background("cluster.replica.repair",
                                       peer=peer.name) \
            if tracer is not None and tracer.enabled else None
        try:
            with trace_mod.use(tctx):
                done = self.repair_peer(peer)
            if probe:
                if done:
                    peer.breaker.record_success()
                else:
                    # the remaining debt is SOURCE-side trouble (a
                    # sibling was down or refused the scan) — the
                    # peer under probe may be perfectly healthy, and
                    # punishing it would quarantine it for as long as
                    # the source stays down. Decide the probe by
                    # touching the peer itself.
                    try:
                        self._check_faults(peer)
                        self._fetch(peer, "GET", "/api/version",
                                    None)
                        peer.breaker.record_success()
                    except OSError:
                        peer.breaker.record_failure()
            return done
        except OSError as exc:
            if tctx is not None:
                tctx.set_error(exc)
            peer.breaker.record_failure()
            LOG.info("replica repair for %s stopped (%s)",
                     peer.name, exc)
            return False
        finally:
            if tracer is not None and tctx is not None:
                tracer.finish(tctx)

    def repair_peer(self, peer: Peer) -> bool:
        """Re-copy every dirty (peer, metric) window from a surviving
        replica: for each replica set containing the peer, ONE alive
        sibling is asked for the window (``replicaSel``-filtered to
        exactly those sets, so nothing is copied twice) and the rows
        re-forward through the normal deliver path. Duplicates dedupe
        last-write-wins on the shard — repair is idempotent. Returns
        True when every dirty metric was repaired (False leaves the
        remaining debt for the next pass)."""
        faults = getattr(self.tsdb, "faults", None)
        if faults is not None:
            faults.check("cluster.replica")
        dirty = self.dirty.peek(peer.name)
        if not dirty:
            return True
        ring = self.ring
        rf = min(self.rf, len(ring.names))
        sets_with = [t for t in ring.replica_sets(rf)
                     if peer.name in t]
        if rf <= 1 or not sets_with:
            # no second copy exists (RF=1), or the peer no longer
            # owns anything on this ring: there is nothing to repair
            # FROM (or for) — the debt is void
            self.dirty.clear(peer.name)
            self.read_repair.drop_peer(peer.name)
            return True
        now_ms = int(time.time() * 1000)
        all_done = True
        for metric, since_ms in sorted(dirty.items()):
            per_source: dict[str, list[tuple]] = {}
            uncovered = False
            for t in sets_with:
                src = next(
                    (n for n in t if n != peer.name
                     and not self.peers[n].breaker.blocking()), None)
                if src is None:
                    uncovered = True  # no alive sibling: retry later
                else:
                    per_source.setdefault(src, []).append(t)
            copied = 0
            metric_ok = not uncovered
            for src, sets in per_source.items():
                pages = self.scan_series_pages(
                    self.peers[src], metric,
                    max(since_ms - 1, 1), now_ms + HORIZON_MS,
                    sel=replica_mod.sel_doc(
                        ring.names, ring.vnodes, rf, sets))
                while True:
                    # SOURCE failures (advancing the scan) only keep
                    # the metric dirty; PEER-side delivery failures
                    # propagate out of repair_peer — the debt stays
                    # (the data still lives on the source, so there
                    # is no ack to protect) and maybe_repair's
                    # breaker accounting sees a failure the peer
                    # actually caused
                    try:
                        rows = next(pages)
                    except StopIteration:
                        break
                    except OSError:
                        metric_ok = False
                        break
                    dps: list[dict] = []
                    for row in rows:
                        tags = row.get("tags") or {}
                        for ts, val in (row.get("dps") or ()):
                            dps.append({"metric": metric,
                                        "timestamp": int(ts),
                                        "value": val, "tags": tags})
                    for i in range(0, len(dps), self.backfill_batch):
                        copied += self._repair_deliver(
                            peer, dps[i:i + self.backfill_batch])
            if metric_ok:
                self.repair_points += copied
                self.dirty.clear(peer.name, [metric])
                self.read_repair.note_repaired(peer.name, [metric])
            else:
                all_done = False
        if all_done:
            self.repairs += 1
            # repaired history just became readable on the peer: any
            # cached complete answer over it is stale now
            self._bump_global_version()
            self.invalidate_sub_memo(peer.name)
        return all_done

    def _repair_deliver(self, peer: Peer, dps: list[dict]) -> int:
        """One repair chunk, delivered DIRECTLY (the ``_replay_one``
        shape): a repair pass often runs as the peer's half-open
        probe, when ``_deliver`` would divert to the spool — which
        would both defeat the probe (nothing touches the peer) and
        turn repair data into spool backlog. Failure raises; the
        dirty debt stays and the data still lives on the source
        replica, so there is no ack to protect."""
        self._check_faults(peer)
        self.invalidate_sub_memo(peer.name,
                                 {dp["metric"] for dp in dps})
        status, data = self._fetch(
            peer, "POST", "/api/put?summary=true&details=true",
            json.dumps(dps).encode())
        doc = self._put_summary_doc(data)
        if doc is None and not 200 <= status < 300:
            raise PeerUnavailable(
                f"peer {peer.name} answered {status} without a put "
                f"summary during repair")
        if doc is not None and int(doc.get("failed", 0)):
            raise PeerUnavailable(
                f"peer {peer.name} rejected "
                f"{doc.get('failed')} repair point(s)")
        return len(dps)

    # ------------------------------------------------------------------
    # read path
    # ------------------------------------------------------------------

    def _read_view(self, delete: bool = False
                   ) -> tuple[HashRing, list[str]]:
        """The ring reads scatter over, plus the peer names involved.
        During a reshard cutover reads stay on the OLD ring — its
        owners hold complete history AND (via dual-write) every
        in-window write, so answers are complete without cross-ring
        merging, the one shape where two copies of a moved series
        could double-sum. Deletes must purge EVERY copy, so they
        cover the union of both rings."""
        old_ring = self.old_ring
        if delete:
            names = list(self.ring.names)
            if old_ring is not None:
                names += [n for n in old_ring.names
                          if n not in names]
            return self.ring, names
        if old_ring is not None:
            return old_ring, list(old_ring.names)
        return self.ring, list(self.ring.names)

    def execute_query(self, tsq) -> tuple[list, list[str]]:
        """Scatter one validated TSQuery, merge partials. Returns
        (results, degraded shard names). Raises ``BadRequestError``
        for non-decomposable aggregators; peer failures NEVER raise —
        they degrade.

        At RF > 1 the scatter is a replica-set READ PLAN: every
        distinct ordered replica set is assigned to one member (the
        first whose breaker isn't blocking), the request carries the
        assignment as a ``replicaSel`` series filter, and a failed
        reader's sets re-assign to the next replica in further rounds
        — so a single shard death yields a COMPLETE marker-less 200,
        and the ``shardsDegraded`` marker appears only when an entire
        replica set is down."""
        self.queries += 1
        for sub in tsq.queries:
            if sub.tsuids:
                # UIDs are assigned independently per shard: the same
                # TSUID bytes name a DIFFERENT series on each shard,
                # so a scattered tsuid sub would merge unrelated
                # series into one plausible-looking answer
                raise BadRequestError(
                    "tsuid sub-queries are not supported in router "
                    "mode: UIDs are assigned per shard — query by "
                    "metric and tags instead")
        plans = [merge_mod.decompose_plan(sub) for sub in tsq.queries]
        # expanded peer-side sub list: avg fans out as sum+count twins
        peer_subs: list[dict] = []
        slots: list[tuple[int, int | None]] = []  # (primary, secondary)
        for sub, plan in zip(tsq.queries, plans):
            sj = sub.to_json()
            sj.pop("pixels", None)  # reduce AFTER the merge
            sj.pop("pixelFn", None)
            sj.pop("index", None)
            if plan == "avg":
                s1 = dict(sj, aggregator="sum")
                s2 = dict(sj, aggregator="count")
                slots.append((len(peer_subs), len(peer_subs) + 1))
                peer_subs.extend([s1, s2])
            elif plan == "sketch_agg":
                # percentile aggregator: each shard emits its raw
                # per-series downsampled values; the router folds
                # them into per-(group, bucket) sketches
                slots.append((len(peer_subs), None))
                peer_subs.append(dict(sj, aggregator="none"))
            else:
                slots.append((len(peer_subs), None))
                peer_subs.append(sj)
        peer_obj = {
            # absolute window: every shard must grid the SAME range,
            # or downsample buckets stop aligning across partials
            "start": str(tsq.start_ms), "end": str(tsq.end_ms),
            "msResolution": True, "showQuery": True,
            "queries": peer_subs,
            "showTSUIDs": tsq.show_tsuids,
            "noAnnotations": tsq.no_annotations,
            "globalAnnotations": tsq.global_annotations,
            "timezone": tsq.timezone,
            "useCalendar": tsq.use_calendar,
            "delete": tsq.delete,
        }
        if any(p == "sketch" for p in plans):
            # percentile subs: shards answer with serialized
            # per-bucket sketch partials instead of extracted
            # quantiles (quantiles of partials don't merge; sketches
            # do, exactly)
            peer_obj["sketchPartials"] = True
        # per-peer scatter plan through the known/unknown memo: subs
        # whose metric a peer has already 400'd "no such name" for
        # are pre-filtered out of that peer's request (their cached
        # 400 still joins the all-shards-agree check), so the steady
        # state over partially-known shards is one request per shard.
        # Deletes bypass the memo: a stale unknown entry must never
        # silently skip a purge.
        use_memo = not tsq.delete
        ring, ring_names = self._read_view(tsq.delete)
        rf = min(self.rf, len(ring.names))
        # the replica filter is needed at RF > 1 (each series has RF
        # live copies) and on ANY resharded cluster (epoch > 0: moved
        # series leave stale copies on their former owners — backfill
        # copies, it does not purge); deletes go unfiltered because
        # they must reach every copy, stale ones included
        use_sel = (rf > 1 or self.state.epoch > 0) and not tsq.delete
        # read assignment: replica tuple -> reader. sel=None means
        # "everything you own" (single-owner epoch-0 ring, and
        # deletes)
        if use_sel:
            def takes_reads(n: str) -> bool:
                # .get: a reshard finalize may pop a departed peer
                # between the ring snapshot and here — route around
                # it like any unhealthy replica
                peer = self.peers.get(n)
                return peer is not None and \
                    not peer.breaker.blocking()

            tuples = ring.replica_sets(rf)
            pending: dict[str, list[tuple] | None] = {}
            for t in tuples:
                reader = next((n for n in t if takes_reads(n)), t[0])
                pending.setdefault(reader, []).append(t)
        else:
            pending = {name: None for name in ring_names}
        # trace the fan-out: one cluster.scatter stage, one
        # cluster.peer leg per shard (error-tagged when degraded)
        tctx = trace_mod.current()
        sp_scatter = trace_begin("cluster.scatter", ctx=tctx,
                                 shards=len(pending))
        scatter_id = sp_scatter.span_id if sp_scatter is not None \
            else None
        # expanded-sub index -> 4xx bodies, one per rejecting peer;
        # answered/unknown peer sets drive the all-shards-agree check
        sub_400: dict[int, list[bytes]] = {}
        sub_answered: dict[int, set] = \
            {k: set() for k in range(len(peer_subs))}
        sub_unknown: dict[int, set] = \
            {k: set() for k in range(len(peer_subs))}
        # unknown outcomes served from the memo (vs a FRESH 400 this
        # scatter): the read-repair divergence hook ignores them, or
        # every repeat query of a legitimately shard-unknown metric
        # would re-stage the same no-op repair
        sub_memo_unknown: dict[int, set] = {}
        # incremental merge: every COMPLETE leg folds the moment its
        # future resolves (wire legs additionally decode frame-by-
        # frame), instead of gathering all partials and merging last.
        # Fold order still equals the old partials-list order, so the
        # merged result is bit-identical to the batch path.
        merger = merge_mod.StreamMerger(
            tsq.queries, plans, slots,
            sketch_alpha=self.config.get_float(
                "tsd.sketch.alpha", 0.01))
        failed_peers: set[str] = set()
        degraded_set: set[str] = set()

        def mark_trouble() -> None:
            if tctx is not None:
                # force retention the moment trouble is KNOWN —
                # before later legs stamp their headers, so those
                # legs (header_for reads ctx.forced at call time)
                # carry keep=1 and their shard subtrees survive
                # sampling. Legs already dispatched with keep=0
                # cannot be retro-retained.
                tctx.forced = True

        while pending:
            futures = {}
            round_req: dict[str, tuple] = {}
            round_failed: list[str] = []
            for name in sorted(pending):
                sel = pending[name]
                peer = self.peers.get(name)
                if peer is None:
                    # popped by a concurrent reshard finalize: fail
                    # the leg so its sets fall back (or degrade)
                    round_failed.append(name)
                    mark_trouble()
                    continue
                req_obj = peer_obj if sel is None else dict(
                    peer_obj, replicaSel=replica_mod.sel_doc(
                        ring.names, ring.vnodes, rf, sel))
                skip: dict[int, bytes] = {}
                if use_memo:
                    for k, sj in enumerate(peer_subs):
                        cached = self._memo_lookup(
                            name, sj.get("metric") or "")
                        if cached is not None:
                            skip[k] = cached
                sent = [k for k in range(len(peer_subs))
                        if k not in skip]
                if skip:
                    self.sub_memo_skips += len(skip)
                    for k, cached in skip.items():
                        sub_400.setdefault(k, []).append(cached)
                        sub_unknown[k].add(name)
                        sub_answered[k].add(name)
                        sub_memo_unknown.setdefault(k, set()) \
                            .add(name)
                round_req[name] = (peer, sel, sent, req_obj)
                if not sent:
                    continue  # nothing this shard knows
                pbody = json.dumps(dict(
                    req_obj,
                    queries=[peer_subs[k] for k in sent])).encode()
                futures[name] = self.pool.submit(
                    self._query_peer_traced, tctx, scatter_id, peer,
                    pbody)
            for name, fut in futures.items():
                peer, sel, sent, req_obj = round_req[name]
                try:
                    status, data = fut.result(
                        timeout=self.timeout_s * 2 + 5)
                except (OSError,
                        concurrent.futures.TimeoutError) as exc:
                    peer.query_failures += 1
                    round_failed.append(name)
                    mark_trouble()
                    LOG.warning("shard %s failed this scatter round "
                                "(%s: %s)", name,
                                type(exc).__name__, exc)
                    continue
                if status == 200:
                    try:
                        # a wire leg arrives already decoded (list);
                        # an HTTP leg is a JSON body
                        rows = data if isinstance(data, list) \
                            else json.loads(data)
                    except ValueError:
                        peer.query_failures += 1
                        round_failed.append(name)
                        mark_trouble()
                        continue
                    if len(sent) != len(peer_subs):
                        # trimmed request: peer-local sub indexes map
                        # back to the expanded scatter's
                        for r in rows:
                            q = r.get("query")
                            if isinstance(q, dict) and \
                                    isinstance(q.get("index"), int) \
                                    and 0 <= q["index"] < len(sent):
                                q["index"] = sent[q["index"]]
                    merger.add_leg(rows)
                    for k in sent:
                        sub_answered[k].add(name)
                    if use_memo:
                        self._memo_known(
                            name, {peer_subs[k].get("metric")
                                   for k in sent})
                    continue
                if status != 400:
                    # 413 (scan budget), 404/405 (not a TSD query
                    # endpoint — proxy / auth wall / misroute), 5xx
                    # passed through: NOT the no-such-name empty
                    # partial. Treating it as one would silently
                    # blank this shard's series in a cacheable
                    # "complete" answer; fail the leg loudly instead
                    # (fallback, else marker — never cached).
                    peer.query_failures += 1
                    round_failed.append(name)
                    mark_trouble()
                    LOG.warning("shard %s answered %d to the "
                                "scatter; failing it for this query",
                                name, status)
                    continue
                # 400 from a HEALTHY peer: a shard that owns no
                # series of the metric 400s with "no such name" — an
                # empty partial, not peer damage and not a client
                # error (other shards may own it). Kept for the
                # all-shards-agree check below.
                if len(sent) == 1:
                    sub_400.setdefault(sent[0], []).append(data)
                    sub_unknown[sent[0]].add(name)
                    sub_answered[sent[0]].add(name)
                    merger.add_leg([])
                    if use_memo:
                        self._memo_unknown(
                            name,
                            peer_subs[sent[0]].get("metric") or "",
                            data)
                    continue
                # multi-sub scatter: the request-level 400 hides
                # WHICH sub the peer rejected — and blanks subs it
                # DOES own series for. Re-ask in metric-elimination
                # rounds (one request per rejected metric, not one
                # per sub) and memoize every definite outcome so the
                # NEXT query scatters once.
                rows, died = self._per_sub_retry(
                    peer, req_obj,
                    [(k, peer_subs[k]) for k in sent], data,
                    sub_400, sub_answered, sub_unknown,
                    memoize=use_memo, tctx=tctx,
                    parent_id=scatter_id)
                if died:
                    peer.query_failures += 1
                    round_failed.append(name)
                    mark_trouble()
                else:
                    merger.add_leg(rows)
            # re-assign a failed reader's replica sets to the next
            # member that hasn't failed this query; a set with no
            # member left is DOWN — the only case that degrades
            next_pending: dict[str, list] = {}
            for name in round_failed:
                failed_peers.add(name)
            for name in round_failed:
                sel = pending[name]
                if sel is None:
                    degraded_set.add(name)  # no replica to fall to
                    continue
                for t in sel:
                    cand = next((n for n in t
                                 if n not in failed_peers), None)
                    if cand is None:
                        degraded_set.update(t)
                    else:
                        next_pending.setdefault(cand, []).append(t)
            if next_pending:
                self.read_fallbacks += sum(
                    len(v) for v in next_pending.values())
            pending = next_pending
        degraded = sorted(degraded_set)
        if failed_peers and self.read_repair_enabled and rf > 1 \
                and not tsq.delete:
            # a reader that died mid-scatter may be missing writes in
            # the window this read wanted (a fallback round covered
            # its sets, but the replica itself stays suspect): stage
            # the window for repair — idempotent, so a reader that
            # merely timed out heals to a no-op
            metrics = {s.metric for s in tsq.queries if s.metric}
            since = max(int(tsq.start_ms), 1)
            for name in sorted(failed_peers):
                if metrics and name in self.peers:
                    self.read_repair.enqueue(name, metrics, since)
        if tsq.delete:
            # the shards already purged whatever rows they own during
            # the scatter (and per-sub retries): any cached entry
            # over these metrics is stale NOW, on EVERY exit path
            # below — including the all-shards-agree 400 (a multi-sub
            # delete can purge one sub's metric everywhere and still
            # 400 on a nowhere-known sibling sub)
            metrics = [s.metric for s in tsq.queries if s.metric]
            if len(metrics) < len(tsq.queries):
                self._bump_global_version()
            self._bump_versions(metrics)
        if sp_scatter is not None:
            if degraded:
                sp_scatter.tag(degraded=",".join(degraded))
            trace_end(sp_scatter)
        if not degraded_set:
            for idx in sorted(sub_unknown):
                unknown = sub_unknown[idx]
                if unknown and unknown == sub_answered[idx]:
                    # every peer that definitively answered this sub
                    # rejected it, and every replica set was covered
                    # (no degradation): surface the real client error
                    # (single-node parity: an unknown metric in ANY
                    # sub fails the whole query)
                    errs = sub_400.get(idx) or [b""]
                    try:
                        msg = json.loads(
                            errs[0])["error"]["message"]
                    except Exception:  # noqa: BLE001
                        msg = errs[0].decode("utf-8", "replace")[:200]
                    raise BadRequestError(msg)
        if self.read_repair_enabled and rf > 1 and not tsq.delete:
            # replica-divergence detection: replicas DISAGREED about
            # a metric's existence this scatter (some answered series,
            # others freshly 400'd "no such name"). The unknown side
            # may have lost the series' writes — or may legitimately
            # be assigned none of them; staging is cheap and a clean
            # window repairs to a no-op. Memo-served unknowns are
            # excluded (nothing new was observed about them).
            for idx, unknown in sub_unknown.items():
                fresh = unknown - sub_memo_unknown.get(idx, set())
                if not fresh or unknown == sub_answered[idx]:
                    continue
                metric = peer_subs[idx].get("metric") or ""
                if not metric:
                    continue
                since = max(int(tsq.start_ms), 1)
                for name in sorted(fresh):
                    if name in self.peers:
                        self.read_repair.enqueue(name, [metric],
                                                 since)
        if degraded:
            self.degraded_queries += 1
            if tctx is not None:
                # a degraded partial IS what an operator goes looking
                # for after seeing the shardsDegraded marker: force
                # retention so 1-in-N sampling can never discard the
                # trace carrying the error-tagged peer span
                tctx.forced = True
        if tsq.delete and degraded:
            # unlike writes, deletes have no spool/replay story (only
            # put bodies replay): a 200 here would ack a purge the
            # degraded shard never saw, and its rows would survive
            # FOREVER. Loud structured 503 instead — delete is
            # idempotent, so retrying once the shard returns
            # completes the purge.
            raise DegradedError(
                "delete partially applied: shard(s) "
                f"{', '.join(degraded)} unreachable — "
                "retry to complete the purge")
        with trace_mod.trace_span("cluster.merge", ctx=tctx,
                                  shards=merger.legs):
            # per-leg folding already happened as legs completed;
            # this finishes the accumulated groups (avg division,
            # grid sort) and applies post-merge pixel budgets
            results = merger.results()
            results = self._apply_pixels(tsq, results)
        return results, degraded

    _NO_SUCH_NAME_RE = re.compile(
        r"No such name for '[^']+': '([^']*)'")

    @classmethod
    def _unknown_metric_from_400(cls, data: bytes) -> str | None:
        """The metric a peer's no-such-name 400 body rejects, or None
        when the body is some other 400 shape."""
        try:
            msg = json.loads(data)["error"]["message"]
        except Exception:  # noqa: BLE001 - defensive: odd peer body
            return None
        m = cls._NO_SUCH_NAME_RE.search(str(msg))
        return m.group(1) if m else None

    def _per_sub_retry(self, peer: Peer, req_obj: dict,
                       indexed_subs: list[tuple[int, dict]],
                       first_400: bytes,
                       sub_400: dict[int, list[bytes]],
                       sub_answered: dict[int, set],
                       sub_unknown: dict[int, set],
                       memoize: bool = True, tctx=None,
                       parent_id=None) -> tuple[list[dict], bool]:
        """Re-ask a peer that 400'd the combined request in
        METRIC-ELIMINATION rounds: a no-such-name body names the
        rejected metric, so each 400 — starting with the scatter's
        own (``first_400``) — drops that metric's subs (recording
        their rejection) and re-issues the remainder as ONE request.
        The amplification is one round trip per unknown metric, not
        one per expanded sub (a 12-sub dashboard with one cold
        metric used to pay 12 re-asks). A 400 the body cannot
        attribute falls back to the one-request-per-sub sweep, so no
        peer answer shape loses correctness.

        Returns (result rows with their sub index restored,
        peer-died flag). A peer that dies partway contributes
        NOTHING — not the rows it already answered: an avg expands to
        sum+count twins, and merging a shard's sum partial without
        its count twin would make every merged value WRONG
        (inflated), not merely incomplete."""
        remaining = list(indexed_subs)
        data = first_400
        for _round in range(len(indexed_subs) + 1):
            metric = self._unknown_metric_from_400(data)
            hit = [(k, sj) for k, sj in remaining
                   if (sj.get("metric") or "") == metric] \
                if metric else []
            if not hit:
                # unattributable 400 (not the engine's no-such-name
                # shape, or naming a metric we didn't send): the
                # conservative one-request-per-sub sweep still
                # resolves every sub individually
                return self._per_sub_retry_singles(
                    peer, req_obj, remaining, sub_400, sub_answered,
                    sub_unknown, memoize=memoize, tctx=tctx,
                    parent_id=parent_id)
            for k, sj in hit:
                sub_400.setdefault(k, []).append(data)
                sub_unknown[k].add(peer.name)
                sub_answered[k].add(peer.name)
                if memoize:
                    self._memo_unknown(peer.name, metric or "", data)
            remaining = [(k, sj) for k, sj in remaining
                         if (sj.get("metric") or "") != metric]
            if not remaining:
                return [], False
            body = json.dumps(dict(
                req_obj,
                queries=[sj for _k, sj in remaining])).encode()
            self.sub_retry_rounds += 1
            try:
                status, data = self._query_peer_traced(
                    tctx, parent_id, peer, body)
            except OSError:
                return [], True
            if status == 200:
                try:
                    part = data if isinstance(data, list) \
                        else json.loads(data)
                except ValueError:
                    return [], True
                for r in part:
                    q = r.get("query")
                    if isinstance(q, dict) and \
                            isinstance(q.get("index"), int) \
                            and 0 <= q["index"] < len(remaining):
                        q["index"] = remaining[q["index"]][0]
                for k, sj in remaining:
                    sub_answered[k].add(peer.name)
                if memoize:
                    self._memo_known(
                        peer.name,
                        {sj.get("metric") for _k, sj in remaining})
                return part, False
            if status != 400:
                # same rule as the combined scatter: a non-400
                # rejection is peer damage, not an empty partial
                return [], True
        return [], True  # cannot converge: treat as peer damage

    def _per_sub_retry_singles(self, peer: Peer, req_obj: dict,
                               indexed_subs: list[tuple[int, dict]],
                               sub_400: dict[int, list[bytes]],
                               sub_answered: dict[int, set],
                               sub_unknown: dict[int, set],
                               memoize: bool = True, tctx=None,
                               parent_id=None
                               ) -> tuple[list[dict], bool]:
        """One request per expanded sub: the fallback when a 400 body
        cannot name the rejected metric (see ``_per_sub_retry``).

        Submission runs in WAVES of at most
        ``tsd.cluster.sub_retry.max_concurrent`` against this one
        peer: the sweep's amplification is per-sub, and uncapped it
        could monopolize the shared fan-out pool (and the peer) on a
        wide dashboard query. A wave that observes peer death stops
        submitting further waves — the peer contributes nothing
        anyway (see ``_per_sub_retry`` on avg twins)."""
        self.sub_retry_singles += len(indexed_subs)
        cap = self.sub_retry_max_concurrent
        if len(indexed_subs) > cap:
            self.sub_retry_capped += 1
        rows: list[dict] = []
        died = False
        for w0 in range(0, len(indexed_subs), cap):
            if died:
                break  # don't hammer a dead peer with more waves
            futs = [(k, sj, self.pool.submit(
                        self._query_peer_traced, tctx, parent_id,
                        peer,
                        json.dumps(dict(req_obj,
                                        queries=[sj])).encode()))
                    for k, sj in indexed_subs[w0:w0 + cap]]
            for k, sj, fut in futs:
                try:
                    status, data = fut.result(
                        timeout=self.timeout_s * 2 + 5)
                except (OSError, concurrent.futures.TimeoutError):
                    died = True
                    continue  # keep draining the in-flight futures
                if died:
                    continue
                if status == 400:
                    sub_400.setdefault(k, []).append(data)
                    sub_unknown[k].add(peer.name)
                    sub_answered[k].add(peer.name)
                    if memoize:
                        self._memo_unknown(peer.name,
                                           sj.get("metric") or "",
                                           data)
                    continue
                if status != 200:
                    # same rule as the combined scatter: a non-400
                    # rejection is peer damage, not an empty partial
                    died = True
                    continue
                try:
                    part = data if isinstance(data, list) \
                        else json.loads(data)
                except ValueError:
                    died = True
                    continue
                sub_answered[k].add(peer.name)
                if memoize:
                    self._memo_known(peer.name, {sj.get("metric")})
                for r in part:
                    q = r.get("query")
                    if isinstance(q, dict):
                        # single-sub answers say index 0
                        q["index"] = k
                rows.extend(part)
        return ([], True) if died else (rows, False)

    @staticmethod
    def _sub_results(peer_results: list[dict], sub_idx: int
                     ) -> list[dict]:
        """One peer's partials for one expanded sub: the scatter sets
        ``showQuery`` so every result row names its sub index."""
        return [r for r in peer_results
                if (r.get("query") or {}).get("index") == sub_idx]

    def _query_peer(self, peer: Peer, body: bytes,
                    headers: dict[str, str] | None = None
                    ) -> tuple[int, Any]:
        if not peer.breaker.allow():
            raise PeerUnavailable(
                f"breaker for {peer.name} is "
                f"{peer.breaker.state}")
        if self.wire.usable(peer):
            # streamed columnar leg: partial grids decode as frames
            # arrive. Returns decoded ROWS on 200 (callers treat a
            # list as already-parsed) and body bytes on non-200, so
            # the 400-body checks work identically on either
            # transport. WireUnsupported falls through to HTTP.
            try:
                self._check_faults(peer)
                status, data = self.wire.query(peer, body,
                                               headers=headers)
            except (wire_mod.WireUnsupported,
                    wire_mod.WireBacklogged):
                pass
            except OSError:
                peer.breaker.record_failure()
                raise
            else:
                peer.breaker.record_success()
                return status, data
        try:
            # fault site inside the recorded section: an injected
            # cluster.peer fault must trip the breaker exactly like a
            # real peer failure, or the chaos battery could not drive
            # the breaker deterministically
            self._check_faults(peer)
            status, data = self._fetch(peer, "POST",
                                       "/api/query?arrays=true", body,
                                       headers=headers)
        except OSError:
            peer.breaker.record_failure()
            raise
        peer.breaker.record_success()
        return status, data

    def _query_peer_traced(self, tctx, parent_id, peer: Peer,
                           body: bytes) -> tuple[int, Any]:
        """One scatter leg under its ``cluster.peer`` span (runs on a
        pool thread): the span id rides the ``X-TSD-Trace`` header so
        the shard roots its subtree under THIS leg, and a failed leg
        — dead, hung, tripped — is the error-tagged span the stitched
        tree shows for a degraded shard."""
        if tctx is None:
            return self._query_peer(peer, body)
        sp = trace_begin("cluster.peer", ctx=tctx, parent=parent_id,
                         peer=peer.name)
        headers = {TRACE_HEADER: tctx.tracer.header_for(tctx, sp)} \
            if sp is not None else None
        try:
            status, data = self._query_peer(peer, body,
                                            headers=headers)
        except BaseException as exc:
            trace_end(sp, error=exc)
            raise
        if sp is not None:
            sp.tag(status=status)
        trace_end(sp)
        return status, data

    def _apply_pixels(self, tsq, results: list) -> list:
        """Pixel budgets apply AFTER the merge (a per-shard reduction
        would select subset points before partials combine — wrong
        values, wrong extremes). Same kernels, same semantics as
        ``QueryEngine._build_results``."""
        import numpy as np

        from opentsdb_tpu.ops import visual_downsample as vd
        from opentsdb_tpu.query.model import effective_pixels
        if tsq.delete:
            return results
        by_sub: dict[int, tuple[int, str]] = {}
        for sub in tsq.queries:
            by_sub[sub.index] = effective_pixels(tsq, sub)
        out = []
        for r in results:
            px, fn = by_sub.get(r.sub_query_index, (0, ""))
            arrays = getattr(r, "dps_arrays", None)
            if px and arrays is None:
                # percentile rows merge as plain (ts, value) lists —
                # post-assembly they reduce like any other emitted row
                r.dps = vd.reduce_dps(r.dps, tsq.start_ms, tsq.end_ms,
                                      px, fn)
                out.append(r)
                continue
            if not px or arrays is None or not len(arrays[0]):
                out.append(r)
                continue
            ts_arr, vals = arrays
            # merged rows carry only EMITTED points (NaN = an emitted
            # fill gap), so the emit mask is all-True — matching the
            # engine, where NaN fill points are emitted too
            emit = np.ones((1, len(ts_arr)), dtype=bool)
            keep = vd.keep_mask(vals[None, :], emit, ts_arr,
                                tsq.start_ms, tsq.end_ms, px, fn)
            if keep is not None:
                sel = keep[0]
                r.dps_arrays = (ts_arr[sel], vals[sel])
                r.dps = None
            out.append(r)
        return out

    # ------------------------------------------------------------------
    # result cache integration
    # ------------------------------------------------------------------

    def _bump_versions(self, metrics, announce: bool = True) -> None:
        names = set(metrics)
        with self._version_lock:
            for m in names:
                self._metric_versions[m] = \
                    self._metric_versions.get(m, 0) + 1
            if len(self._metric_versions) > self.metric_versions_max:
                # fold the per-metric knowledge into the global
                # component: strictly conservative (any entry cached
                # under the old tuple mismatches the new one), and
                # the map restarts bounded
                self._metric_versions.clear()
                self._global_version += 1
        # gossip AFTER releasing the version lock (the bus has its
        # own lock; never hold both). announce=False is the receive
        # side applying a sibling's delta — re-logging it would
        # bounce the same invalidation between routers forever.
        if announce and names and self.gossip is not None:
            self.gossip.record_writes(names)

    def _bump_global_version(self, announce: bool = True) -> None:
        with self._version_lock:
            self._global_version += 1
        if announce and self.gossip is not None:
            self.gossip.record_global()

    def write_version(self, tsq=None) -> tuple:
        """Invalidation version of the router's view of the cluster
        as ``tsq`` reads it: per-METRIC write/delete counters (so
        steady ingest of unrelated metrics leaves dashboard entries
        hitting — the cluster twin of the engine's per-sub store
        versions) plus a global component bumped by spool replays
        (replayed history lands on shards long after its ack; any
        entry could be affected). Without ``tsq`` (or for tsuid subs
        that name no metric) the conservative whole-cluster version.
        Writes landing on shards directly (bypassing the router) are
        invisible — relative-window entries stay bounded by the same
        TTL rule as single-node serving; absolute-window dashboards
        behind a multi-router deployment should disable the router
        cache (``tsd.query.cache.enable=false``).

        Every version is EPOCH-QUALIFIED (the persisted ring-change
        epoch leads the tuple): a ring install atomically mismatches
        every cached entry, so no router — including one restarting
        across a reshard, the epoch survives in ``reshard.json`` —
        can ever serve a pre-cutover answer as current."""
        epoch = self.state.epoch
        with self._version_lock:
            whole = (epoch, self._global_version,
                     sum(self._metric_versions.values()))
            if tsq is None:
                return whole
            metrics = set()
            for sub in tsq.queries:
                if not sub.metric:
                    return whole
                metrics.add(sub.metric)
            return (epoch, self._global_version) + tuple(
                self._metric_versions.get(m, 0)
                for m in sorted(metrics))

    def cache_plan(self, tsq) -> tuple[tuple, float] | None:
        from opentsdb_tpu.query import result_cache as rc_mod
        if tsq.delete:
            return None
        keys = []
        ttl_ms = 0.0
        for sub in tsq.queries:
            plan = rc_mod.cache_plan(tsq, sub, self.config)
            if plan is None:
                return None
            key, ttl = plan
            keys.append(key)
            if ttl:
                ttl_ms = ttl if ttl_ms == 0 else min(ttl_ms, ttl)
        return ("cluster", tuple(keys)), ttl_ms

    def run_cached(self, tsq) -> tuple[list, list[str]]:
        """Execute through the serve-path result cache. A degraded
        partial is NEVER retained (the marker must never outlive the
        outage it reports); a later complete answer repopulates."""
        cache = self.tsdb.result_cache
        if self.gossip is not None and self.gossip.degraded():
            # a partitioned sibling router may be forwarding writes
            # whose invalidations this router cannot see: any cache
            # hit could be stale and any store could cache around an
            # unseen write. Bypass the cache entirely — exact answers,
            # never a stale serve, never a 5xx — until a gossip push
            # lands again.
            self.gossip.cache_bypasses += 1
            if cache is not None:
                cache.count_bypass()
            return self.execute_query(tsq)
        plan = self.cache_plan(tsq) if cache is not None else None
        if plan is None:
            if cache is not None:
                cache.count_bypass()
            return self.execute_query(tsq)
        key, ttl_ms = plan
        version = self.write_version(tsq)
        hit = cache.lookup(key, version, ttl_ms)
        if hit is not None:
            self.cache_hits += 1
            return hit, []
        results, degraded = self.execute_query(tsq)
        if degraded:
            self.cache_degraded_skips += 1
        else:
            cache.store(key, version, results)
            self.cache_stores += 1
        return results, degraded

    # ------------------------------------------------------------------
    # online resharding (ring-change epochs)
    # ------------------------------------------------------------------

    @property
    def epoch(self) -> int:
        return self.state.epoch

    @property
    def resharding(self) -> bool:
        return self.old_ring is not None

    def begin_reshard(self, new_spec: str, vnodes: int = 0) -> dict:
        """Install a new ring at a fenced epoch and open the cutover
        window (``POST /api/cluster/reshard``): joining shards get
        peers + spools, the epoch/rings persist for kill-during-
        reshard recovery, every write starts dual-delivering to
        old∪new owners, reads stay on the old ring, and the backfill
        starts streaming moved keyspace. Raises ``BadRequestError``
        on a bad spec or while a cutover is already open."""
        specs = parse_peer_spec(new_spec)
        if not specs:
            raise BadRequestError(
                "reshard needs a non-empty peers spec")
        with self._reshard_lock:
            if self.old_ring is not None:
                raise BadRequestError(
                    "a reshard is already in progress (epoch "
                    f"{self.state.epoch}); wait for it to finalize")
            for name, host, port in specs:
                cur = self.peers.get(name)
                if cur is not None and (cur.client.host != host or
                                        cur.client.port != port):
                    raise BadRequestError(
                        f"shard {name!r} changes address in the new "
                        f"ring ({cur.client.address} -> {host}:"
                        f"{port}); rename it instead")
            old_spec = ",".join(
                f"{n}={self.peers[n].client.host}:"
                f"{self.peers[n].client.port}"
                for n in self.ring.names)
            new_vnodes = int(vnodes) or self.ring.vnodes
            for name, host, port in specs:
                if name not in self.peers:
                    self.peers[name] = Peer(name, host, port,
                                            self.config,
                                            self._spool_dir)
            # order matters for racing writers (no lock on the write
            # path): old_ring fills first, so the worst interleaving
            # writes to the OLD owners only — which the backfill scan
            # (running strictly later) still moves
            prev = self.ring
            self.old_ring = prev
            self.ring = HashRing([n for n, _, _ in specs],
                                 vnodes=new_vnodes)
            epoch = self.state.begin(new_spec, new_vnodes, old_spec,
                                     prev.vnodes)
            self.backfiller.reset()
            # the epoch leads every cache version: installing it
            # atomically mismatches every cached entry
            self._bump_global_version()
        LOG.info("reshard installed at epoch %d: %s -> %s", epoch,
                 old_spec, new_spec)
        if self._started:
            self._start_backfill()
        return self.reshard_info()

    def backfill_step(self) -> dict[str, Any]:
        """Copy one backfill unit and finalize when the copy is
        complete (the background loop drives this; tests/ops may call
        it directly for deterministic cutovers)."""
        if self.old_ring is None:
            return {"phase": "idle"}
        info = self.backfiller.step()
        if info.get("phase") == "done":
            self.finalize_reshard()
        return info

    def finalize_reshard(self) -> None:
        """Close the cutover window: the new ring is the only ring.
        Shards that left are dropped — dual-write already placed
        everything they were owed on the new owners, so their
        remaining spool backlog (if any) is void."""
        with self._reshard_lock:
            old_ring = self.old_ring
            if old_ring is None:
                return
            self.old_ring = None
            removed = [n for n in old_ring.names
                       if n not in self.ring.names]
            self.state.finish()
            for n in removed:
                peer = self.peers.pop(n, None)
                if peer is not None:
                    pending = peer.spool.pending_records
                    if pending:
                        LOG.warning(
                            "dropping departed shard %s with %d "
                            "spooled record(s): dual-write already "
                            "delivered them to the new owners", n,
                            pending)
                    peer.spool.close()
                self.dirty.drop_peer(n)
                self.read_repair.drop_peer(n)
                self.invalidate_sub_memo(n)
            self._bump_global_version()
            # the ownership map just changed: re-arm the stale-copy
            # retire pass for this epoch (former owners still in the
            # ring hold moved series replicaSel now hides)
            self.retirer.reset()
        LOG.info("reshard finalized at epoch %d; ring: %s",
                 self.state.epoch, ",".join(self.ring.names))
        if self._started and self.retire_enabled:
            self._start_retire()

    def adopt_topology(self, doc: dict) -> bool:
        """Adopt a sibling router's gossiped ring topology. Three
        shapes: the remote epoch is BEHIND (or equal with the same
        phase) — no-op; the remote FINALIZED the epoch this router
        still holds open — finalize locally; the remote epoch is
        AHEAD — install its ring, and when the cutover window is
        still open, adopt the dual-write window and run a local
        idempotent backfill. The last shape is what lets a sibling
        RESUME a reshard whose initiating router was killed
        mid-flight: duplicated copy units dedupe last-write-wins on
        the shards. Version bumps here do not re-announce — the
        initiator already announced the epoch change to every
        sibling. Returns True when anything changed."""
        try:
            epoch = int(doc.get("epoch", 0))
            spec = str(doc.get("peers", "") or "")
            vnodes = int(doc.get("vnodes", 0) or 0)
            active = bool(doc.get("active"))
            old_spec = str(doc.get("old_peers", "") or "")
            old_vnodes = int(doc.get("old_vnodes", 0) or 0)
            fence_ms = int(doc.get("fence_ms", 0) or 0)
        except (TypeError, ValueError):
            return False
        if epoch < self.state.epoch or not spec:
            return False
        if epoch == self.state.epoch:
            if active or not self.state.active:
                return False  # same epoch, same phase: in agreement
            # the sibling finalized the window this router still
            # holds open (its backfill completed first): finalize
            # locally — idempotent under _reshard_lock
            self.finalize_reshard()
            return True
        specs = parse_peer_spec(spec)
        if not specs:
            return False
        resumed = False
        with self._reshard_lock:
            if epoch <= self.state.epoch:
                return False  # raced with another adoption
            vn = int(vnodes) or self.ring.vnodes
            for name, host, port in specs:
                cur = self.peers.get(name)
                if cur is not None and (cur.client.host != host or
                                        cur.client.port != port):
                    LOG.warning(
                        "gossiped topology renames shard %s (%s -> "
                        "%s:%d); refusing adoption", name,
                        cur.client.address, host, port)
                    return False
                if cur is None:
                    self.peers[name] = Peer(name, host, port,
                                            self.config,
                                            self._spool_dir)
            if active and old_spec:
                old_specs = parse_peer_spec(old_spec)
                for name, host, port in old_specs:
                    if name not in self.peers:
                        self.peers[name] = Peer(name, host, port,
                                                self.config,
                                                self._spool_dir)
                if not self.state.adopt(epoch, spec, vn, old_spec,
                                        old_vnodes or vn, fence_ms):
                    return False
                # same ordering rule as begin_reshard: old_ring
                # fills first so a racing writer's worst case is
                # old-owners-only — which the backfill still moves
                self.old_ring = HashRing(
                    [n for n, _, _ in old_specs],
                    vnodes=old_vnodes or vn)
                self.ring = HashRing([n for n, _, _ in specs],
                                     vnodes=vn)
                self.backfiller.reset()
                resumed = True
            else:
                if not self.state.adopt_final(epoch, spec, vn):
                    return False
                self.old_ring = None
                self.ring = HashRing([n for n, _, _ in specs],
                                     vnodes=vn)
                for n in [n for n in self.peers
                          if n not in self.ring.names]:
                    peer = self.peers.pop(n, None)
                    if peer is not None:
                        peer.spool.close()
                    self.dirty.drop_peer(n)
                    self.read_repair.drop_peer(n)
                    self.invalidate_sub_memo(n)
                self.retirer.reset()
            self._bump_global_version(announce=False)
        LOG.info("adopted gossiped topology at epoch %d (cutover "
                 "%s); ring: %s", epoch,
                 "open" if resumed else "final",
                 ",".join(self.ring.names))
        if self._started:
            if resumed:
                self._start_backfill()
            elif self.retire_enabled and self.retirer.pending():
                self._start_retire()
        return True

    def _backfill_loop(self) -> None:
        tracer = getattr(self.tsdb, "tracer", None)
        while not self._stop.wait(self.reshard_interval_s):
            if self.old_ring is None:
                return
            tctx = tracer.start_background(
                "cluster.reshard.backfill") \
                if tracer is not None and tracer.enabled else None
            info: dict[str, Any] = {}
            try:
                with trace_mod.use(tctx):
                    info = self.backfill_step()
                if tctx is not None:
                    tctx.tag(phase=str(info.get("phase", "")),
                             metric=str(info.get("metric", "")))
                    if info.get("phase") == "blocked":
                        # an idle/blocked poll is not worth a
                        # retained trace
                        tctx.sampled = False
            except Exception:  # noqa: BLE001 - keep the loop alive
                LOG.exception("backfill step failed")
            finally:
                if tracer is not None and tctx is not None:
                    tracer.finish(tctx)
            if info.get("phase") in ("done", "idle"):
                return

    def _retire_loop(self) -> None:
        tracer = getattr(self.tsdb, "tracer", None)
        while not self._stop.wait(self.retire_interval_s):
            if self.old_ring is not None:
                return  # a NEW cutover opened: finalize re-arms us
            tctx = tracer.start_background("cluster.retire") \
                if tracer is not None and tracer.enabled else None
            info: dict[str, Any] = {}
            try:
                with trace_mod.use(tctx):
                    info = self.retirer.step()
                if tctx is not None:
                    tctx.tag(phase=str(info.get("phase", "")),
                             metric=str(info.get("metric", "")))
                    if info.get("phase") in ("blocked", "idle"):
                        # an idle/blocked poll is not worth a
                        # retained trace
                        tctx.sampled = False
            except Exception:  # noqa: BLE001 - keep the loop alive
                LOG.exception("retire step failed")
            finally:
                if tracer is not None and tctx is not None:
                    tracer.finish(tctx)
            if info.get("phase") in ("done", "idle", "disabled"):
                return

    def retire_step(self) -> dict[str, Any]:
        """One deterministic stale-copy retire unit (tests/ops; the
        background loop drives the same step)."""
        return self.retirer.step()

    def reshard_info(self) -> dict[str, Any]:
        out = self.state.describe()
        out["rf"] = self.rf
        out["ring"] = {"peers": list(self.ring.names),
                       "vnodes": self.ring.vnodes}
        if self.old_ring is not None:
            out["old_ring"] = {"peers": list(self.old_ring.names),
                               "vnodes": self.old_ring.vnodes}
            out["backfill"] = self.backfiller.health_info()
        out["retire"] = self.retirer.health_info()
        return out

    # ------------------------------------------------------------------
    # suggest/search scatter (the router owns no names of its own)
    # ------------------------------------------------------------------

    def _name_scatter_degraded(self, ring: HashRing,
                               failed: set[str]) -> list[str]:
        """A failed peer degrades a name scatter only when NO member
        of some replica set survived: every name hangs off >= 1
        series, and every series has a live replica otherwise."""
        if not failed:
            return []
        rf = min(self.rf, len(ring.names))
        if rf <= 1:
            return sorted(failed)
        degraded: set[str] = set()
        for t in ring.replica_sets(rf):
            if all(n in failed for n in t):
                degraded.update(t)
        return sorted(degraded)

    def scatter_suggest(self, stype: str, q: str, max_results: int
                        ) -> tuple[list[str], list[str]]:
        """Union one suggest over every read-ring shard (names live
        wherever their series landed, so the union IS the cluster's
        answer). Returns (sorted names capped at ``max_results``,
        degraded shard names — per the replica-coverage rule)."""
        self.scatter_name_queries += 1
        import urllib.parse
        ring, names = self._read_view()
        path = ("/api/suggest?type=" + urllib.parse.quote(stype)
                + "&q=" + urllib.parse.quote(q or "")
                + "&max=" + str(int(max_results)))
        futs = {name: self.pool.submit(
                    self.fetch_guarded, peer, "GET", path)
                for name in names
                if (peer := self.peers.get(name)) is not None}
        out: set[str] = set()
        failed: set[str] = {n for n in names if n not in futs}
        for name, fut in futs.items():
            try:
                status, data = fut.result(
                    timeout=self.timeout_s * 2 + 5)
                if status != 200:
                    raise PeerError(
                        f"suggest answered {status}")
                doc = json.loads(data)
                if not isinstance(doc, list):
                    raise PeerError("suggest body is not a list")
            except (OSError, ValueError,
                    concurrent.futures.TimeoutError):
                peer = self.peers.get(name)
                if peer is not None:
                    peer.query_failures += 1
                failed.add(name)
                continue
            out.update(str(x) for x in doc)
        return (sorted(out)[:max(int(max_results), 0)],
                self._name_scatter_degraded(ring, failed))

    def scatter_lookup(self, metric: str, tags: list[tuple],
                       limit: int, use_meta: bool
                       ) -> tuple[dict[str, Any], list[str]]:
        """Scatter ``/api/search/lookup`` and union the per-shard
        results, deduplicated on (metric, tags) — at RF > 1 every
        series answers from each replica, and per-shard TSUIDs are
        not cluster identities. ``totalResults`` counts the deduped
        union (shards cap their own lists at ``limit``, so it is a
        floor, exactly as the reference's scanner-capped counts
        are)."""
        self.scatter_name_queries += 1
        ring, names = self._read_view()
        body = json.dumps({
            "metric": metric or "",
            "tags": [{"key": k, "value": v} for k, v in tags],
            "limit": int(limit), "useMeta": bool(use_meta),
        }).encode()
        futs = {name: self.pool.submit(
                    self.fetch_guarded, peer, "POST",
                    "/api/search/lookup", body)
                for name in names
                if (peer := self.peers.get(name)) is not None}
        rows: dict[tuple, dict] = {}
        failed: set[str] = {n for n in names if n not in futs}
        for name, fut in futs.items():
            try:
                status, data = fut.result(
                    timeout=self.timeout_s * 2 + 5)
                if status != 200:
                    raise PeerError(f"lookup answered {status}")
                doc = json.loads(data)
                results = doc.get("results") \
                    if isinstance(doc, dict) else None
                if not isinstance(results, list):
                    raise PeerError("lookup body has no results")
            except (OSError, ValueError,
                    concurrent.futures.TimeoutError):
                peer = self.peers.get(name)
                if peer is not None:
                    peer.query_failures += 1
                failed.add(name)
                continue
            for r in results:
                if not isinstance(r, dict):
                    continue
                tags_doc = r.get("tags") or {}
                key = (r.get("metric"),
                       tuple(sorted(tags_doc.items())))
                rows.setdefault(key, r)
        merged = [rows[k] for k in sorted(rows)][:max(int(limit), 0)]
        doc = {"type": "LOOKUP", "metric": metric or "*",
               "limit": int(limit), "time": 0, "results": merged,
               "totalResults": len(rows)}
        return doc, self._name_scatter_degraded(ring, failed)

    def scatter_last(self, specs: list[dict], back_scan: int,
                     resolve: bool
                     ) -> tuple[list[dict], list[str]]:
        """Scatter ``/api/query/last`` over the read ring and keep
        the NEWEST point per series (metric + tags): at RF > 1 every
        series answers once per replica, and after a reshard a former
        owner's stale copy may still answer — both dedupe on the
        series key with the newest timestamp winning (a stale copy is
        by definition not newer than the live one, which dual-write
        kept current). Shards are always asked to resolve names — the
        merge key must be the one cluster-wide spelling, never the
        per-shard TSUID bytes — and metric/tags are stripped back out
        when the client didn't ask for them. Returns (points,
        degraded shard names per the replica-coverage rule)."""
        self.scatter_name_queries += 1
        ring, names = self._read_view()
        body = json.dumps({"queries": specs,
                           "backScan": int(back_scan),
                           "resolveNames": True}).encode()
        futs = {name: self.pool.submit(
                    self.fetch_guarded, peer, "POST",
                    "/api/query/last", body)
                for name in names
                if (peer := self.peers.get(name)) is not None}
        best: dict[tuple, dict] = {}
        failed: set[str] = {n for n in names if n not in futs}
        refused: list[bytes] = []
        for name, fut in futs.items():
            try:
                status, data = fut.result(
                    timeout=self.timeout_s * 2 + 5)
                if status == 400:
                    # a shard that owns no series of a spec'd metric
                    # 400s "no such name": an empty partial from a
                    # healthy shard, kept for the all-shards-agree
                    # parity check below
                    refused.append(data)
                    continue
                if status != 200:
                    raise PeerError(f"query/last answered {status}")
                doc = json.loads(data)
                if not isinstance(doc, list):
                    raise PeerError("query/last body is not a list")
            except (OSError, ValueError,
                    concurrent.futures.TimeoutError):
                peer = self.peers.get(name)
                if peer is not None:
                    peer.query_failures += 1
                failed.add(name)
                continue
            for r in doc:
                if not isinstance(r, dict) or not r.get("metric"):
                    continue
                tags_doc = r.get("tags") or {}
                key = (str(r.get("metric")),
                       tuple(sorted(tags_doc.items())))
                cur = best.get(key)
                if cur is None or int(r.get("timestamp", 0)) \
                        > int(cur.get("timestamp", 0)):
                    best[key] = r
        if refused and not best and not failed \
                and len(refused) == len(futs):
            # single-node parity: every shard that answered rejected
            # every spec — surface the real client error
            try:
                msg = json.loads(refused[0])["error"]["message"]
            except Exception:  # noqa: BLE001
                msg = refused[0].decode("utf-8", "replace")[:200]
            raise BadRequestError(msg)
        points = [best[k] for k in sorted(best)]
        if not resolve:
            points = [{k: v for k, v in r.items()
                       if k not in ("metric", "tags")}
                      for r in points]
        return points, self._name_scatter_degraded(ring, failed)

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def fetch_peer_trace(self, trace_id: str
                         ) -> tuple[list[dict], list[str]]:
        """Stitch support for ``GET /api/trace/<id>``: ask every
        shard for its subtree of the trace (``?local=true`` so the
        request can never recurse). Returns (flat span docs from all
        reachable shards, names of shards that could not answer) —
        an unreachable shard's scatter leg already carries the error
        span from query time, so the stitched tree stays truthful
        without it."""
        spans: list[dict] = []
        incomplete: list[str] = []
        futs = {}
        for name, peer in self.peers.items():
            if peer.breaker.blocking():
                # known-dead peer: don't burn a scatter-pool thread
                # on a guaranteed socket timeout per poll of this
                # endpoint (the error-tagged leg from query time
                # already tells the tree's story)
                incomplete.append(name)
                continue
            futs[name] = self.pool.submit(
                peer.client.request, "GET",
                f"/api/trace/{trace_id}?local=true")
        for name, fut in futs.items():
            try:
                status, data = fut.result(
                    timeout=self.timeout_s + 2)
            except (OSError, concurrent.futures.TimeoutError):
                incomplete.append(name)
                continue
            if status == 404:
                # the shard never saw (or already evicted) the
                # trace: nothing to stitch, not an outage
                continue
            if status != 200:
                # 400 (shard tracing disabled), 5xx, ...: the shard
                # could not answer — the tree is INCOMPLETE, not
                # "this shard recorded nothing"
                incomplete.append(name)
                continue
            try:
                doc = json.loads(data)
            except ValueError:
                incomplete.append(name)
                continue
            spans.extend(doc.get("spans") or [])
        return spans, sorted(incomplete)

    def fleet_stats(self) -> dict[str, Any]:
        """Fleet-merged stats (``GET /api/stats/fleet``): counters
        summed, gauges per-node + min/max, histograms bucket-summed
        at full resolution so a fleet p99 is exact."""
        from opentsdb_tpu.cluster import fleet
        return fleet.fleet_stats(self)

    def fleet_health(self) -> dict[str, Any]:
        """Per-shard health summary for the router's ``/api/health``
        ``fleet`` section (never raises — an unreachable shard is a
        row, not a failure). TTL-cached
        (``tsd.cluster.fleet_health_ttl_ms``): /api/health is a
        probe surface polled every second or two by load balancers —
        without the cache every poll would fan out a network scatter
        per shard, and one hung-but-not-yet-tripped shard would
        stall the probe long enough for the checker to eject a
        healthy router."""
        from opentsdb_tpu.cluster import fleet
        ttl_s = self.config.get_float(
            "tsd.cluster.fleet_health_ttl_ms", 5000.0) / 1000.0
        now = time.monotonic()
        with self._fleet_health_lock:
            doc, stamp = self._fleet_health_cache
            if doc is not None and now - stamp < ttl_s:
                return doc
        doc = fleet.fleet_health(self)
        with self._fleet_health_lock:
            self._fleet_health_cache = (doc, now)
        return doc

    def cluster_status(self) -> dict[str, Any]:
        """The consolidated operator progress surface behind
        ``GET /api/cluster/status``."""
        from opentsdb_tpu.cluster import fleet
        return fleet.cluster_status(self)

    def health_info(self) -> dict[str, Any]:
        return {
            "role": "router",
            "shards": len(self.peers),
            "vnodes": self.ring.vnodes,
            "rf": self.rf,
            "epoch": self.state.epoch,
            "reshard": self.reshard_info(),
            "replica_dirty": self.dirty.health_info(),
            "read_repair": self.read_repair.health_info(),
            "gossip": self.gossip.health_info()
            if self.gossip is not None else None,
            "read_fallbacks": self.read_fallbacks,
            "repairs": self.repairs,
            "repair_points": self.repair_points,
            "queries": self.queries,
            "degraded_queries": self.degraded_queries,
            "cache_hits": self.cache_hits,
            "cache_stores": self.cache_stores,
            "cache_degraded_skips": self.cache_degraded_skips,
            "sub_memo_entries": len(self._sub_memo),
            "sub_memo_skips": self.sub_memo_skips,
            "sub_memo_invalidations": self.sub_memo_invalidations,
            "sub_memo_evictions": self.sub_memo_evictions,
            "sub_retry": {
                "max_concurrent": self.sub_retry_max_concurrent,
                "rounds": self.sub_retry_rounds,
                "singles": self.sub_retry_singles,
                "capped": self.sub_retry_capped,
            },
            "spool_backlog_records": sum(
                p.spool.pending_records for p in self.peers.values()),
            "peers": {name: peer.health_info()
                      for name, peer in sorted(self.peers.items())},
        }

    def collect_stats(self, collector) -> None:
        collector.record("cluster.queries", self.queries)
        collector.record("cluster.queries_degraded",
                         self.degraded_queries)
        collector.record("cluster.epoch", self.state.epoch)
        collector.record("cluster.rf", self.rf)
        collector.record("cluster.read_fallbacks",
                         self.read_fallbacks)
        collector.record("cluster.replica.repairs", self.repairs)
        collector.record("cluster.replica.repair_points",
                         self.repair_points)
        collector.record("cluster.replica.dirty_entries",
                         self.dirty.total_entries)
        rr = self.read_repair.health_info()
        collector.record("cluster.read_repair.depth", rr["depth"])
        collector.record("cluster.read_repair.enqueued",
                         rr["enqueued"])
        collector.record("cluster.read_repair.shed", rr["shed"])
        collector.record("cluster.read_repair.completed",
                         rr["completed"])
        if self.gossip is not None:
            self.gossip.collect_stats(collector)
        collector.record("cluster.name_scatters",
                         self.scatter_name_queries)
        collector.record("cluster.reshard.backfilled_points",
                         self.backfiller.backfilled_points)
        collector.record("cluster.reshard.backfilled_series",
                         self.backfiller.backfilled_series)
        collector.record("cluster.retire.retired_series",
                         self.retirer.retired_series)
        collector.record("cluster.retire.queries",
                         self.retirer.retire_queries)
        collector.record("cluster.retire.failed_steps",
                         self.retirer.failed_steps)
        collector.record("cluster.cache_degraded_skips",
                         self.cache_degraded_skips)
        collector.record("cluster.sub_memo.skips",
                         self.sub_memo_skips)
        collector.record("cluster.sub_memo.invalidations",
                         self.sub_memo_invalidations)
        collector.record("cluster.sub_memo.evictions",
                         self.sub_memo_evictions)
        collector.record("cluster.sub_retry.rounds",
                         self.sub_retry_rounds)
        collector.record("cluster.sub_retry.singles",
                         self.sub_retry_singles)
        collector.record("cluster.sub_retry.capped",
                         self.sub_retry_capped)
        for name, p in sorted(self.peers.items()):
            collector.record("cluster.forwarded_points",
                             p.forwarded_points, peer=name)
            collector.record("cluster.spooled_points",
                             p.spooled_points, peer=name)
            collector.record("cluster.spool_pending",
                             p.spool.pending_records, peer=name)
            collector.record("cluster.replayed_batches",
                             p.replayed_batches, peer=name)
            collector.record("cluster.query_failures",
                             p.query_failures, peer=name)
            collector.record("cluster.hedges", p.hedges, peer=name)
            collector.record("cluster.wire.bytes_out",
                             p.wire_bytes_out, peer=name)
            collector.record("cluster.wire.bytes_in",
                             p.wire_bytes_in, peer=name)
            collector.record("cluster.wire.frames_out",
                             p.wire_frames_out, peer=name)
            collector.record("cluster.wire.frames_in",
                             p.wire_frames_in, peer=name)
            collector.record("cluster.wire.pipeline_depth",
                             p.wire_pipeline_depth, peer=name)
            collector.record("cluster.wire.fallbacks",
                             p.wire_fallbacks, peer=name)
            collector.record("cluster.wire.backpressure_sheds",
                             p.wire_backpressure_sheds, peer=name)
            p.breaker.collect_stats(collector)
        self.cqs.collect_stats(collector)
