"""Per-peer durable write spool: the no-loss half of the handoff.

When a shard is unreachable (dead, hung, breaker open), the router
must still acknowledge client writes — the reference reaches for a
``StorageExceptionHandler`` plugin to requeue failed RPCs
(``PutDataPointRpc`` SEH spool); here the spool is built in and framed
exactly like the WAL (:mod:`opentsdb_tpu.core.wal`): an append-only
file of ``[len u32 | seq u64 | crc32 u32 | payload]`` records behind a
magic header, fsync'd before the client's write is acknowledged. A
torn tail (crash mid-append) fails the CRC and replay stops at the
acknowledged prefix.

Replay tracks its position in a sidecar ``.offset`` file updated
*after* each record is applied — a crash between apply and offset
update replays that record once more, which is harmless: the peer's
point store dedupes ``(ts, value)`` last-write-wins. When the spool
fully drains the file truncates back to the magic header.

FIFO discipline: while a peer's spool is non-empty, NEW writes for
that peer enqueue behind it instead of racing past — so for
*causally ordered* writes (the second issued after the first was
acknowledged) a same-(series, ts) rewrite is never clobbered by an
older spooled value. Writes concurrently in flight while a peer
fails have no defined order, exactly as two concurrent puts to one
standalone TSD don't: one may forward directly while the other lands
in the spool.

With no directory configured (no ``data_dir`` and no
``tsd.cluster.spool.dir``) the spool degrades to an in-memory queue:
the no-loss guarantee then only spans the router process's lifetime,
reported as ``durable: false`` in ``/api/health``.
"""

from __future__ import annotations

import collections
import logging
import os
import struct
import threading
import zlib

LOG = logging.getLogger("cluster.spool")

MAGIC = b"OTSDBSPOOL1\n"
_HDR = struct.Struct("<IQI")  # payload_len, seq, crc32


class SpoolFull(RuntimeError):
    """The spool hit ``tsd.cluster.spool.max_mb``: the write must be
    refused (reported per-point to the client) — silently dropping the
    oldest record would break the no-loss guarantee."""


class PeerSpool:
    """One peer's durable FIFO of serialized forward bodies."""

    def __init__(self, directory: str | None, name: str,
                 max_bytes: int = 256 << 20,
                 compact_bytes: int = 4 << 20):
        self._lock = threading.Lock()
        # serializes whole replay passes: two concurrent replayers
        # would both apply the head record and then pop TWO records —
        # the second one never applied (held across apply_fn, so it
        # must never be taken while holding self._lock)
        self._replay_lock = threading.Lock()
        self.name = name
        self.max_bytes = int(max_bytes)
        self.compact_bytes = int(compact_bytes)
        self.durable = bool(directory)
        self.appended_records = 0
        self.replayed_records = 0
        self.rejected_full = 0
        # >= 0: file end a failed torn-append rollback still owes us
        # (appends refuse until the truncate finally succeeds)
        self._dirty_end = -1
        if not directory:
            self._queue: collections.deque[bytes] = collections.deque()
            self._mem_bytes = 0
            self.path = self.offset_path = ""
            return
        os.makedirs(directory, exist_ok=True)
        self.path = os.path.join(directory, f"{name}.spool")
        self.offset_path = self.path + ".offset"
        self._fh = None
        self._offset = self._load_offset()
        # startup scan: count the pending tail (and stop at a torn
        # record, truncating it off like WAL replay does)
        self._pending, self._pending_bytes, good_end = self._scan()
        if good_end < len(MAGIC):
            # missing or magic-less file: the sidecar offset belongs
            # to a spool that no longer exists — forget it, or replay
            # would seek past EOF forever while appends pile up
            self._offset = 0
        elif self._offset > good_end:
            # stale sidecar PAST the scanned end (crash between the
            # drained-spool truncate and the offset rewrite, or a
            # mangled sidecar): same seek-past-EOF wedge — new
            # appends would never drain. Reset to the header and
            # replay the whole readable file: duplicates are
            # harmless (peer point store dedupes last-write-wins),
            # silent loss is not.
            self._offset = len(MAGIC)
            self._pending, self._pending_bytes, good_end = \
                self._scan()
        self._repair_tail(good_end)

    # ---------------- durable file form ----------------

    def _load_offset(self) -> int:
        try:
            with open(self.offset_path, "r", encoding="ascii") as fh:
                return max(int(fh.read().strip() or 0), 0)
        except (OSError, ValueError):
            return 0

    def _save_offset_locked(self) -> None:
        tmp = self.offset_path + ".tmp"
        with open(tmp, "w", encoding="ascii") as fh:
            fh.write(str(self._offset))
            fh.flush()
            # tsdlint: allow[lock-blocking] the replay position must
            # be durable before the record counts as applied; the
            # lock serializes exactly the append-vs-replay race
            os.fsync(fh.fileno())
        os.replace(tmp, self.offset_path)

    def _scan(self) -> tuple[int, int, int]:
        """(pending records, pending bytes, good_end offset) from the
        current offset to the last intact record."""
        pending = nbytes = 0
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return 0, 0, 0
        good_end = len(MAGIC)
        try:
            with open(self.path, "rb") as fh:
                if fh.read(len(MAGIC)) != MAGIC:
                    LOG.warning("spool %s has bad magic; starting "
                                "fresh", self.path)
                    return 0, 0, 0
                pos = len(MAGIC)
                while pos < size:
                    hdr = fh.read(_HDR.size)
                    if len(hdr) < _HDR.size:
                        break
                    plen, _seq, crc = _HDR.unpack(hdr)
                    payload = fh.read(plen)
                    if len(payload) < plen or \
                            zlib.crc32(payload) != crc:
                        LOG.warning("spool %s torn at offset %d; "
                                    "replay stops there",
                                    self.path, pos)
                        break
                    pos += _HDR.size + plen
                    good_end = pos
                    if pos > max(self._offset, len(MAGIC)):
                        pending += 1
                        nbytes += plen
        except OSError:
            LOG.exception("cannot scan spool %s", self.path)
        return pending, nbytes, good_end

    def _repair_tail(self, good_end: int) -> None:
        if good_end < len(MAGIC):
            # bad magic: drop the unreadable content so _open_locked
            # rewrites a fresh header instead of appending after junk
            try:
                if os.path.exists(self.path):
                    os.truncate(self.path, 0)
            except OSError:  # pragma: no cover - best-effort repair
                pass
            return
        try:
            size = os.path.getsize(self.path)
            if good_end < size:
                os.truncate(self.path, good_end)
                LOG.warning("spool %s: truncated torn tail "
                            "(%d -> %d bytes)", self.path, size,
                            good_end)
        except OSError:  # pragma: no cover - best-effort repair
            pass

    def _open_locked(self):
        if self._fh is None:
            self._fh = open(self.path, "ab", buffering=0)
            if self._fh.tell() == 0:
                self._fh.write(MAGIC)
        return self._fh

    # ---------------- public surface ----------------

    @property
    def pending_records(self) -> int:
        with self._lock:
            if not self.durable:
                return len(self._queue)
            return self._pending

    @property
    def pending_bytes(self) -> int:
        with self._lock:
            if not self.durable:
                return self._mem_bytes
            return self._pending_bytes

    def append(self, payload: bytes) -> None:
        """Durably enqueue one forward body (fsync before return —
        the client's ack rides on this). Raises :class:`SpoolFull`
        past the byte cap."""
        with self._lock:
            if not self.durable:
                if self._mem_bytes + len(payload) > self.max_bytes:
                    self.rejected_full += 1
                    raise SpoolFull(
                        f"spool for {self.name} is full "
                        f"({self.max_bytes} bytes)")
                self._queue.append(payload)
                self._mem_bytes += len(payload)
                self.appended_records += 1
                return
            if self._pending_bytes + len(payload) > self.max_bytes:
                self.rejected_full += 1
                raise SpoolFull(
                    f"spool for {self.name} is full "
                    f"({self.max_bytes} bytes)")
            if self._dirty_end >= 0:
                # a previous torn-append rollback could not truncate:
                # heal now or keep refusing — appending after torn
                # bytes would get this record truncated away later
                os.truncate(self.path, self._dirty_end)
                self._dirty_end = -1
            fh = self._open_locked()
            rec = _HDR.pack(len(payload), self.appended_records + 1,
                            zlib.crc32(payload)) + payload
            start = fh.tell()
            try:
                fh.write(rec)
                # tsdlint: allow[lock-blocking] the client's ack rides
                # on this fsync (no-loss handoff); the lock enforces
                # the spool's FIFO discipline across appenders
                os.fsync(fh.fileno())
            except OSError:
                # roll the torn record back out of the file: the
                # client is refused (correct), but if the partial
                # bytes stayed, LATER acked appends would land after
                # them and _drop_tail_locked would truncate those
                # acked records away when replay hit the torn one
                try:
                    fh.close()
                except OSError:  # pragma: no cover
                    pass
                self._fh = None
                try:
                    os.truncate(self.path, start)
                except OSError:
                    # remember the debt: every later append must
                    # retry this truncate first (and refuse on
                    # failure), or it would land after the torn
                    # bytes and be lost to the corrupt-record heal
                    self._dirty_end = start
                    LOG.exception("cannot roll back torn append in "
                                  "spool %s", self.path)
                raise
            self.appended_records += 1
            self._pending += 1
            self._pending_bytes += len(payload)

    def replay(self, apply_fn, max_records: int = 0) -> int:
        """Apply pending records in order through ``apply_fn(payload)``
        (which raises on failure — replay stops there, position kept).
        Returns records applied; a fully-drained durable spool
        truncates back to the magic header."""
        applied = 0
        with self._replay_lock:
            while max_records <= 0 or applied < max_records:
                with self._lock:
                    if not self.durable:
                        payload = self._queue[0] if self._queue \
                            else None
                    else:
                        payload = self._read_at_offset_locked()
                if payload is None:
                    break
                apply_fn(payload)  # raises => stop, position unchanged
                with self._lock:
                    if not self.durable:
                        self._queue.popleft()
                        self._mem_bytes -= len(payload)
                    else:
                        self._offset = max(self._offset, len(MAGIC)) \
                            + _HDR.size + len(payload)
                        self._pending -= 1
                        self._pending_bytes -= len(payload)
                        self._save_offset_locked()
                        if self._pending == 0:
                            self._truncate_locked()
                        elif self._offset - len(MAGIC) > \
                                max(self.compact_bytes,
                                    self._pending_bytes) and \
                                self._dirty_end < 0:
                            # the drained-at-zero truncate never
                            # fires on a spool that oscillates
                            # without fully draining: drop the
                            # replayed prefix once it outgrows the
                            # pending tail, or the file accretes
                            # replayed records without bound
                            self._compact_locked()
                    self.replayed_records += 1
                applied += 1
        return applied

    def _read_at_offset_locked(self) -> bytes | None:
        if self._pending <= 0:
            return None
        try:
            with open(self.path, "rb") as fh:
                fh.seek(max(self._offset, len(MAGIC)))
                hdr = fh.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    return None
                plen, _seq, crc = _HDR.unpack(hdr)
                payload = fh.read(plen)
                if len(payload) < plen or zlib.crc32(payload) != crc:
                    LOG.warning("spool %s: corrupt record at replay "
                                "offset %d; dropping the tail",
                                self.path, self._offset)
                    # TRUNCATE the unreadable tail (not just zero the
                    # counters): otherwise later appends land after
                    # the corrupt bytes and every replay re-reads the
                    # corrupt head and declares the spool empty — the
                    # new records would never drain
                    self._drop_tail_locked()
                    return None
                return payload
        except OSError:
            LOG.exception("cannot read spool %s", self.path)
            return None

    def _drop_tail_locked(self) -> None:
        """Cut the file back to the replay offset after a mid-file
        corrupt record (caller holds ``self._lock``)."""
        try:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            os.truncate(self.path, max(self._offset, len(MAGIC)))
        except OSError:  # pragma: no cover - disk trouble
            LOG.exception("cannot truncate corrupt spool %s",
                          self.path)
        self._pending = 0
        self._pending_bytes = 0

    def _compact_locked(self) -> None:
        """Rewrite the file without the replayed prefix (caller holds
        ``self._lock``). Crash ordering: the offset resets to the
        header BEFORE the file is replaced — a crash in between
        replays the old prefix again (duplicates, deduped last-write-
        wins on the peer), never the reverse (an offset pointing
        mid-record into the compacted file would read garbage and
        the torn-tail heal would drop acked records)."""
        tmp = self.path + ".compact"
        try:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            with open(self.path, "rb") as src:
                src.seek(self._offset)
                tail = src.read()
            with open(tmp, "wb") as dst:
                dst.write(MAGIC + tail)
                dst.flush()
                # tsdlint: allow[lock-blocking] compaction rewrites
                # the file appends race against; holding the lock for
                # the (bounded, compact_mb-sized) copy IS the safety
                os.fsync(dst.fileno())
            self._offset = len(MAGIC)
            self._save_offset_locked()
            os.replace(tmp, self.path)
            dfd = os.open(os.path.dirname(self.path), os.O_RDONLY)
            try:
                # tsdlint: allow[lock-blocking] directory fsync pins
                # the rename; same bounded compaction critical section
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:  # pragma: no cover - disk trouble
            LOG.exception("cannot compact spool %s", self.path)
            # the offset may already point at the header while the
            # old file survived: resync the counters from a fresh
            # scan, or a later drained-at-zero truncate could fire
            # at the wrong position and drop acked records
            self._pending, self._pending_bytes, good_end = \
                self._scan()
            self._repair_tail(good_end)

    def _truncate_locked(self) -> None:
        try:
            if self._fh is not None:
                self._fh.close()
                self._fh = None
            os.truncate(self.path, len(MAGIC))
            self._offset = len(MAGIC)
            self._save_offset_locked()
        except OSError:  # pragma: no cover - disk trouble
            LOG.exception("cannot truncate drained spool %s",
                          self.path)

    def close(self) -> None:
        with self._lock:
            if self.durable and self._fh is not None:
                try:
                    self._fh.close()
                except OSError:  # pragma: no cover
                    pass
                self._fh = None

    def health_info(self) -> dict:
        return {
            "durable": self.durable,
            "pending_records": self.pending_records,
            "pending_bytes": self.pending_bytes,
            "appended_records": self.appended_records,
            "replayed_records": self.replayed_records,
            "rejected_full": self.rejected_full,
        }
