"""Binary columnar cluster wire: persistent framed router↔shard links.

Per-request JSON HTTP forwarding tears the front doors' columnar
batches (PR 6) back into per-point dicts on every router↔shard hop —
the BENCH_E2E ``cluster`` config measured router ingest at 0.38x a
single node and scatter reads at 3.37x. This module keeps both data
paths columnar end to end over ONE persistent connection per peer and
direction:

- **frames** are the spool's proven ``len|seq|crc`` shape
  (:mod:`opentsdb_tpu.cluster.spool`) lifted to the socket: a 17-byte
  ``<IIBQ`` header (payload length, CRC32, frame type, sequence)
  followed by the payload. A short read, CRC mismatch or oversized
  length means the stream is torn — the connection dies, exactly like
  a torn spool tail truncates the file. No resync is attempted:
  reconnect + retry (writes are idempotent last-write-wins per
  series) is the recovery story.
- **writes** (``T_WRITE`` → ``T_WRITE_ACK``) carry series-grouped
  column blocks: per group a metric, a tags JSON blob, ``int64``
  timestamps, ``float64`` values and a packed int-ness bitmask. The
  shard lands a delivered block through ``TSDB.add_point_groups`` —
  one WAL write, one group-committed fsync, zero intermediate JSON.
  Requests PIPELINE: concurrent router deliveries interleave on the
  socket and complete by sequence-matched acks, bounded by
  ``tsd.cluster.wire.max_inflight``; past the bound the router sheds
  the batch into the peer's durable spool (:class:`WireBacklogged`)
  instead of blocking — spool-style backpressure, never a stall.
- **reads** (``T_QUERY`` → ``T_QRES``* → ``T_QDONE``) stream each
  sub-query's partial grids as framed column blocks AS THE SHARD
  FINISHES THEM, so the router's incremental merge
  (``cluster/merge.StreamMerger``) tracks the slowest shard's first
  byte, not its last.
- **negotiation**: the router opens with ``MAGIC`` + a ``T_HELLO``
  frame. A version-matched shard answers ``T_HELLO_ACK``; anything
  else — an old server routing ``TSDW`` to its telnet parser, a
  closed socket from a ``tsd.cluster.wire.enable=false`` gate, a
  version mismatch — fails the handshake and marks the peer
  HTTP-only for ``tsd.cluster.wire.fallback_ttl_ms``
  (:class:`WireUnsupported`). JSON HTTP remains a first-class
  transport: version skew degrades throughput, never correctness.
- **failure contracts** carry over exactly: transport failures raise
  ``OSError`` subclasses so the router's breaker/spool/degraded
  machinery fires unchanged; :class:`WireUnsupported`,
  :class:`WireBacklogged` and :class:`WireEncodeError` deliberately
  do NOT subclass ``OSError`` — they reroute (to HTTP or the spool)
  without recording a peer failure the peer never committed. Trace
  identity rides a frame header field (the ``X-TSD-Trace``
  equivalent), and the ``cluster.wire`` / ``cluster.wire.<peer>``
  fault sites inject into the wire exchange exactly like
  ``cluster.peer`` injects into HTTP.

Encoding is STRICT on the write path: only canonical datapoints
(``{metric, timestamp, value, tags}`` with a real ``int``/``float``
value and all-string tags) are wire-encodable. Anything else — string
values, exotic key sets, >2^53 integers — raises
:class:`WireEncodeError` and the whole batch falls back to JSON HTTP,
where the shard's validation answers byte-identically to today. The
wire never widens or narrows the accept set.
"""

from __future__ import annotations

import json
import logging
import queue as queue_mod
import socket
import struct
import threading
import time
import zlib
from typing import Any, Callable

import asyncio

import numpy as np

from opentsdb_tpu.obs.trace import TRACE_HEADER, trace_begin, trace_end

LOG = logging.getLogger("cluster.wire")

#: connection preamble the server sniffs (4 bytes, like HTTP methods)
MAGIC = b"TSDW"
WIRE_VERSION = 1
#: frames above this are protocol damage, not data (the spool's
#: sanity-bound idiom): a torn length field must not allocate 4 GiB
MAX_FRAME = 1 << 26

_HDR = struct.Struct("<IIBQ")  # payload_len, crc32, frame type, seq
_U16 = struct.Struct("<H")
_U32 = struct.Struct("<I")

T_HELLO = 1       # router -> shard: {"v": WIRE_VERSION}
T_HELLO_ACK = 2   # shard -> router: {"v": WIRE_VERSION}
T_WRITE = 3       # router -> shard: columnar put batch
T_WRITE_ACK = 4   # shard -> router: u16 status + put-summary body
T_QUERY = 5       # router -> shard: trace + TSQuery JSON body
T_QRES = 6        # shard -> router: one chunk of partial grids
T_QDONE = 7       # shard -> router: u16 status + error body (if any)
T_CQ = 8          # router -> shard: continuous-query control op
T_CQ_RES = 9      # shard -> router: u16 status + JSON body

_DP_KEYS = frozenset({"metric", "timestamp", "value", "tags"})


class WireUnsupported(RuntimeError):
    """The peer does not (currently) speak this wire version: fall
    back to JSON HTTP. NOT an ``OSError`` — the peer is alive, so the
    breaker must not record a failure it never committed."""


class WireBacklogged(RuntimeError):
    """The peer's wire pipeline is at ``max_inflight``: shed this
    batch into the durable spool instead of blocking the router. NOT
    an ``OSError`` — backpressure is not peer damage."""


class WireEncodeError(RuntimeError):
    """The batch is not canonically wire-encodable (string values,
    exotic keys, >2^53 integers): deliver it over JSON HTTP so shard
    validation answers exactly as it always has."""


class WireProtocolError(Exception):
    """The frame stream is torn (bad CRC, oversized length, trailing
    bytes): the connection is unrecoverable and must close."""


# ---------------------------------------------------------------------------
# frame codec
# ---------------------------------------------------------------------------

def _frame(ftype: int, seq: int, payload: bytes) -> bytes:
    if len(payload) > MAX_FRAME:
        raise WireEncodeError(
            f"frame payload of {len(payload)} bytes exceeds the "
            f"{MAX_FRAME}-byte wire bound")
    return _HDR.pack(len(payload), zlib.crc32(payload) & 0xFFFFFFFF,
                     ftype, seq) + payload


def encode_status(status: int, body: bytes = b"") -> bytes:
    """``T_WRITE_ACK`` / ``T_QDONE`` payload: the HTTP exchange's
    (status, body) tuple, verbatim — summary docs, structured errors
    and no-such-name 400 bodies cross the wire unchanged so every
    router-side body check keeps working."""
    return _U16.pack(int(status) & 0xFFFF) + (body or b"")


def decode_status(payload: bytes) -> tuple[int, bytes]:
    (status,) = _U16.unpack_from(payload, 0)
    return status, payload[2:]


def encode_query(trace: str, body: bytes) -> bytes:
    tb = (trace or "").encode("utf-8")
    if len(tb) > 0xFFFF:
        tb = b""  # a malformed giant header is droppable, not fatal
    return _U16.pack(len(tb)) + tb + body


def decode_query(payload: bytes) -> tuple[str, bytes]:
    (tl,) = _U16.unpack_from(payload, 0)
    return payload[2:2 + tl].decode("utf-8", "replace"), \
        payload[2 + tl:]


def encode_cq(trace: str, method: str, path: str,
              body: bytes) -> bytes:
    """``T_CQ`` payload: one continuous-query control op — register,
    delete, pull, delta drain — as an HTTP-shaped (method, path,
    body) replay. The shard routes it through the REAL HTTP handler,
    so QoS gates, fault sites and chaos hangs cover the wire path
    identically to the JSON path."""
    tb = (trace or "").encode("utf-8")
    if len(tb) > 0xFFFF:
        tb = b""
    mb = method.encode("ascii")
    pb = path.encode("utf-8")
    if len(mb) > 0xFF or len(pb) > 0xFFFF:
        raise WireEncodeError("oversized CQ method/path")
    return _U16.pack(len(tb)) + tb + bytes([len(mb)]) + mb + \
        _U16.pack(len(pb)) + pb + (body or b"")


def decode_cq(payload: bytes) -> tuple[str, str, str, bytes]:
    try:
        (tl,) = _U16.unpack_from(payload, 0)
        off = 2 + tl
        trace = payload[2:off].decode("utf-8", "replace")
        ml = payload[off]
        off += 1
        method = payload[off:off + ml].decode("ascii")
        off += ml
        (pl,) = _U16.unpack_from(payload, off)
        off += 2
        path = payload[off:off + pl].decode("utf-8")
        off += pl
    except (struct.error, IndexError, UnicodeDecodeError) as exc:
        raise WireProtocolError(f"torn CQ frame: {exc}") from exc
    return trace, method, path, payload[off:]


# -- write batches ----------------------------------------------------------

def encode_write(dps: list, trace: str = "") -> bytes:
    """Series-grouped column blocks for one put batch. STRICT: any
    non-canonical datapoint raises :class:`WireEncodeError` and the
    caller delivers the whole batch over HTTP instead — the wire
    carries only values that survive an f64/i64 round trip exactly,
    so shard-side semantics cannot drift from the JSON path."""
    tb = (trace or "").encode("utf-8")
    if len(tb) > 0xFFFF:
        tb = b""
    groups: dict[tuple, tuple] = {}
    for dp in dps:
        if type(dp) is not dict or not _DP_KEYS >= dp.keys():
            raise WireEncodeError("non-canonical datapoint shape")
        metric = dp.get("metric")
        if type(metric) is not str or not metric:
            raise WireEncodeError("non-canonical metric")
        ts = dp.get("timestamp")
        if type(ts) is not int or not -(1 << 63) <= ts < (1 << 63):
            raise WireEncodeError("non-canonical timestamp")
        v = dp.get("value")
        if type(v) is int:
            if not -(1 << 53) < v < (1 << 53):
                raise WireEncodeError(
                    "integer value beyond f64 precision")
            is_int = 1
        elif type(v) is float:
            is_int = 0
        else:
            raise WireEncodeError("non-canonical value")
        tags = dp.get("tags")
        if tags is None:
            tags = {}
        elif type(tags) is not dict or not all(
                type(k) is str and type(tv) is str
                for k, tv in tags.items()):
            raise WireEncodeError("non-canonical tags")
        key = (metric, tuple(sorted(tags.items())))
        g = groups.get(key)
        if g is None:
            g = groups[key] = (metric, tags, [], [], [])
        g[2].append(ts)
        g[3].append(v)
        g[4].append(is_int)
    parts = [_U16.pack(len(tb)), tb, _U32.pack(len(groups))]
    for metric, tags, ts_list, vals, masks in groups.values():
        mb = metric.encode("utf-8")
        if len(mb) > 0xFFFF:
            raise WireEncodeError("non-canonical metric")
        tj = json.dumps(tags).encode("utf-8")
        parts.extend((
            _U16.pack(len(mb)), mb, _U32.pack(len(tj)), tj,
            _U32.pack(len(ts_list)),
            np.asarray(ts_list, dtype="<i8").tobytes(),
            np.asarray(vals, dtype="<f8").tobytes(),
            np.packbits(np.asarray(masks, dtype=np.uint8),
                        bitorder="little").tobytes()))
    payload = b"".join(parts)
    if len(payload) > MAX_FRAME:
        raise WireEncodeError("batch exceeds the one-frame wire bound")
    return payload


def decode_write(payload: bytes) -> tuple[str, list[tuple]]:
    """-> (trace header value, groups) where each group is the
    ``(metric, tags, dp_refs, ts_list, values)`` tuple
    ``TSDB.add_point_groups`` (and the put handler's error reporting)
    expects — ``values`` restores Python ``int``-ness from the packed
    mask so shard storage sees exactly what the JSON path decodes."""
    try:
        off = 0
        (tl,) = _U16.unpack_from(payload, off)
        off += 2
        trace = payload[off:off + tl].decode("utf-8", "replace")
        off += tl
        (ng,) = _U32.unpack_from(payload, off)
        off += 4
        groups: list[tuple] = []
        for _ in range(ng):
            (ml,) = _U16.unpack_from(payload, off)
            off += 2
            metric = payload[off:off + ml].decode("utf-8")
            off += ml
            (tjl,) = _U32.unpack_from(payload, off)
            off += 4
            tags = json.loads(payload[off:off + tjl])
            off += tjl
            (n,) = _U32.unpack_from(payload, off)
            off += 4
            ts = np.frombuffer(payload, dtype="<i8", count=n,
                               offset=off)
            off += 8 * n
            vals = np.frombuffer(payload, dtype="<f8", count=n,
                                 offset=off)
            off += 8 * n
            nmb = (n + 7) // 8
            mask = np.unpackbits(
                np.frombuffer(payload, dtype=np.uint8, count=nmb,
                              offset=off),
                count=n, bitorder="little")
            off += nmb
            ts_list = ts.tolist()
            values = [int(v) if m else v
                      for v, m in zip(vals.tolist(), mask.tolist())]
            refs = [{"metric": metric, "timestamp": t, "value": v,
                     "tags": tags}
                    for t, v in zip(ts_list, values)]
            groups.append((metric, tags, refs, ts_list, values))
        if off != len(payload):
            raise WireProtocolError("trailing bytes in write frame")
        return trace, groups
    except WireProtocolError:
        raise
    except Exception as exc:  # struct/json/unicode: the frame is torn
        raise WireProtocolError(
            f"undecodable write frame: {exc}") from exc


# -- streamed partial grids -------------------------------------------------

def _integral_mask(vals: np.ndarray) -> np.ndarray:
    """The serializer's int-emission rule (json_serializer.py): finite,
    |v| < 2^53 and integral — the exact set of values HTTP JSON would
    have emitted as ints, so the router-side merge and any row
    iteration see identical Python values on either transport."""
    finite = np.isfinite(vals)
    return finite & (np.abs(vals) < 2 ** 53) \
        & (vals == np.floor(np.where(finite, vals, 0.0)))


def _encode_qres_row(r, tsq) -> bytes:
    """One QueryResult as meta-JSON + ts/vals columns + int mask. The
    meta carries exactly what ``_result_head`` would have (gated the
    same way); ``query.index`` is restored router-side from the
    chunk's sub index."""
    meta: dict[str, Any] = {"metric": r.metric, "tags": r.tags,
                            "aggregateTags": r.aggregated_tags}
    if r.tsuids:
        meta["tsuids"] = r.tsuids
    if not tsq.no_annotations and r.annotations:
        meta["annotations"] = [a.to_json() for a in r.annotations]
    if tsq.global_annotations and r.global_annotations:
        meta["globalAnnotations"] = [a.to_json()
                                     for a in r.global_annotations]
    if getattr(r, "sketches", None):
        # sketch partials travel in the meta (b64, same shape the
        # HTTP serializer emits) — decode_qres restores them wholesale
        import base64
        meta["sketchDps"] = [
            [int(t), base64.b64encode(b).decode("ascii")]
            for t, b in r.sketches]
    arrs = getattr(r, "dps_arrays", None)
    if arrs is not None:
        ts_arr = np.ascontiguousarray(arrs[0], dtype="<i8")
        vals = np.ascontiguousarray(arrs[1], dtype="<f8")
    else:
        pts = list(r.dps)
        ts_arr = np.asarray([p[0] for p in pts], dtype="<i8")
        vals = np.asarray([float(p[1]) for p in pts], dtype="<f8")
    mj = json.dumps(meta).encode("utf-8")
    return b"".join((
        _U32.pack(len(mj)), mj, _U32.pack(int(ts_arr.size)),
        ts_arr.tobytes(), vals.tobytes(),
        np.packbits(_integral_mask(vals),
                    bitorder="little").tobytes()))


def qres_frames(seq: int, sub_index: int, results: list, tsq,
                chunk_bytes: int = 1 << 20) -> list[bytes]:
    """One sub-query's results as a list of ready-to-send ``T_QRES``
    frames, chunked near ``chunk_bytes`` so a giant sub streams
    instead of buffering whole (an empty sub emits no frames — the
    router treats absence as the empty partial it is)."""
    frames: list[bytes] = []
    head = _U32.pack(sub_index)
    rows: list[bytes] = []
    size = 0
    for r in results:
        rb = _encode_qres_row(r, tsq)
        rows.append(rb)
        size += len(rb)
        if size >= chunk_bytes:
            frames.append(_frame(T_QRES, seq, b"".join(
                (head, _U32.pack(len(rows)), *rows))))
            rows = []
            size = 0
    if rows:
        frames.append(_frame(T_QRES, seq, b"".join(
            (head, _U32.pack(len(rows)), *rows))))
    return frames


class WireDps:
    """Columnar stand-in for a JSON ``dps`` arrays list: iterates
    ``(int ts, int|float value)`` pairs exactly as ``json.loads`` of
    the HTTP arrays form would yield them, so repair/backfill row
    walks work on either transport without copying."""

    __slots__ = ("ts", "values", "int_mask")

    def __init__(self, ts: np.ndarray, values: np.ndarray,
                 int_mask: np.ndarray):
        self.ts = ts
        self.values = values
        self.int_mask = int_mask

    def __len__(self) -> int:
        return int(self.ts.size)

    def __bool__(self) -> bool:
        return self.ts.size > 0

    def __iter__(self):
        for t, v, m in zip(self.ts.tolist(), self.values.tolist(),
                           self.int_mask.tolist()):
            yield (t, int(v)) if m else (t, v)


def decode_qres(payload: bytes) -> tuple[int, list[dict]]:
    """-> (sub index, result-row dicts shaped like the HTTP arrays
    response rows, with ``dps`` as a :class:`WireDps` column view)."""
    try:
        off = 0
        (sub_index,) = _U32.unpack_from(payload, off)
        off += 4
        (nrows,) = _U32.unpack_from(payload, off)
        off += 4
        rows: list[dict] = []
        for _ in range(nrows):
            (mjl,) = _U32.unpack_from(payload, off)
            off += 4
            meta = json.loads(payload[off:off + mjl])
            off += mjl
            (n,) = _U32.unpack_from(payload, off)
            off += 4
            ts = np.frombuffer(payload, dtype="<i8", count=n,
                               offset=off)
            off += 8 * n
            vals = np.frombuffer(payload, dtype="<f8", count=n,
                                 offset=off)
            off += 8 * n
            nmb = (n + 7) // 8
            mask = np.unpackbits(
                np.frombuffer(payload, dtype=np.uint8, count=nmb,
                              offset=off),
                count=n, bitorder="little")
            off += nmb
            meta["query"] = {"index": sub_index}
            meta["dps"] = WireDps(ts, vals, mask)
            rows.append(meta)
        if off != len(payload):
            raise WireProtocolError(
                "trailing bytes in partial-grid frame")
        return sub_index, rows
    except WireProtocolError:
        raise
    except Exception as exc:
        raise WireProtocolError(
            f"undecodable partial-grid frame: {exc}") from exc


# ---------------------------------------------------------------------------
# router side: negotiation, connection, manager
# ---------------------------------------------------------------------------

def _recv_exact(sock: socket.socket, n: int) -> bytes:
    chunks = []
    got = 0
    while got < n:
        chunk = sock.recv(n - got)
        if not chunk:
            raise ConnectionError("peer closed during wire handshake")
        chunks.append(chunk)
        got += len(chunk)
    return b"".join(chunks)


def _negotiate(host: str, port: int, connect_timeout_s: float,
               io_timeout_s: float) -> socket.socket:
    """Open + handshake one wire connection. A pre-connect failure
    propagates as ``OSError`` (the peer is DOWN: breaker/spool
    territory); any post-connect failure — the old server's telnet
    parser never answering, a closed socket from a disabled shard
    gate, a version mismatch — raises :class:`WireUnsupported` (the
    peer is alive but not speaking wire: HTTP fallback territory)."""
    sock = socket.create_connection((host, port),
                                    timeout=connect_timeout_s)
    try:
        sock.settimeout(connect_timeout_s)
        sock.sendall(MAGIC + _frame(
            T_HELLO, 0, json.dumps({"v": WIRE_VERSION}).encode()))
        ln, crc, ftype, _seq = _HDR.unpack(
            _recv_exact(sock, _HDR.size))
        if ftype != T_HELLO_ACK or ln > 4096:
            raise WireProtocolError(
                f"unexpected handshake frame type {ftype}")
        payload = _recv_exact(sock, ln)
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            raise WireProtocolError("handshake frame CRC mismatch")
        if int(json.loads(payload).get("v", 0)) != WIRE_VERSION:
            raise WireProtocolError("wire version mismatch")
    except Exception as exc:
        try:
            sock.close()
        except OSError:
            # tsdlint: allow[swallow] closing a socket the handshake
            # already failed on; the WireUnsupported below carries
            # the real error
            pass
        raise WireUnsupported(
            f"peer {host}:{port} does not speak wire "
            f"v{WIRE_VERSION}: {type(exc).__name__}: {exc}") from exc
    sock.settimeout(io_timeout_s)
    return sock


_DEAD = object()  # broadcast sentinel: the connection died under you


class WireConnection:
    """One persistent, pipelined wire connection (router side).

    Sends interleave under a socket lock; a daemon reader thread
    demultiplexes response frames to per-sequence waiter queues, so
    any number of pool threads share the link concurrently. Any
    transport or protocol failure marks the connection dead, wakes
    every waiter with a ``ConnectionError`` and closes the socket —
    the manager opens a fresh connection on the next use (torn-frame
    truncation semantics: no resync inside a damaged stream)."""

    def __init__(self, name: str, sock: socket.socket,
                 io_timeout_s: float, stats: Any = None):
        self.name = name
        self.sock = sock
        self.timeout_s = io_timeout_s
        self.stats = stats  # Peer counter sink (wire_frames_* etc.)
        self.dead = False
        self.dead_exc: Exception | None = None
        self._wlock = threading.Lock()   # seq + waiter registry
        self._slock = threading.Lock()   # socket sends
        self._seq = 0
        self._waiters: dict[int, queue_mod.Queue] = {}
        self._reader = threading.Thread(
            target=self._read_loop, name=f"tsd-wire-{name}",
            daemon=True)
        self._reader.start()

    # -- reader thread -------------------------------------------------

    def _read_loop(self) -> None:
        buf = b""
        hdr = _HDR.size
        stats = self.stats
        while True:
            while len(buf) >= hdr:
                ln, crc, ftype, seq = _HDR.unpack_from(buf)
                if ln > MAX_FRAME:
                    self._fail(WireProtocolError(
                        f"oversized frame ({ln} bytes) from "
                        f"{self.name}"))
                    return
                if len(buf) < hdr + ln:
                    break
                payload = bytes(buf[hdr:hdr + ln])
                buf = buf[hdr + ln:]
                if zlib.crc32(payload) & 0xFFFFFFFF != crc:
                    self._fail(WireProtocolError(
                        f"frame CRC mismatch from {self.name}"))
                    return
                if stats is not None:
                    stats.wire_frames_in += 1
                    stats.wire_bytes_in += hdr + ln
                with self._wlock:
                    q = self._waiters.get(seq)
                if q is not None:
                    q.put((ftype, payload))
                # else: a late frame for an abandoned sequence
                # (timed-out waiter) — drop it
            if self.dead:
                return
            try:
                chunk = self.sock.recv(65536)
            except socket.timeout:
                continue  # idle is normal; partial frames stay in buf
            except OSError as exc:
                self._fail(exc)
                return
            if not chunk:
                self._fail(ConnectionError(
                    f"peer {self.name} closed the wire connection"))
                return
            buf += chunk

    # -- request lifecycle ---------------------------------------------

    def begin(self, ftype: int, payload: bytes
              ) -> tuple[int, queue_mod.Queue]:
        """Register a waiter, then send the request frame. Returns
        (seq, queue); pair with :meth:`end` in a finally."""
        with self._wlock:
            if self.dead:
                raise ConnectionError(
                    f"wire connection to {self.name} is dead: "
                    f"{self.dead_exc}")
            self._seq += 1
            seq = self._seq
            q: queue_mod.Queue = queue_mod.Queue()
            self._waiters[seq] = q
        data = _frame(ftype, seq, payload)
        try:
            with self._slock:
                self.sock.sendall(data)
        except OSError as exc:
            self.end(seq)
            self._fail(exc)
            raise
        if self.stats is not None:
            self.stats.wire_frames_out += 1
            self.stats.wire_bytes_out += len(data)
        return seq, q

    def wait(self, q: queue_mod.Queue, timeout_s: float
             ) -> tuple[int, bytes]:
        """Next response frame for one sequence. A timeout raises
        ``TimeoutError`` (an ``OSError``: breaker/retry territory)
        WITHOUT killing the connection — write acks are in flight
        order, a slow shard is not a torn stream, and a retried
        delivery is idempotent (same-series last-write-wins)."""
        try:
            item = q.get(timeout=max(timeout_s, 0.001))
        except queue_mod.Empty:
            raise TimeoutError(
                f"wire response timeout from {self.name} "
                f"({timeout_s:.1f}s)") from None
        if item is _DEAD:
            raise ConnectionError(
                f"wire connection to {self.name} died: "
                f"{self.dead_exc}")
        return item

    def end(self, seq: int) -> None:
        with self._wlock:
            self._waiters.pop(seq, None)

    def _fail(self, exc: Exception) -> None:
        with self._wlock:
            if self.dead:
                return
            self.dead = True
            self.dead_exc = exc
            waiters = list(self._waiters.values())
        for q in waiters:
            q.put(_DEAD)
        try:
            self.sock.close()
        except OSError:
            # tsdlint: allow[swallow] double-close race on a socket
            # that is already dead; dead_exc carries the real error
            pass

    def close(self) -> None:
        self._fail(ConnectionError(
            f"wire connection to {self.name} closed"))
        if self._reader is not threading.current_thread():
            self._reader.join(timeout=2)


class _ConnSlot:
    """One (peer, direction) connection holder; the slot lock
    serializes reconnects without blocking other peers."""

    __slots__ = ("lock", "conn")

    def __init__(self):
        self.lock = threading.Lock()
        self.conn: WireConnection | None = None


class WireManager:
    """Router-side owner of the per-peer wire links and the HTTP
    fallback policy. Writes and reads use SEPARATE connections per
    peer ('w'/'r') so a shard wedged mid-put cannot stall the read
    scatter's streaming acks."""

    def __init__(self, router):
        self.router = router
        config = router.config
        self.enabled = config.get_bool("tsd.cluster.wire.enable",
                                       True)
        self.max_inflight = max(config.get_int(
            "tsd.cluster.wire.max_inflight", 32), 1)
        self.fallback_ttl_s = config.get_float(
            "tsd.cluster.wire.fallback_ttl_ms", 30000.0) / 1000.0
        self.connect_timeout_s = config.get_float(
            "tsd.cluster.wire.connect_timeout_ms", 1000.0) / 1000.0
        self._lock = threading.Lock()
        # both maps are bounded by the peer set x 2 directions
        self._slots: dict[tuple[str, str], _ConnSlot] = {}
        self._sems: dict[str, threading.BoundedSemaphore] = {}
        # peer name -> monotonic stamp of the failed negotiation;
        # bounded by the peer set, entries expire after fallback_ttl
        self._unsupported: dict[str, float] = {}

    # -- policy --------------------------------------------------------

    def usable(self, peer) -> bool:
        """Whether the next exchange with this peer should try the
        wire (vs going straight to HTTP)."""
        if not self.enabled:
            return False
        if self.router.hedge_after_s > 0:
            # tail-latency hedging races duplicate HTTP requests;
            # the wire has no duplicate-cancel story, so a hedged
            # router keeps the HTTP transport wholesale
            return False
        with self._lock:
            stamp = self._unsupported.get(peer.name)
            if stamp is None:
                return True
            if time.monotonic() - stamp >= self.fallback_ttl_s:
                del self._unsupported[peer.name]
                return True
            return False

    def _mark_unsupported(self, peer) -> None:
        with self._lock:
            self._unsupported[peer.name] = time.monotonic()
        peer.wire_fallbacks += 1
        LOG.info("peer %s does not speak wire v%d; HTTP fallback for "
                 "%.0fs", peer.name, WIRE_VERSION, self.fallback_ttl_s)

    def _check_faults(self, peer) -> None:
        """``cluster.wire`` twin of the router's ``cluster.peer``
        sites: an armed fault raises ``InjectedFault`` (an OSError)
        INSIDE the guarded exchange, driving breaker/spool/degrade
        exactly like real wire damage."""
        faults = getattr(self.router.tsdb, "faults", None)
        if faults is not None:
            faults.check("cluster.wire")
            faults.check(f"cluster.wire.{peer.name}")

    # -- connections ---------------------------------------------------

    def _slot(self, peer, kind: str) -> _ConnSlot:
        with self._lock:
            return self._slots.setdefault((peer.name, kind),
                                          _ConnSlot())

    def _sem(self, name: str) -> threading.BoundedSemaphore:
        with self._lock:
            sem = self._sems.get(name)
            if sem is None:
                sem = self._sems[name] = threading.BoundedSemaphore(
                    self.max_inflight)
            return sem

    def _conn(self, peer, kind: str) -> WireConnection:
        slot = self._slot(peer, kind)
        with slot.lock:
            conn = slot.conn
            if conn is not None and not conn.dead:
                return conn
            sp = trace_begin("cluster.wire.connect", peer=peer.name,
                             kind=kind)
            try:
                sock = _negotiate(peer.client.host, peer.client.port,
                                  self.connect_timeout_s,
                                  self.router.timeout_s)
            except WireUnsupported as exc:
                trace_end(sp, error=exc)
                self._mark_unsupported(peer)
                raise
            except BaseException as exc:
                trace_end(sp, error=exc)
                raise
            trace_end(sp)
            conn = WireConnection(f"{peer.name}-{kind}", sock,
                                  self.router.timeout_s, stats=peer)
            slot.conn = conn
            peer.wire_connects += 1
            return conn

    def close_all(self) -> None:
        with self._lock:
            slots = list(self._slots.values())
            self._slots.clear()
            self._sems.clear()
            self._unsupported.clear()
        for slot in slots:
            with slot.lock:
                conn, slot.conn = slot.conn, None
            if conn is not None:
                conn.close()

    # -- data paths ----------------------------------------------------

    def put_batch(self, peer, dps: list | None = None,
                  body: bytes | None = None,
                  headers: dict[str, str] | None = None
                  ) -> tuple[int, bytes]:
        """One columnar put delivery; returns the HTTP-shaped
        (status, summary body). Raises :class:`WireEncodeError`
        BEFORE touching the socket for non-canonical batches,
        :class:`WireBacklogged` when the pipeline is at max_inflight
        (shed to spool), :class:`WireUnsupported` when negotiation
        says HTTP, and ``OSError`` for transport failures
        (breaker/spool territory)."""
        if dps is None:
            try:
                dps = json.loads(body)
            except Exception as exc:  # noqa: BLE001 - odd spool body
                raise WireEncodeError(
                    f"undecodable batch body: {exc}") from exc
        trace = (headers or {}).get(TRACE_HEADER, "")
        payload = encode_write(dps, trace)
        self._check_faults(peer)
        conn = self._conn(peer, "w")
        sem = self._sem(peer.name)
        if not sem.acquire(blocking=False):
            raise WireBacklogged(
                f"wire pipeline to {peer.name} is at "
                f"{self.max_inflight} in flight")
        depth = peer.wire_pipeline_depth = peer.wire_pipeline_depth + 1
        if depth > peer.wire_pipeline_max:
            peer.wire_pipeline_max = depth
        try:
            seq, q = conn.begin(T_WRITE, payload)
            try:
                ftype, ack = conn.wait(q, self.router.timeout_s)
            finally:
                conn.end(seq)
            if ftype != T_WRITE_ACK:
                conn.close()
                raise ConnectionError(
                    f"peer {peer.name} answered frame type {ftype} "
                    f"to a write")
            return decode_status(ack)
        finally:
            peer.wire_pipeline_depth -= 1
            sem.release()

    def cq(self, peer, method: str, path: str, body: bytes = b"",
           headers: dict[str, str] | None = None) -> tuple[int, bytes]:
        """One continuous-query control exchange (register / delete /
        pull / delta drain) over the persistent read connection;
        returns the HTTP-shaped (status, body). Raises
        :class:`WireUnsupported` when negotiation says HTTP and
        ``OSError`` for transport failures — callers fall back to the
        JSON path on the former and degrade the shard on the latter."""
        trace = (headers or {}).get(TRACE_HEADER, "")
        payload = encode_cq(trace, method, path, body)
        self._check_faults(peer)
        conn = self._conn(peer, "r")
        seq, q = conn.begin(T_CQ, payload)
        try:
            ftype, ack = conn.wait(q, self.router.timeout_s)
        finally:
            conn.end(seq)
        if ftype != T_CQ_RES:
            conn.close()
            raise ConnectionError(
                f"peer {peer.name} answered frame type {ftype} "
                f"to a CQ op")
        return decode_status(ack)

    def query(self, peer, body: bytes,
              headers: dict[str, str] | None = None
              ) -> tuple[int, Any]:
        """One streamed scatter leg: returns ``(200, decoded result
        rows)`` — partial grids decoded AS THEY ARRIVE — or
        ``(status, error body bytes)`` for non-200 answers, so every
        router-side status/body check works unchanged."""
        trace = (headers or {}).get(TRACE_HEADER, "")
        payload = encode_query(trace, body)
        self._check_faults(peer)
        conn = self._conn(peer, "r")
        rows: list[dict] = []
        # per-frame gap bound + overall deadline, mirroring the HTTP
        # path's socket timeout + fut.result cap
        deadline = time.monotonic() + self.router.timeout_s * 2
        seq, q = conn.begin(T_QUERY, payload)
        try:
            while True:
                gap = min(self.router.timeout_s,
                          deadline - time.monotonic())
                if gap <= 0:
                    raise TimeoutError(
                        f"streamed read from {peer.name} exceeded "
                        f"{self.router.timeout_s * 2:.1f}s")
                ftype, data = conn.wait(q, gap)
                if ftype == T_QRES:
                    try:
                        _sub, part = decode_qres(data)
                    except WireProtocolError as exc:
                        conn.close()
                        raise ConnectionError(str(exc)) from exc
                    rows.extend(part)
                    continue
                if ftype == T_QDONE:
                    status, done = decode_status(data)
                    if status == 200:
                        return 200, rows
                    return status, done
                conn.close()
                raise ConnectionError(
                    f"peer {peer.name} answered frame type {ftype} "
                    f"to a query")
        finally:
            conn.end(seq)


# ---------------------------------------------------------------------------
# shard side: the accept-loop session
# ---------------------------------------------------------------------------

async def _read_frame(reader) -> tuple[int, int, bytes]:
    hdr = await reader.readexactly(_HDR.size)
    ln, crc, ftype, seq = _HDR.unpack(hdr)
    if ln > MAX_FRAME:
        raise WireProtocolError(f"oversized frame ({ln} bytes)")
    payload = await reader.readexactly(ln)
    if zlib.crc32(payload) & 0xFFFFFFFF != crc:
        raise WireProtocolError("frame CRC mismatch")
    return ftype, seq, payload


async def serve_wire(server, reader, writer) -> None:
    """One shard-side wire session (the server sniffed ``MAGIC``).

    Structure: a read loop dispatches frames — writes to a SERIAL
    worker (frame order is delivery order, like the HTTP keep-alive
    pipeline), queries to per-request tasks that run on the query
    pool under the SAME admission/timeout/SLO discipline as
    ``_serve_http`` — while a sender task drains one output queue
    (frames from executor threads hop in via
    ``call_soon_threadsafe``, which keeps every partial-grid frame
    ordered before its ``T_QDONE``). A watchdog closes the session
    when the listener stops serving, because chaos harnesses (and
    ``stop()``) close only the LISTENER — without it a persistent
    wire connection would outlive its killed server and the router
    would never see the failure."""
    tsdb = server.tsdb
    if not tsdb.config.get_bool("tsd.cluster.wire.enable", True):
        return  # close without an ack = "speak HTTP" to the router
    try:
        ftype, _seq, payload = await asyncio.wait_for(
            _read_frame(reader), 5)
        if ftype != T_HELLO or \
                int(json.loads(payload).get("v", 0)) != WIRE_VERSION:
            return
    except Exception:  # noqa: BLE001
        # tsdlint: allow[swallow] a malformed handshake is a client
        # that cannot speak wire: closing IS the negotiated answer
        return
    writer.write(_frame(T_HELLO_ACK, 0,
                        json.dumps({"v": WIRE_VERSION}).encode()))
    await writer.drain()

    loop = asyncio.get_event_loop()
    outq: asyncio.Queue = asyncio.Queue()
    wq: asyncio.Queue = asyncio.Queue()
    qtasks: set[asyncio.Task] = set()
    peername = writer.get_extra_info("peername")
    remote = f"{peername[0]}:{peername[1]}" if peername else ""

    def listener_dead() -> bool:
        # the kill idioms (tests' LivePeer.kill, bench Peer.kill,
        # server.stop) close the LISTENER and model "the network
        # died": a persistent session must honor that the moment a
        # request arrives (or an answer would leave), or a killed
        # shard would keep serving through pre-established links —
        # the failure contract HTTP gets for free from per-request
        # connects
        srv = server._server
        return srv is None or not srv.is_serving()

    async def sender() -> None:
        while True:
            data = await outq.get()
            if listener_dead():
                return  # drop the answer: the shard is "down"
            writer.write(data)
            await writer.drain()

    def handle_write(seq: int, payload: bytes) -> bytes:
        # executor thread: decode columns -> the REAL put handler
        # (server.http_router.handle, a dynamic attribute on purpose:
        # chaos hang("/api/put") swaps it and must catch wire writes
        # too) with the decoded groups attached — add_point_groups
        # lands the block as one WAL write + one fsync, zero JSON
        from opentsdb_tpu.tsd.http_api import HttpRequest
        t0 = time.monotonic()
        trace, groups = decode_write(payload)
        req = HttpRequest(
            method="POST", path="/api/put",
            params={"summary": ["true"], "details": ["true"]},
            headers={TRACE_HEADER: trace} if trace else {},
            body=b"", remote=remote, received_at=t0)
        req.wire_groups = groups
        resp = server.http_router.handle(req)
        elapsed_ms = (time.monotonic() - t0) * 1000
        tsdb.stats.latency_put.add(elapsed_ms)
        if tsdb.slo.enabled:
            tsdb.slo.record("put", elapsed_ms, resp.status >= 500)
        return _frame(T_WRITE_ACK, seq,
                      encode_status(resp.status, resp.body))

    async def write_worker() -> None:
        while True:
            seq, payload = await wq.get()
            try:
                data = await loop.run_in_executor(
                    None, handle_write, seq, payload)
            except WireProtocolError:
                raise  # torn payload: the session must die
            except Exception as exc:  # noqa: BLE001 - per-batch 500
                LOG.exception("wire write failed")
                data = _frame(T_WRITE_ACK, seq, encode_status(
                    500, json.dumps({"error": {
                        "code": 500, "message": str(exc)}}).encode()))
            outq.put_nowait(data)

    async def handle_query(seq: int, payload: bytes) -> None:
        from opentsdb_tpu.tsd.server import _structured_error
        t0 = time.monotonic()
        shed = server.admission.try_admit(server.query_queue_depth())
        if shed is not None:
            resp = server._overload_response(shed)
            outq.put_nowait(_frame(T_QDONE, seq, encode_status(
                resp.status, resp.body)))
            return
        server.admission.started()

        def sink(tsq, sub_index: int, results: list) -> None:
            # query-pool thread: ship one sub's grids the moment the
            # engine finishes them. call_soon_threadsafe is FIFO with
            # the executor future's resolution, so every T_QRES
            # queues before this request's T_QDONE.
            for fr in qres_frames(seq, sub_index, results, tsq):
                loop.call_soon_threadsafe(outq.put_nowait, fr)

        def tracked() -> Any:
            from opentsdb_tpu.tsd.http_api import HttpRequest
            try:
                trace, qbody = decode_query(payload)
                req = HttpRequest(
                    method="POST", path="/api/query",
                    params={"arrays": ["true"]},
                    headers={TRACE_HEADER: trace} if trace else {},
                    body=qbody, remote=remote, received_at=t0)
                req.wire_sink = sink
                return server.http_router.handle(req)
            finally:
                server.admission.finished()

        fut = loop.run_in_executor(server._query_pool, tracked)
        try:
            if server.query_timeout_ms > 0:
                resp = await asyncio.wait_for(
                    fut, server.query_timeout_ms / 1000.0)
            else:
                resp = await fut
        except asyncio.TimeoutError:
            # the worker keeps running (admission frees on ITS exit);
            # grids it streams after this are dropped router-side by
            # the abandoned sequence
            resp = _structured_error(
                504, "Query timeout exceeded "
                f"({server.query_timeout_ms}ms)")
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - per-query 500
            LOG.exception("wire query failed")
            resp = _structured_error(500, str(exc))
        elapsed_ms = (time.monotonic() - t0) * 1000
        tsdb.stats.latency_query.add(elapsed_ms)
        if tsdb.slo.enabled:
            tsdb.slo.record("query", elapsed_ms, resp.status >= 500)
        outq.put_nowait(_frame(T_QDONE, seq, encode_status(
            resp.status, resp.body)))

    async def handle_cq(seq: int, payload: bytes) -> None:
        # continuous-query control op: replay as a real HTTP request
        # (the handle_write idiom — chaos hangs, fault sites and QoS
        # gates on the HTTP handler cover the wire path for free). No
        # admission gate: registrations and delta drains are control
        # traffic that must not be shed with the query load.
        from opentsdb_tpu.tsd.server import _structured_error

        def tracked() -> Any:
            from opentsdb_tpu.tsd.http_api import HttpRequest
            trace, method, path, qbody = decode_cq(payload)
            if not path.startswith("/api/query/continuous"):
                return _structured_error(
                    400, f"path {path!r} is not a continuous-query "
                    f"operation")
            req = HttpRequest(
                method=method, path=path, params={},
                headers={TRACE_HEADER: trace} if trace else {},
                body=qbody, remote=remote,
                received_at=time.monotonic())
            return server.http_router.handle(req)

        try:
            resp = await loop.run_in_executor(None, tracked)
        except asyncio.CancelledError:
            raise
        except Exception as exc:  # noqa: BLE001 - per-op 500
            LOG.exception("wire CQ op failed")
            resp = _structured_error(500, str(exc))
        outq.put_nowait(_frame(T_CQ_RES, seq, encode_status(
            resp.status, resp.body)))

    async def watchdog() -> None:
        # idle twin of listener_dead(): a session with nothing in
        # flight still follows a kill within one poll
        while True:
            if listener_dead():
                return
            await asyncio.sleep(0.05)

    async def read_dispatch() -> None:
        while True:
            ftype, seq, payload = await _read_frame(reader)
            if listener_dead():
                return  # refuse the request: the shard is "down"
            if ftype == T_WRITE:
                wq.put_nowait((seq, payload))
            elif ftype == T_QUERY:
                task = asyncio.ensure_future(
                    handle_query(seq, payload))
                qtasks.add(task)
                task.add_done_callback(qtasks.discard)
            elif ftype == T_CQ:
                task = asyncio.ensure_future(
                    handle_cq(seq, payload))
                qtasks.add(task)
                task.add_done_callback(qtasks.discard)
            else:
                raise WireProtocolError(
                    f"unexpected frame type {ftype}")

    tasks = [asyncio.ensure_future(t()) for t in
             (read_dispatch, sender, write_worker, watchdog)]
    try:
        await asyncio.wait(tasks,
                           return_when=asyncio.FIRST_COMPLETED)
    finally:
        pending = [*tasks, *qtasks]
        for t in pending:
            t.cancel()
        await asyncio.gather(*pending, return_exceptions=True)


__all__ = [
    "MAGIC", "WIRE_VERSION", "MAX_FRAME",
    "WireBacklogged", "WireConnection", "WireDps", "WireEncodeError",
    "WireManager", "WireProtocolError", "WireUnsupported",
    "decode_cq", "decode_qres", "decode_query", "decode_status",
    "decode_write", "encode_cq", "encode_query", "encode_status",
    "encode_write", "qres_frames", "serve_wire",
]
