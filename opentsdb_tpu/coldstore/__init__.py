"""Cold-tier columnar store: mmap-backed disk spill of demoted,
retained history (no reference equivalent — the reference's HBase
tables ARE its disk tier; this build owns its storage engine, so aged
history must be spilled explicitly or RAM caps the horizon).

- :mod:`opentsdb_tpu.coldstore.format` — the checksummed segment file
  format (int32-packed timestamp column + per-stat value columns) and
  its mmap reader
- :mod:`opentsdb_tpu.coldstore.store` — the segment/manifest owner
  (:class:`ColdStore`) plus the ``TimeSeriesStore``-shaped read view
  (:class:`ColdStatView`) the three-way stitched store consumes

Spilling is the lifecycle sweeper's fourth mechanism (after retention,
demotion and compaction — :mod:`opentsdb_tpu.lifecycle.manager`);
reads join the serve path through
:class:`opentsdb_tpu.lifecycle.stitch.StitchedStore`.
"""

from opentsdb_tpu.coldstore.store import ColdStatView, ColdStore

__all__ = ["ColdStore", "ColdStatView"]
