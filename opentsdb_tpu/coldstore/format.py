"""Cold-tier segment file format.

One segment = one spill of one metric's demoted history for one rollup
tier interval, written once and never mutated in place (rewrites go
through tmpfile + atomic rename, like every other persist path in this
build). Layout::

    magic   "TSDBCOLD"                      8 bytes
    version u32 LE                          4 bytes
    hdr_len u32 LE                          4 bytes
    hdr_crc u32 LE (crc32 of header json)   4 bytes
    header  json (hdr_len bytes)
    ts      int32 [rows]  (or int64 when header["scale"] == 0)
    <stat>  float64 [rows]   for each stat in header["stats"]
                             (sum / count / min / max)
    sk_off  int64 [rows+1]   (format 2 only, when header["sketch"])
    sk_blob bytes            concatenated per-row DDSketch blobs;
                             row i spans sk_off[i]..sk_off[i+1]
                             (equal offsets = no sketch for the row)

Format 2 adds the OPTIONAL quantile-sketch column (the fifth stat):
a segment without sketches still writes format 1, so files this build
produces stay readable by format-1 readers unless they actually carry
sketches; format-2 files without corruption are read by this build
whether or not the sketch section is present.

The header json carries the series table (sorted tag NAME pairs with
row offsets — names, not UID ids, so a segment outlives any UID
renumbering), the timestamp packing (``ts = base_ms + ts[i] * scale``,
the same int32-offset scheme ``SeriesBuffer.compact`` uses; scale 0 is
the >int32-span escape hatch and stores raw int64), and the crc32 of
the data section (``data_crc``) so fsck can verify the columns without
trusting the file length.

Readers ``np.memmap`` the columns — a segment's resident cost is the
pages a query actually touches, not the file. The header crc is
verified on every open; the data crc is verified by fsck (a full
sequential read, deliberately not paid at query time).
"""

from __future__ import annotations

import json
import os
import tempfile
import zlib

import numpy as np

MAGIC = b"TSDBCOLD"
FORMAT_VERSION = 2
# newest version a reader of this build accepts
SUPPORTED_VERSIONS = (1, 2)
STATS = ("sum", "count", "min", "max")

_PREAMBLE = len(MAGIC) + 4 + 4 + 4


class SegmentError(ValueError):
    """A segment file failed validation (bad magic/version/crc/shape).
    Readers treat this as a degraded-serve condition, never a crash."""


def pack_timestamps(ts_ms: np.ndarray) -> tuple[np.ndarray, int, int]:
    """(column, base_ms, scale): int32 offsets at second (1000) or ms
    (1) resolution when the span fits, raw int64 (scale 0) otherwise.
    ``ts_ms`` need not be globally sorted (rows are per-series runs)."""
    ts_ms = np.asarray(ts_ms, dtype=np.int64)
    if len(ts_ms) == 0:
        return ts_ms.astype(np.int32), 0, 1
    base = int(ts_ms.min())
    scale = 1000 if (base % 1000 == 0 and not (ts_ms % 1000).any()) \
        else 1
    span = (int(ts_ms.max()) - base) // scale
    if span > np.iinfo(np.int32).max:
        return ts_ms.copy(), 0, 0
    return ((ts_ms - base) // scale).astype(np.int32), base, scale


def write_segment(directory: str, name: str, header: dict,
                  ts_col: np.ndarray, cols: dict[str, np.ndarray],
                  sketch: tuple[np.ndarray, bytes] | None = None
                  ) -> dict:
    """Write one segment durably (tmpfile + fsync + atomic rename).
    ``header`` is completed in place with format/crc fields; returns
    the manifest entry for the segment. ``sketch`` is the optional
    fifth column as ``(offsets int64[rows+1], blob bytes)`` — its
    presence bumps the segment to format 2 (a sketch-free segment
    stays format 1, readable by older builds)."""
    os.makedirs(directory, exist_ok=True)
    n = len(ts_col)
    data_parts = [np.ascontiguousarray(ts_col).tobytes()]
    for stat in header["stats"]:
        col = np.ascontiguousarray(cols[stat], dtype=np.float64)
        if len(col) != n:
            raise SegmentError(f"stat column {stat!r} length {len(col)}"
                               f" != {n} rows")
        data_parts.append(col.tobytes())
    header = dict(header)
    version = 1
    if sketch is not None:
        sk_off, sk_blob = sketch
        sk_off = np.ascontiguousarray(sk_off, dtype=np.int64)
        if len(sk_off) != n + 1:
            raise SegmentError(
                f"sketch offsets length {len(sk_off)} != {n + 1}")
        if int(sk_off[-1]) != len(sk_blob):
            raise SegmentError(
                f"sketch blob length {len(sk_blob)} != "
                f"offset end {int(sk_off[-1])}")
        data_parts.append(sk_off.tobytes())
        data_parts.append(sk_blob)
        header["sketch"] = {"blob_len": len(sk_blob)}
        version = 2
    data = b"".join(data_parts)
    header["format"] = version
    header["rows"] = n
    header["data_crc"] = zlib.crc32(data) & 0xFFFFFFFF
    hdr_json = json.dumps(header, sort_keys=True).encode()
    hdr_crc = zlib.crc32(hdr_json) & 0xFFFFFFFF
    blob = (MAGIC
            + version.to_bytes(4, "little")
            + len(hdr_json).to_bytes(4, "little")
            + hdr_crc.to_bytes(4, "little")
            + hdr_json + data)
    path = os.path.join(directory, name)
    fd, tmp = tempfile.mkstemp(dir=directory, prefix=".seg-")
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    entry = {"file": name, "interval": header["interval"],
             "start_ms": header["start_ms"],
             "end_ms": header["end_ms"],
             "rows": n, "bytes": len(blob),
             "data_crc": header["data_crc"], "header_crc": hdr_crc}
    if sketch is not None:
        entry["sketch"] = True
    return entry


def read_header(path: str) -> tuple[dict, int]:
    """(header, data_offset). Raises :class:`SegmentError` on any
    structural problem — including a bad header crc."""
    try:
        with open(path, "rb") as fh:
            pre = fh.read(_PREAMBLE)
            if len(pre) < _PREAMBLE or pre[:len(MAGIC)] != MAGIC:
                raise SegmentError(f"{path}: bad magic")
            version = int.from_bytes(pre[8:12], "little")
            if version not in SUPPORTED_VERSIONS:
                raise SegmentError(f"{path}: unsupported segment "
                                   f"format {version}")
            hdr_len = int.from_bytes(pre[12:16], "little")
            hdr_crc = int.from_bytes(pre[16:20], "little")
            hdr_json = fh.read(hdr_len)
    except OSError as exc:
        raise SegmentError(f"{path}: {exc}") from exc
    if len(hdr_json) != hdr_len or \
            (zlib.crc32(hdr_json) & 0xFFFFFFFF) != hdr_crc:
        raise SegmentError(f"{path}: header checksum mismatch")
    try:
        header = json.loads(hdr_json)
    except ValueError as exc:
        raise SegmentError(f"{path}: header not json ({exc})") from exc
    return header, _PREAMBLE + hdr_len


class Segment:
    """One mmapped segment: the ts column plus per-stat value columns,
    opened read-only. Columns are ``np.memmap`` views — touching a row
    faults in that page only."""

    __slots__ = ("path", "header", "ts", "cols", "series",
                 "sk_off", "sk_blob")

    def __init__(self, path: str):
        header, off = read_header(path)
        n = int(header["rows"])
        ts_dtype = np.int64 if header.get("scale", 1) == 0 else np.int32
        sk_meta = header.get("sketch")
        try:
            size = os.path.getsize(path)
            ts_bytes = n * np.dtype(ts_dtype).itemsize
            need = off + ts_bytes + 8 * n * len(header["stats"])
            if sk_meta is not None:
                need += 8 * (n + 1) + int(sk_meta["blob_len"])
            if size < need:
                raise SegmentError(
                    f"{path}: truncated ({size} < {need} bytes)")
            if n:
                self.ts = np.memmap(path, dtype=ts_dtype, mode="r",
                                    offset=off, shape=(n,))
            else:
                self.ts = np.empty(0, dtype=ts_dtype)
            self.cols = {}
            pos = off + ts_bytes
            for stat in header["stats"]:
                if n:
                    self.cols[stat] = np.memmap(
                        path, dtype=np.float64, mode="r", offset=pos,
                        shape=(n,))
                else:
                    self.cols[stat] = np.empty(0, dtype=np.float64)
                pos += 8 * n
            self.sk_off = None
            self.sk_blob = None
            if sk_meta is not None:
                blob_len = int(sk_meta["blob_len"])
                self.sk_off = np.memmap(path, dtype=np.int64,
                                        mode="r", offset=pos,
                                        shape=(n + 1,))
                pos += 8 * (n + 1)
                self.sk_blob = np.memmap(
                    path, dtype=np.uint8, mode="r", offset=pos,
                    shape=(blob_len,)) if blob_len else \
                    np.empty(0, dtype=np.uint8)
        except OSError as exc:
            raise SegmentError(f"{path}: {exc}") from exc
        self.path = path
        self.header = header
        # [(sorted ((tagk_name, tagv_name), ...), off, cnt)]
        self.series = [(tuple(tuple(p) for p in e["tags"]),
                        int(e["off"]), int(e["cnt"]))
                       for e in header["series"]]

    @property
    def has_sketches(self) -> bool:
        return self.sk_off is not None

    def sketch_blob(self, row: int) -> bytes | None:
        """One row's serialized sketch (None when the segment or the
        row has no sketch column — format-1 segments, or rows spilled
        before their cells were ever folded)."""
        if self.sk_off is None:
            return None
        lo, hi = int(self.sk_off[row]), int(self.sk_off[row + 1])
        if hi <= lo:
            return None
        return bytes(self.sk_blob[lo:hi])

    def ts64(self, lo: int, hi: int) -> np.ndarray:
        """Row slice materialized as int64 ms."""
        scale = self.header.get("scale", 1)
        if scale == 0:
            return np.asarray(self.ts[lo:hi], dtype=np.int64)
        return (int(self.header["base_ms"])
                + self.ts[lo:hi].astype(np.int64) * scale)

    def row_bounds(self, off: int, cnt: int, start_ms: int,
                   end_ms: int) -> tuple[int, int]:
        """(lo, hi) absolute row range of one series' points within the
        inclusive [start_ms, end_ms] window — searched in the packed
        domain, no column materialization."""
        scale = self.header.get("scale", 1)
        run = self.ts[off:off + cnt]
        if scale == 0:
            lo = int(np.searchsorted(run, start_ms, side="left"))
            hi = int(np.searchsorted(run, end_ms, side="right"))
        else:
            base = int(self.header["base_ms"])
            # ts >= start <=> packed >= ceil((start-base)/scale)
            lo = int(np.searchsorted(run, -((base - start_ms) // scale),
                                     side="left"))
            hi = int(np.searchsorted(run, (end_ms - base) // scale,
                                     side="right"))
        return off + lo, off + hi


def verify_data_crc(path: str) -> bool:
    """Full sequential read of the data section vs the header's
    ``data_crc`` (the fsck check; query reads never pay this)."""
    header, off = read_header(path)
    crc = 0
    with open(path, "rb") as fh:
        fh.seek(off)
        while True:
            chunk = fh.read(1 << 20)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return (crc & 0xFFFFFFFF) == header.get("data_crc")
