"""Cold-tier columnar store: mmap-backed disk spill of demoted history.

The lifecycle subsystem (PR 4) bounds RAM by demoting aged raw points
into rollup tiers — but the tiers themselves still live in process
memory, so retained history is capped by host RAM, not disk. This
module is the disk backend the sweeper spills COLD tier history into:
per-metric segment files (:mod:`.format`) holding the four per-stat
tier columns (sum/count/min/max) over an int32-packed timestamp
column, plus a json manifest tracking every segment and each metric's
**spill boundary** (ms, exclusive: tier cells before it live on disk,
not in RAM).

Reads go through :class:`ColdStatView` — a ``TimeSeriesStore``-shaped
object (``bucket_reduce`` / ``materialize`` / ``materialize_padded`` /
``count_range`` / ``delete_range``, the ``StorageBackend`` surface)
over the mmapped columns, consumed by the three-way
:class:`~opentsdb_tpu.lifecycle.stitch.StitchedStore` (cold segments <
spill boundary < in-RAM tier < demotion boundary < raw tail). Series
identity inside a segment is stored as sorted tag NAME pairs and
resolved back to the raw store's sids at read time, so segments
survive UID renumbering and restarts.

Durability/crash ordering mirrors the demotion sweep: the segment file
is fsynced and renamed into place first, the manifest (segment list +
moved spill boundary) commits second in ONE atomic write, and only
then is the spilled range deleted from the in-RAM tier stores. A crash
at any point leaves either (a) an orphan segment file invisible to
reads (fsck reports it) or (b) RAM duplicates of spilled cells that
the stitched read CLIPS at the spill boundary — never a double-serve,
never a lost range — and the next sweep's reconciliation purge
removes them.

Degradation follows the PR-1 idiom: segment writes run under the
``coldstore.write`` fault site (a failed spill leaves the RAM copies
authoritative), reads under ``coldstore.read`` with their own circuit
breaker — a failed or breaker-blocked cold read degrades that query to
tier/raw serving (partial history, never a 500) and bumps the cold
``mutation_epoch`` so the degraded result can never be re-served from
the result cache (entries are stored under the pre-read version).
"""

from __future__ import annotations

import logging
import os
import re
import threading
import zlib
from typing import Any, Sequence

import numpy as np

from opentsdb_tpu.coldstore import format as fmt
from opentsdb_tpu.core.store import (PaddedBatch, PointBatch,
                                     STORE_INSTANCE_IDS,
                                     padded_from_batch)

LOG = logging.getLogger("coldstore")

MANIFEST = "manifest.json"
SEGMENT_SUFFIX = ".cold"
QUARANTINE_SUFFIX = ".quarantine"


def _metric_slug(metric: str) -> str:
    """Filesystem-safe, collision-safe metric tag for segment names."""
    safe = re.sub(r"[^A-Za-z0-9_.\-]", "_", metric)[:80]
    return f"{safe}-{zlib.crc32(metric.encode()) & 0xFFFFFFFF:08x}"


class _SegmentHandle:
    """Manifest entry + lazily-opened mmap + cached identity maps."""

    __slots__ = ("entry", "_seg", "_ids", "_lock")

    def __init__(self, entry: dict):
        self.entry = entry
        self._seg: fmt.Segment | None = None
        # per-series sorted (tagk_id, tagv_id) tuple (or None when a
        # tag name no longer resolves), aligned with segment.series
        self._ids: list | None = None
        self._lock = threading.Lock()

    def open(self, directory: str) -> fmt.Segment:
        seg = self._seg
        if seg is None:
            with self._lock:
                seg = self._seg
                if seg is None:
                    seg = fmt.Segment(
                        os.path.join(directory, self.entry["file"]))
                    self._seg = seg
        return seg

    def id_map(self, directory: str, uids) -> dict:
        """{sorted tag-id tuple: (off, cnt)} for this segment. UID
        tables are append-only, so one resolution is cached forever."""
        seg = self.open(directory)
        with self._lock:
            if self._ids is None:
                out = {}
                for tags, off, cnt in seg.series:
                    try:
                        key = tuple(sorted(
                            (uids.tag_names.get_id(k),
                             uids.tag_values.get_id(v))
                            for k, v in tags))
                    except LookupError:
                        continue  # unresolvable identity: fsck's find
                    out[key] = (off, cnt)
                self._ids = out
            return self._ids


class ColdStatView:
    """Read surface over one (metric, tier interval, stat): the cold
    third of the stitched store. Takes RAW-store series ids and maps
    them to segment rows by (metric, tags) identity, exactly like the
    stitched store maps raw sids to tier sids.

    Raises on any segment problem (missing file, bad checksum, armed
    ``coldstore.read`` fault) — the stitched store's cold guard
    converts that into a degraded tier/raw-only serve."""

    fault_site = "coldstore.read"

    def __init__(self, cold: "ColdStore", metric: str, interval: str,
                 stat: str, raw_store):
        self.instance_id = next(STORE_INSTANCE_IDS)
        self.cold = cold
        self.metric = metric
        self.interval = interval
        self.stat = stat
        self.raw = raw_store

    @property
    def handles(self) -> list[_SegmentHandle]:
        # resolved per call (cached on the ColdStore, cleared by every
        # manifest mutation) so a long-lived stitched view never holds
        # handles onto rewritten or quarantined segment files
        return self.cold._handles(self.metric, self.interval)

    # version surface consumed by StitchedStore / result-cache keys
    @property
    def points_written(self) -> int:
        return self.cold.points_spilled

    @property
    def mutation_epoch(self) -> int:
        return self.cold.mutation_epoch

    def total_points(self) -> int:
        return sum(h.entry["rows"] for h in self.handles)

    def _check(self) -> None:
        faults = self.cold.faults
        if faults is not None:
            faults.check(self.fault_site)

    def _rows_for(self, handle: _SegmentHandle,
                  sids: np.ndarray) -> list[tuple[int, int, int]]:
        """[(position-in-sids, off, cnt)] of the requested raw series
        present in this segment."""
        id_map = handle.id_map(self.cold.directory, self.cold.uids)
        out = []
        for i, sid in enumerate(sids):
            rec = self.raw.series(int(sid))
            hit = id_map.get(rec.tags)
            if hit is not None:
                out.append((i, hit[0], hit[1]))
        return out

    def _overlapping(self, start_ms: int, end_ms: int
                     ) -> list[_SegmentHandle]:
        return [h for h in self.handles
                if h.entry["start_ms"] <= end_ms
                and h.entry["end_ms"] >= start_ms]

    # -- StorageBackend read surface ------------------------------------

    def count_range(self, series_ids, start_ms: int,
                    end_ms: int) -> np.ndarray:
        self._check()
        sids = np.asarray(series_ids, dtype=np.int64)
        out = np.zeros(len(sids), dtype=np.int64)
        for h in self._overlapping(start_ms, end_ms):
            seg = h.open(self.cold.directory)
            for i, off, cnt in self._rows_for(h, sids):
                lo, hi = seg.row_bounds(off, cnt, start_ms, end_ms)
                out[i] += hi - lo
        return out

    def materialize(self, series_ids, start_ms: int,
                    end_ms: int) -> PointBatch:
        """Flat batch of the stat column. Segments of one metric are
        time-disjoint and visited oldest-first, so after the stable
        sort on the series index each series' points are
        time-ascending (the PointBatch contract)."""
        self._check()
        sids = np.asarray(series_ids, dtype=np.int64)
        idx_parts: list[np.ndarray] = []
        ts_parts: list[np.ndarray] = []
        val_parts: list[np.ndarray] = []
        for h in sorted(self._overlapping(start_ms, end_ms),
                        key=lambda h: h.entry["start_ms"]):
            seg = h.open(self.cold.directory)
            col = seg.cols[self.stat]
            for i, off, cnt in self._rows_for(h, sids):
                lo, hi = seg.row_bounds(off, cnt, start_ms, end_ms)
                if hi > lo:
                    idx_parts.append(np.full(hi - lo, i,
                                             dtype=np.int32))
                    ts_parts.append(seg.ts64(lo, hi))
                    val_parts.append(np.asarray(col[lo:hi]))
        if not ts_parts:
            return PointBatch(sids, np.empty(0, dtype=np.int32),
                              np.empty(0, dtype=np.int64),
                              np.empty(0, dtype=np.float64))
        series_idx = np.concatenate(idx_parts)
        ts_ms = np.concatenate(ts_parts)
        values = np.concatenate(val_parts)
        order = np.argsort(series_idx, kind="stable")
        return PointBatch(sids, series_idx[order], ts_ms[order],
                          values[order])

    def bucket_reduce(self, series_ids, start_ms: int, end_ms: int,
                      t0: int, interval_ms: int, nbuckets: int,
                      want_minmax: bool = False):
        """Same fused shape as ``TimeSeriesStore.bucket_reduce``: [S,B]
        sum/count (+min/max) grids over the stat column."""
        batch = self.materialize(series_ids, start_ms, end_ms)
        s = len(batch.series_ids)
        b = (batch.ts_ms - t0) // interval_ms
        ok = (b >= 0) & (b < nbuckets) & ~np.isnan(batch.values)
        seg = batch.series_idx[ok].astype(np.int64) * nbuckets + b[ok]
        vals = batch.values[ok]
        n = s * nbuckets
        sums = np.bincount(seg, weights=vals, minlength=n).reshape(
            s, nbuckets)
        cnts = np.bincount(seg, minlength=n).astype(np.float64) \
            .reshape(s, nbuckets)
        mins = maxs = None
        if want_minmax:
            mins = np.full(n, np.inf)
            np.minimum.at(mins, seg, vals)
            maxs = np.full(n, -np.inf)
            np.maximum.at(maxs, seg, vals)
            mins = mins.reshape(s, nbuckets)
            maxs = maxs.reshape(s, nbuckets)
        return sums, cnts, mins, maxs

    def materialize_padded(self, series_ids, start_ms: int,
                           end_ms: int) -> PaddedBatch:
        return padded_from_batch(
            self.materialize(series_ids, start_ms, end_ms))

    def delete_range(self, series_ids, start_ms: int,
                     end_ms: int) -> int:
        """delete=true over cold history: segment rewrite. A cold row
        holds ALL four stat columns of one tier cell, so deleting it
        removes the point from every stat — the point is gone, which
        is what delete means. Raises on failure (a delete must never
        silently not happen)."""
        sids = np.asarray(series_ids, dtype=np.int64)
        identities = set()
        for sid in sids:
            identities.add(self.raw.series(int(sid)).tags)
        return self.cold.delete_rows(self.metric, self.interval,
                                     identities, start_ms, end_ms)


class ColdStore:
    """Segment + manifest owner for one cold directory (see module
    docstring). Owned by the :class:`~opentsdb_tpu.lifecycle.manager.
    LifecycleManager`; all mutation goes through the sweep or fsck."""

    def __init__(self, directory: str, faults=None, uids=None,
                 read_breaker=None):
        self.directory = directory
        self.faults = faults
        self.uids = uids
        self.read_breaker = read_breaker
        self._lock = threading.Lock()
        # metric -> {"spill_boundary_ms": int, "segments": [entry]}
        self._metrics: dict[str, dict] = {}
        # (metric, interval) -> [_SegmentHandle] (sorted by start_ms)
        self._handle_cache: dict[tuple[str, str],
                                 list[_SegmentHandle]] = {}
        self.mutation_epoch = 0
        self.points_spilled = 0
        self.segments_written = 0
        self.bytes_spilled = 0
        self.spill_errors = 0
        self.read_errors = 0
        self.degraded_serves = 0
        self.segments_quarantined = 0
        self.segments_dropped = 0       # retention
        self.segments_compacted = 0     # merge-compaction
        self.points_deleted = 0         # delete=true rewrites
        self.last_error = ""
        self._load_manifest()

    # ------------------------------------------------------------------
    # manifest
    # ------------------------------------------------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.directory, MANIFEST)

    def _load_manifest(self) -> None:
        import json
        path = self.manifest_path
        if not os.path.isfile(path):
            return
        try:
            with open(path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as exc:
            # a corrupt manifest degrades to "no cold data": tier/raw
            # serving continues, fsck reports the segments as orphans
            LOG.warning("could not load cold manifest %s: %s", path,
                        exc)
            self.last_error = f"manifest: {exc}"
            return
        self._metrics = doc.get("metrics") or {}
        self.points_spilled = sum(
            e["rows"] for m in self._metrics.values()
            for e in m.get("segments", ()))

    def _save_manifest_locked(self) -> None:
        import json
        from opentsdb_tpu.core.persist import _atomic_write
        os.makedirs(self.directory, exist_ok=True)
        _atomic_write(self.manifest_path, json.dumps(
            {"version": 1, "metrics": self._metrics},
            sort_keys=True).encode())

    # ------------------------------------------------------------------
    # read-side lookups
    # ------------------------------------------------------------------

    def spill_boundary(self, metric: str) -> int:
        with self._lock:
            rec = self._metrics.get(metric)
            return int(rec["spill_boundary_ms"]) if rec else 0

    def spill_boundaries(self) -> dict[str, int]:
        """Locked snapshot for the admin surface (a sweep may insert
        a metric mid-request)."""
        with self._lock:
            return {m: int(rec["spill_boundary_ms"])
                    for m, rec in self._metrics.items()}

    def has_segments(self, metric: str, interval: str) -> bool:
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return False
            return any(e["interval"] == interval
                       for e in rec.get("segments", ()))

    def _handles(self, metric: str, interval: str
                 ) -> list[_SegmentHandle]:
        key = (metric, interval)
        with self._lock:
            cached = self._handle_cache.get(key)
            if cached is None:
                rec = self._metrics.get(metric) or {}
                cached = sorted(
                    (_SegmentHandle(e) for e in rec.get("segments", ())
                     if e["interval"] == interval),
                    key=lambda h: h.entry["start_ms"])
                self._handle_cache[key] = cached
            return cached

    def stat_view(self, metric: str, interval: str, stat: str,
                  raw_store) -> ColdStatView:
        return ColdStatView(self, metric, interval, stat, raw_store)

    def sketch_rows(self, metric: str, interval: str | None,
                    start_ms: int, end_ms: int
                    ) -> list[tuple[tuple, int, bytes]]:
        """The fifth column's cold read: ``(tags_names, cell_ts,
        blob)`` rows of every format-2 segment overlapping
        [start_ms, end_ms]. ``interval=None`` reads every interval
        that has sketch-bearing segments (the query path doesn't know
        which tier carried the cells at fold time). Runs under
        ``coldstore.read`` (same degrade contract as the stat views —
        the caller converts a raise into a degraded serve)."""
        if self.faults is not None:
            self.faults.check("coldstore.read")
        if interval is None:
            with self._lock:
                rec = self._metrics.get(metric)
                intervals = sorted({e["interval"]
                                    for e in rec["segments"]
                                    if e.get("sketch")}) if rec else []
            out: list[tuple[tuple, int, bytes]] = []
            for iv in intervals:
                out.extend(self._sketch_rows_one(metric, iv, start_ms,
                                                 end_ms))
            return out
        return self._sketch_rows_one(metric, interval, start_ms,
                                     end_ms)

    def _sketch_rows_one(self, metric: str, interval: str,
                         start_ms: int, end_ms: int
                         ) -> list[tuple[tuple, int, bytes]]:
        out: list[tuple[tuple, int, bytes]] = []
        for h in self._handles(metric, interval):
            if h.entry["start_ms"] > end_ms or \
                    h.entry["end_ms"] < start_ms:
                continue
            seg = h.open(self.directory)
            if not seg.has_sketches:
                continue
            for tags, off, cnt in seg.series:
                lo, hi = seg.row_bounds(off, cnt, start_ms, end_ms)
                if hi <= lo:
                    continue
                ts = seg.ts64(lo, hi)
                for j in range(hi - lo):
                    blob = seg.sketch_blob(lo + j)
                    if blob is not None:
                        out.append((tags, int(ts[j]), blob))
        return out

    def has_sketch_segments(self, metric: str, interval: str) -> bool:
        """Whether any committed segment of this (metric, tier)
        carries the sketch column (manifest-entry check, no file
        open)."""
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return False
            return any(e["interval"] == interval and e.get("sketch")
                       for e in rec["segments"])

    # ------------------------------------------------------------------
    # spill (called by the lifecycle sweep, under coldstore.write)
    # ------------------------------------------------------------------

    def write_segment(self, metric: str, interval: str,
                      series_entries: Sequence[dict],
                      ts_ms: np.ndarray,
                      cols: dict[str, np.ndarray],
                      sketch: tuple[np.ndarray, bytes] | None = None
                      ) -> dict:
        """Write one durable segment file (NOT yet visible: the caller
        commits it to the manifest via :meth:`commit_spill` once every
        tier's segment of the sweep is on disk). ``sketch`` is the
        optional fifth column — ``(offsets int64[rows+1], blob)`` of
        per-row serialized quantile sketches; its presence makes the
        file a format-2 segment."""
        if self.faults is not None:
            self.faults.check("coldstore.write")
        ts_col, base, scale = fmt.pack_timestamps(ts_ms)
        start = int(ts_ms.min()) if len(ts_ms) else 0
        end = int(ts_ms.max()) if len(ts_ms) else 0
        name = (f"{_metric_slug(metric)}-{interval}-{start}-{end}"
                f"{SEGMENT_SUFFIX}")
        header = {
            "metric": metric, "interval": interval,
            "base_ms": base, "scale": scale,
            "start_ms": start, "end_ms": end,
            "stats": list(fmt.STATS),
            "series": list(series_entries),
        }
        return fmt.write_segment(self.directory, name, header, ts_col,
                                 cols, sketch=sketch)

    def commit_spill(self, metric: str, boundary_ms: int,
                     entries: Sequence[dict]) -> None:
        """Publish freshly-written segments + the moved spill boundary
        in one atomic manifest write. After this returns, stitched
        reads clip the in-RAM tier at the new boundary — the caller
        may then safely purge the spilled range from RAM."""
        with self._lock:
            rec = self._metrics.setdefault(
                metric, {"spill_boundary_ms": 0, "segments": []})
            existing = {e["file"] for e in rec["segments"]}
            for e in entries:
                if e["file"] in existing:   # re-spill after a crash:
                    rec["segments"] = [     # newest write wins
                        x for x in rec["segments"]
                        if x["file"] != e["file"]]
                rec["segments"].append(dict(e))
                self.segments_written += 1
                self.points_spilled += int(e["rows"])
                self.bytes_spilled += int(e["bytes"])
            rec["spill_boundary_ms"] = max(
                int(rec["spill_boundary_ms"]), int(boundary_ms))
            self._handle_cache.clear()
            self._save_manifest_locked()
            self.mutation_epoch += 1

    # ------------------------------------------------------------------
    # destructive ops (delete=true, retention, fsck quarantine)
    # ------------------------------------------------------------------

    def delete_rows(self, metric: str, interval: str,
                    identities: set, start_ms: int,
                    end_ms: int) -> int:
        """Remove the given series' rows within [start_ms, end_ms] by
        rewriting every overlapping segment (cold deletes are rare
        admin ops; a rewrite keeps the format append-only)."""
        deleted = 0
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return 0
            keep_entries = []
            obsolete: list[str] = []
            changed = False
            for entry in rec["segments"]:
                if entry["interval"] != interval or \
                        entry["start_ms"] > end_ms or \
                        entry["end_ms"] < start_ms:
                    keep_entries.append(entry)
                    continue
                seg = fmt.Segment(os.path.join(self.directory,
                                               entry["file"]))
                removed, new_entry = self._rewrite_segment(
                    seg, entry, identities, start_ms, end_ms)
                deleted += removed
                if removed == 0:
                    keep_entries.append(entry)
                    continue
                if new_entry is not None:
                    keep_entries.append(new_entry)
                obsolete.append(entry["file"])
                changed = True
            if changed:
                rec["segments"] = keep_entries
                self._handle_cache.clear()
                self.points_deleted += deleted
                self.mutation_epoch += 1
                self._save_manifest_locked()
                # unlink the replaced files only AFTER the manifest
                # commit: a crash before this point leaves both files
                # on disk with the manifest still authoritative (the
                # old rows readable, the .rw file an fsck-visible
                # orphan) — never a referenced-but-missing segment
                for name in obsolete:
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:  # pragma: no cover
                        pass
        return deleted

    def _rewrite_segment(self, seg: fmt.Segment, entry: dict,
                         identities: set | None, start_ms: int,
                         end_ms: int) -> tuple[int, dict | None]:
        """(rows removed, replacement manifest entry or None when the
        whole segment emptied). ``identities`` of None means EVERY
        series (retention trim); a set restricts to those tag-id
        identities (delete=true). Writes the replacement file but does
        NOT touch the old one — the caller unlinks it after the
        manifest commit. Caller holds the lock."""
        uids = self.uids
        n = int(entry["rows"])
        keep = np.ones(n, dtype=bool)
        for tags, off, cnt in seg.series:
            if identities is not None:
                try:
                    key = tuple(sorted((uids.tag_names.get_id(k),
                                        uids.tag_values.get_id(v))
                                       for k, v in tags))
                except LookupError:
                    continue
                if key not in identities:
                    continue
            lo, hi = seg.row_bounds(off, cnt, start_ms, end_ms)
            keep[lo:hi] = False
        removed = int(n - keep.sum())
        if removed == 0:
            return 0, entry
        if removed == n:
            return removed, None
        ts64 = seg.ts64(0, n)[keep]
        cols = {stat: np.asarray(seg.cols[stat])[keep]
                for stat in seg.header["stats"]}
        series_entries = []
        pos = np.cumsum(keep) - keep  # new row index of each old row
        for tags, off, cnt in seg.series:
            cnt_new = int(keep[off:off + cnt].sum())
            if cnt_new:
                series_entries.append({
                    "tags": [list(p) for p in tags],
                    "off": int(pos[off]), "cnt": cnt_new})
        # the sketch column survives rewrites: kept rows keep their
        # blobs (re-packed contiguously), dropped rows drop theirs
        sketch = None
        if seg.has_sketches:
            offs = np.asarray(seg.sk_off)
            lens = (offs[1:] - offs[:-1])[keep]
            new_off = np.zeros(len(lens) + 1, dtype=np.int64)
            np.cumsum(lens, out=new_off[1:])
            blob_parts = []
            for row in np.nonzero(keep)[0].tolist():
                lo2, hi2 = int(offs[row]), int(offs[row + 1])
                if hi2 > lo2:
                    blob_parts.append(bytes(seg.sk_blob[lo2:hi2]))
            sketch = (new_off, b"".join(blob_parts))
        ts_col, base, scale = fmt.pack_timestamps(ts64)
        header = {
            "metric": entry.get("metric", seg.header["metric"]),
            "interval": entry["interval"],
            "base_ms": base, "scale": scale,
            "start_ms": int(ts64.min()), "end_ms": int(ts64.max()),
            "stats": list(seg.header["stats"]),
            "series": series_entries,
        }
        # the replacement keeps the SEGMENT_SUFFIX (fsck's orphan scan
        # matches on it) and carries a monotonic nonce so repeated
        # rewrites never collide or accrete suffixes
        base = entry["file"]
        if base.endswith(SEGMENT_SUFFIX):
            base = base[:-len(SEGMENT_SUFFIX)]
        base = re.sub(r"-rw\d+$", "", base)
        name = (f"{base}-rw{self.points_deleted + removed}"
                f"{SEGMENT_SUFFIX}")
        new_entry = fmt.write_segment(self.directory, name, header,
                                      ts_col, cols, sketch=sketch)
        return removed, new_entry

    def compact_segments(self, metric: str, threshold: int) -> int:
        """Merge-compact every (metric, tier) group that accumulated
        MORE than ``threshold`` per-sweep segments into one segment
        per tier. Same crash ordering as the delete rewrite: each
        merged replacement is durable on disk BEFORE the single
        manifest commit that swaps the entries, and the obsolete files
        unlink only AFTER it — a crash at any point leaves fsck-visible
        orphans, never a referenced-but-missing segment. Returns the
        number of segments merged away."""
        if threshold <= 0:
            return 0
        if self.faults is not None:
            self.faults.check("coldstore.write")
        removed = 0
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return 0
            by_tier: dict[str, list[dict]] = {}
            for entry in rec["segments"]:
                by_tier.setdefault(entry["interval"], []).append(entry)
            keep_entries = [e for e in rec["segments"]
                            if len(by_tier[e["interval"]]) <= threshold]
            obsolete: list[str] = []
            changed = False
            for interval, entries in sorted(by_tier.items()):
                if len(entries) <= threshold:
                    continue
                entries = sorted(entries,
                                 key=lambda e: e["start_ms"])
                new_entry = self._merge_segments_locked(
                    metric, interval, entries)
                if new_entry is None:   # unreadable input: leave as-is
                    keep_entries.extend(entries)
                    continue
                keep_entries.append(new_entry)
                obsolete.extend(e["file"] for e in entries)
                removed += len(entries) - 1
                changed = True
            if changed:
                rec["segments"] = keep_entries
                self._handle_cache.clear()
                self.segments_compacted += removed
                self.mutation_epoch += 1
                self._save_manifest_locked()
                # unlink the merged inputs only AFTER the manifest
                # commit (the delete-rewrite ordering, see above)
                for name in obsolete:
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:  # pragma: no cover
                        pass
        return removed

    def _merge_segments_locked(self, metric: str, interval: str,
                               entries: list[dict]) -> dict | None:
        """Write ONE durable segment holding every row of ``entries``
        (time-disjoint, passed sorted by start_ms), series-major like
        any spilled segment: per identity, the per-segment runs
        concatenate in segment order, so each series' rows stay
        time-ascending. Returns the replacement manifest entry, or
        None when an input segment cannot be read (checksum, missing
        file — the group is left untouched for fsck to report).
        Caller holds the lock."""
        try:
            segs = [fmt.Segment(os.path.join(self.directory,
                                             e["file"]))
                    for e in entries]
        except (fmt.SegmentError, OSError) as exc:
            self.last_error = f"compact: {exc}"
            return None
        stats = list(segs[0].header["stats"])
        if any(list(s.header["stats"]) != stats for s in segs[1:]):
            return None
        # per-identity row runs, first-seen order (deterministic:
        # segment order is start_ms order, series order is on-disk)
        order: list[tuple] = []
        runs: dict[tuple, list[tuple[int, int, int]]] = {}
        for si, seg in enumerate(segs):
            for tags, off, cnt in seg.series:
                if tags not in runs:
                    order.append(tags)
                    runs[tags] = []
                runs[tags].append((si, off, cnt))
        has_sk = any(s.has_sketches for s in segs)
        ts_parts: list[np.ndarray] = []
        col_parts: dict[str, list[np.ndarray]] = \
            {st: [] for st in stats}
        sk_lens: list[np.ndarray] = []
        sk_blobs: list[bytes] = []
        series_entries = []
        off_out = 0
        for tags in order:
            cnt_total = 0
            for si, off, cnt in runs[tags]:
                seg = segs[si]
                ts_parts.append(seg.ts64(off, off + cnt))
                for st in stats:
                    col_parts[st].append(
                        np.asarray(seg.cols[st])[off:off + cnt])
                if has_sk:
                    if seg.has_sketches:
                        offs = np.asarray(seg.sk_off)
                        sk_lens.append(offs[off + 1:off + cnt + 1]
                                       - offs[off:off + cnt])
                        lo, hi = int(offs[off]), int(offs[off + cnt])
                        if hi > lo:
                            sk_blobs.append(bytes(seg.sk_blob[lo:hi]))
                    else:
                        # format-1 input rows merge into a format-2
                        # output as empty (offset-equal) sketch slots
                        sk_lens.append(np.zeros(cnt, dtype=np.int64))
                cnt_total += cnt
            series_entries.append({"tags": [list(p) for p in tags],
                                   "off": off_out, "cnt": cnt_total})
            off_out += cnt_total
        ts64 = np.concatenate(ts_parts) if ts_parts else \
            np.zeros(0, dtype=np.int64)
        cols = {st: np.concatenate(col_parts[st]) if col_parts[st]
                else np.zeros(0, dtype=np.float64) for st in stats}
        sketch = None
        if has_sk:
            lens = np.concatenate(sk_lens) if sk_lens else \
                np.zeros(0, dtype=np.int64)
            new_off = np.zeros(len(lens) + 1, dtype=np.int64)
            np.cumsum(lens, out=new_off[1:])
            sketch = (new_off, b"".join(sk_blobs))
        ts_col, base_ms, scale = fmt.pack_timestamps(ts64)
        header = {
            "metric": metric, "interval": interval,
            "base_ms": base_ms, "scale": scale,
            "start_ms": int(ts64.min()) if len(ts64) else 0,
            "end_ms": int(ts64.max()) if len(ts64) else 0,
            "stats": stats, "series": series_entries,
        }
        # keeps SEGMENT_SUFFIX (fsck's orphan scan matches on it) and
        # a monotonic nonce so repeated compactions never collide
        name = (f"{_metric_slug(metric)}-{interval}"
                f"-{header['start_ms']}-{header['end_ms']}"
                f"-mc{self.segments_compacted + len(entries)}"
                f"{SEGMENT_SUFFIX}")
        return fmt.write_segment(self.directory, name, header, ts_col,
                                 cols, sketch=sketch)

    @staticmethod
    def _entry_interval_ms(entry: dict, interval_ms_of) -> int:
        """One segment's cell-window span in ms: the shared expiry
        rule for the drop and trim paths (unknown/absent tier maps
        conservatively to 0)."""
        if interval_ms_of is None:
            return 0
        try:
            return max(int(interval_ms_of(entry["interval"])), 0)
        except Exception:  # noqa: BLE001 - unknown tier
            return 0

    def drop_segments_before(self, metric: str, cutoff_ms: int,
                             interval_ms_of=None) -> int:
        """Retention for the cold tier, segment-granular: drop every
        segment whose WHOLE range expired — including the last cell's
        aggregation window ``[end_ms, end_ms + interval)``, the same
        cell rule the partial trim and the RAM-tier purge use (a cell
        stamped just before the cutoff still aggregates unexpired
        history). Returns rows dropped."""
        dropped = 0
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return 0
            keep = []
            for entry in rec["segments"]:
                iv_ms = self._entry_interval_ms(entry, interval_ms_of)
                if entry["end_ms"] + iv_ms < cutoff_ms:
                    dropped += int(entry["rows"])
                    self.segments_dropped += 1
                    path = os.path.join(self.directory, entry["file"])
                    try:
                        os.unlink(path)
                    except OSError:
                        pass  # already gone; manifest is authoritative
                else:
                    keep.append(entry)
            if dropped:
                rec["segments"] = keep
                self._handle_cache.clear()
                self.mutation_epoch += 1
                self._save_manifest_locked()
        return dropped

    # a straddling segment is only rewritten once its expired prefix
    # is worth the copy: the rewrite is O(segment) regardless of how
    # little expired, so trimming every sweep would re-copy a huge
    # long-lived segment per cycle for a sliver. 25% bounds the
    # amortized write amplification at ~4x while whole-expired
    # segments keep dropping for free via drop_segments_before.
    TRIM_MIN_EXPIRED_FRACTION = 0.25

    def trim_segments_before(self, metric: str, cutoff_ms: int,
                             interval_ms_of=None) -> int:
        """Partial-segment retention trim: rewrite still-live segments
        whose RANGE straddles the cutoff, dropping the expired prefix
        through the delete-rewrite path (same crash ordering:
        replacement written + manifest committed BEFORE the old file
        unlinks). :meth:`drop_segments_before` handles whole-expired
        segments cheaply (unlink, no rewrite) — this covers the long
        tail a single huge segment would otherwise pin on disk until
        its newest cell expired.

        A cold cell stamped T aggregates ``[T, T+interval)``: like the
        RAM-tier purge rule, only cells whose WHOLE window expired are
        trimmed (``T + interval <= cutoff``), so unexpired aggregated
        history is never lost with its cell. ``interval_ms_of``
        maps a tier interval string ("1m") to its ms span; absent
        (or unknown interval), the trim conservatively assumes 0.
        Segments whose expired prefix is under
        :data:`TRIM_MIN_EXPIRED_FRACTION` of their range are left for
        a later sweep (write-amplification gate). Returns rows
        removed."""
        trimmed = 0
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return 0
            keep_entries: list[dict] = []
            obsolete: list[str] = []
            changed = False
            for entry in rec["segments"]:
                iv_ms = self._entry_interval_ms(entry, interval_ms_of)
                # inclusive delete end: mirrors the RAM tier's
                # ``cutoff - 1 - iv`` purge bound
                cut_end = cutoff_ms - 1 - iv_ms
                if cut_end < 1 or entry["start_ms"] > cut_end:
                    keep_entries.append(entry)
                    continue
                span = max(entry["end_ms"] - entry["start_ms"], 1)
                frac = (cut_end - entry["start_ms"]) / span
                if frac < self.TRIM_MIN_EXPIRED_FRACTION:
                    keep_entries.append(entry)
                    continue
                seg = fmt.Segment(os.path.join(self.directory,
                                               entry["file"]))
                removed, new_entry = self._rewrite_segment(
                    seg, entry, None, 1, cut_end)
                trimmed += removed
                if removed == 0:
                    keep_entries.append(entry)
                    continue
                if new_entry is not None:
                    keep_entries.append(new_entry)
                else:
                    self.segments_dropped += 1
                obsolete.append(entry["file"])
                changed = True
            if changed:
                rec["segments"] = keep_entries
                self._handle_cache.clear()
                self.points_deleted += trimmed
                self.mutation_epoch += 1
                self._save_manifest_locked()
                # unlink replaced files only AFTER the manifest commit
                # (delete_rows crash ordering: an orphan is
                # fsck-visible, a referenced-but-missing segment is
                # data loss)
                for name in obsolete:
                    try:
                        os.unlink(os.path.join(self.directory, name))
                    except OSError:  # pragma: no cover
                        pass
        return trimmed

    def quarantine(self, metric: str, file: str) -> bool:
        """fsck --fix: move a corrupt segment out of the manifest (and
        aside on disk) so reads degrade to tier/raw serving instead of
        failing on every query."""
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec:
                return False
            hit = [e for e in rec["segments"] if e["file"] == file]
            if not hit:
                return False
            rec["segments"] = [e for e in rec["segments"]
                               if e["file"] != file]
            path = os.path.join(self.directory, file)
            try:
                if os.path.exists(path):
                    os.replace(path, path + QUARANTINE_SUFFIX)
            except OSError as exc:  # pragma: no cover - disk trouble
                LOG.warning("could not quarantine %s: %s", path, exc)
            self.segments_quarantined += 1
            self._handle_cache.clear()
            self.mutation_epoch += 1
            self._save_manifest_locked()
            return True

    def clamp_boundary(self, metric: str, boundary_ms: int) -> bool:
        """fsck --fix for a spill boundary past the demotion boundary
        (would double-serve [demote, spill) from both cold and raw)."""
        with self._lock:
            rec = self._metrics.get(metric)
            if not rec or rec["spill_boundary_ms"] <= boundary_ms:
                return False
            rec["spill_boundary_ms"] = int(boundary_ms)
            self.mutation_epoch += 1
            self._save_manifest_locked()
            return True

    # ------------------------------------------------------------------
    # degradation bookkeeping (called by the stitched store's guard)
    # ------------------------------------------------------------------

    def note_read_error(self, exc: Exception) -> None:
        self.read_errors += 1
        self.last_error = f"{type(exc).__name__}: {exc}"
        # the epoch bump makes any result computed during this failure
        # stale for every later cache lookup (entries store the
        # pre-read version) — a degraded serve can never linger
        self.mutation_epoch += 1
        if self.read_errors <= 5 or self.read_errors % 1000 == 0:
            LOG.warning("cold read failed (%s); serving tier/raw only",
                        self.last_error)

    def note_degraded_serve(self) -> None:
        self.degraded_serves += 1
        self.mutation_epoch += 1

    # ------------------------------------------------------------------
    # fsck surface
    # ------------------------------------------------------------------

    def fsck_scan(self, demote_boundaries: dict[str, int]
                  ) -> list[dict]:
        """Integrity findings: [{metric, file|None, problem,
        fixable}]. ``demote_boundaries`` maps metric name -> lifecycle
        demotion boundary (from ``lifecycle.json``)."""
        findings: list[dict] = []
        with self._lock:
            metrics = {m: dict(rec, segments=list(rec["segments"]))
                       for m, rec in self._metrics.items()}
        listed: set[str] = set()
        for metric, rec in metrics.items():
            spill_b = int(rec["spill_boundary_ms"])
            demote_b = demote_boundaries.get(metric)
            if spill_b and demote_b is None:
                # lifecycle.json lost or the metric UID unresolvable:
                # clamping to 0 here would cascade into quarantining
                # every (healthy) segment — report only, the operator
                # restores lifecycle.json (serving already clamps the
                # stitch, so nothing double-serves meanwhile)
                findings.append({
                    "metric": metric, "file": None, "fix": "report",
                    "problem": (
                        "spill boundary set but the metric has no "
                        "demotion boundary (lifecycle.json missing "
                        "or stale?) — cold history is unreachable "
                        "until it is restored")})
            elif demote_b is not None and spill_b > int(demote_b):
                findings.append({
                    "metric": metric, "file": None, "fix": "clamp",
                    "problem": (
                        f"spill boundary {spill_b} is past the "
                        f"demotion boundary {demote_b} — the range "
                        "between them would be double-served"),
                    "boundary": int(demote_b)})
            for entry in rec["segments"]:
                listed.add(entry["file"])
                path = os.path.join(self.directory, entry["file"])
                problem = None
                if not os.path.isfile(path):
                    problem = "segment file missing"
                else:
                    try:
                        fmt.Segment(path)
                        if not fmt.verify_data_crc(path):
                            problem = "data checksum mismatch"
                    except fmt.SegmentError as exc:
                        problem = str(exc)
                if problem is None and entry["end_ms"] >= spill_b:
                    problem = (f"segment range ends at "
                               f"{entry['end_ms']} >= spill boundary "
                               f"{spill_b}")
                if problem is not None:
                    findings.append({"metric": metric,
                                     "file": entry["file"],
                                     "fix": "quarantine",
                                     "problem": problem})
        try:
            on_disk = os.listdir(self.directory)
        except OSError:
            on_disk = []
        for name in on_disk:
            if name.endswith(SEGMENT_SUFFIX) and name not in listed:
                findings.append({
                    "metric": "", "file": name, "fix": "orphan",
                    "problem": "segment file not in manifest "
                               "(interrupted spill)"})
        return findings

    def remove_orphan(self, file: str) -> None:
        path = os.path.join(self.directory, file)
        try:
            os.replace(path, path + QUARANTINE_SUFFIX)
        except OSError:  # pragma: no cover - disk trouble
            pass

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def cold_bytes(self) -> int:
        with self._lock:
            return sum(int(e["bytes"])
                       for rec in self._metrics.values()
                       for e in rec.get("segments", ()))

    def memory_info(self) -> dict:
        with self._lock:
            segs = [e for rec in self._metrics.values()
                    for e in rec.get("segments", ())]
            return {
                "series": 0,  # identity lives in the raw store
                "points": sum(int(e["rows"]) for e in segs),
                "segments": len(segs),
                "disk_bytes": sum(int(e["bytes"]) for e in segs),
                "resident_bytes": 0,  # mmap: pages are reclaimable
                "live_bytes": 0, "dead_bytes": 0,
            }

    def counters(self) -> dict[str, Any]:
        return {
            "segmentsWritten": self.segments_written,
            "segmentsQuarantined": self.segments_quarantined,
            "segmentsDropped": self.segments_dropped,
            "segmentsCompacted": self.segments_compacted,
            "pointsSpilled": self.points_spilled,
            "pointsDeleted": self.points_deleted,
            "bytesSpilled": self.bytes_spilled,
            "coldBytes": self.cold_bytes(),
            "spillErrors": self.spill_errors,
            "readErrors": self.read_errors,
            "degradedServes": self.degraded_serves,
            "lastError": self.last_error,
        }

    def health_info(self) -> dict[str, Any]:
        doc = {"enabled": True, "dir": self.directory,
               **self.counters()}
        if self.read_breaker is not None:
            doc["breaker"] = self.read_breaker.health_info()
        return doc

    def collect_stats(self, collector) -> None:
        collector.record("coldstore.segments.written",
                         self.segments_written)
        collector.record("coldstore.segments.quarantined",
                         self.segments_quarantined)
        collector.record("coldstore.segments.compacted",
                         self.segments_compacted)
        collector.record("coldstore.points.spilled",
                         self.points_spilled)
        collector.record("coldstore.bytes", self.cold_bytes())
        collector.record("coldstore.spill_errors", self.spill_errors)
        collector.record("coldstore.read_errors", self.read_errors)
        collector.record("coldstore.degraded_serves",
                         self.degraded_serves)
