"""Self-driving control plane: a closed loop that watches the
workload surfaces the TSD already exports (query-shape log, SLO burn,
per-shard load) and steers three actuators — adaptive
materialization, multi-tenant QoS, and placement. See
:mod:`opentsdb_tpu.control.plane`."""
