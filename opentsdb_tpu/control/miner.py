"""Shape miner: scores standing-query candidates out of the shape log.

One scan reads ``query_shapes.jsonl`` (rotated generation first, same
walk as ``/api/stats/query_shapes``), groups lines by their canonical
``cq`` candidate tag (:mod:`opentsdb_tpu.control.shapes`), and scores
each group ``count x miss-cost`` — the workload-observed benefit of
materializing that shape as a standing shared partial: how often it
is pulled, times what a pull costs when neither the streaming
registry nor the result cache already answers it.

The miner is a PURE function of the log bytes: same log ⇒ same scores
⇒ same materialization set (the determinism oracle the control test
battery checks). Torn lines, non-JSON lines and lines without a
candidate tag are skipped exactly like the stats endpoint skips them.
"""

from __future__ import annotations

import json
import os
from dataclasses import dataclass, field


@dataclass
class ShapeScore:
    """One mined candidate's aggregate."""

    candidate: str
    count: int = 0
    miss_count: int = 0
    durations: list = field(default_factory=list)   # miss durations
    all_durations: list = field(default_factory=list)

    @property
    def miss_cost_ms(self) -> float:
        """p50 of cache-miss durations; a shape the cache always
        answers falls back to the overall p50 (its miss cost is
        unobserved, not zero — scoring it zero would starve shapes
        that are hot precisely because the cache carries them)."""
        vals = self.durations or self.all_durations
        return _p50(vals)

    @property
    def score(self) -> float:
        return round(self.count * self.miss_cost_ms, 3)


def _p50(vals: list) -> float:
    if not vals:
        return 0.0
    s = sorted(vals)
    return float(s[len(s) // 2])


def mine_shapes(shape_path: str) -> list[ShapeScore]:
    """Scan the shape log into candidate scores, highest score first;
    ties break on the candidate string so the ordering (and therefore
    the materialization set) is fully deterministic."""
    shapes: dict[str, ShapeScore] = {}
    if not shape_path:
        return []
    for p in (shape_path + ".1", shape_path):
        if not os.path.isfile(p):
            continue
        try:
            with open(p, "r", encoding="utf-8") as fh:
                for line in fh:
                    try:
                        doc = json.loads(line)
                    except ValueError:
                        continue  # torn tail of a rotation
                    if not isinstance(doc, dict):
                        continue
                    cand = doc.get("cq")
                    if not cand or not isinstance(cand, str):
                        continue
                    s = shapes.get(cand)
                    if s is None:
                        s = shapes[cand] = ShapeScore(cand)
                    s.count += 1
                    dur = float(doc.get("durationMs", 0.0))
                    s.all_durations.append(dur)
                    if doc.get("cache") == "miss":
                        s.miss_count += 1
                        s.durations.append(dur)
        except OSError:
            continue
    return sorted(shapes.values(),
                  key=lambda s: (-s.score, s.candidate))


__all__ = ["ShapeScore", "mine_shapes"]
