"""Placement control: SLO-fed hot-shard detection and reshard plans.

The planner reads the per-peer load counters the router already
keeps (forwarded + spooled points — the write traffic each shard
absorbed) plus breaker state, flags peers carrying more than
``hot_ratio`` x the mean load, and folds that into a *proposed* ring
spec: the same peer set with the vnode count stepped up, which
re-spreads the hot shard's hash ranges without moving the membership.

The proposal is exactly that — a proposal. ``GET /api/control/plan``
shows it (with a content-addressed ``planId``), and only an operator
confirming that id via POST — or ``tsd.control.placement.auto=true``
letting the control loop confirm its own plan — feeds it to the
existing ``POST /api/cluster/reshard`` machinery. A wrong plan
therefore costs an operator review, never data: reshard itself keeps
its dual-read/cutover safety.
"""

from __future__ import annotations

import hashlib
import json
import time
from typing import Any

#: vnode step applied by a rebalance proposal (bounded so repeated
#: auto-applies converge instead of doubling forever)
VNODE_STEP = 16
MAX_VNODES = 512


def shard_loads(router) -> dict[str, dict[str, Any]]:
    """Per-peer load signal out of the router's own counters."""
    loads: dict[str, dict[str, Any]] = {}
    for name, peer in router.peers.items():
        loads[name] = {
            "points": int(peer.forwarded_points +
                          peer.spooled_points),
            "spooledPoints": int(peer.spooled_points),
            "queryFailures": int(peer.query_failures),
            "breakerOpen": bool(peer.breaker.blocking()),
        }
    return loads


def build_plan(router, hot_ratio: float,
               now_ms: int | None = None) -> dict[str, Any]:
    """One placement assessment: loads, hot shards, and (when any
    shard is hot) a proposed reshard spec. Pure function of the
    router's counters — no I/O, no mutation."""
    loads = shard_loads(router)
    plan: dict[str, Any] = {
        "ts": int(now_ms if now_ms is not None else
                  time.time() * 1000),
        "vnodes": int(router.ring.vnodes),
        "loads": loads,
        "hotShards": [],
        "proposal": None,
        "reason": "balanced",
    }
    if len(loads) < 2:
        plan["reason"] = "single shard: nothing to rebalance"
        return plan
    points = [entry["points"] for entry in loads.values()]
    total = sum(points)
    if total <= 0:
        plan["reason"] = "no traffic observed"
        return plan
    mean = total / len(points)
    hot = sorted(name for name, entry in loads.items()
                 if entry["points"] > hot_ratio * mean)
    plan["hotShards"] = hot
    if not hot:
        return plan
    vnodes = min(int(router.ring.vnodes) + VNODE_STEP, MAX_VNODES)
    if vnodes <= router.ring.vnodes:
        plan["reason"] = ("hot shards %s but vnodes already at the "
                          "%d cap" % (",".join(hot), MAX_VNODES))
        return plan
    peers = ",".join(
        "%s=%s:%d" % (name, peer.client.host, peer.client.port)
        for name, peer in sorted(router.peers.items()))
    plan["proposal"] = {"peers": peers, "vnodes": vnodes}
    plan["reason"] = ("shards %s exceed %.1fx mean load; re-spread "
                      "hash ranges at vnodes=%d"
                      % (",".join(hot), hot_ratio, vnodes))
    plan["planId"] = plan_id(plan)
    return plan


def plan_id(plan: dict[str, Any]) -> str:
    """Content address of the actionable part of a plan. Confirming a
    planId that no longer matches the current proposal is rejected —
    the operator approved a different world."""
    doc = json.dumps({"proposal": plan.get("proposal"),
                      "hotShards": plan.get("hotShards")},
                     sort_keys=True, separators=(",", ":"))
    return hashlib.sha256(doc.encode()).hexdigest()[:16]


__all__ = ["MAX_VNODES", "VNODE_STEP", "build_plan", "plan_id",
           "shard_loads"]
