"""Self-driving control plane: one background loop, three actuators.

Each tick is a background trace root (``control.loop``) gated by its
own circuit breaker, and runs three independent actuators:

* **materialize** (fault site ``control.materialize``) — mine the
  query-shape log for hot decomposable shapes and keep the top
  scorers registered as auto continuous queries (``auto-*`` ids)
  through the streaming registry; retire them after
  ``tsd.control.materialize.hysteresis`` consecutive cold scans.
* **qos** (fault site ``control.qos``) — recompute tenant burn
  penalties and reset per-interval byte windows on the
  :class:`~opentsdb_tpu.control.qos.TenantGovernor`. Admission itself
  never runs here: a dead loop means stale penalties, not closed
  doors.
* **placement** (fault site ``control.placement``) — rebuild the
  hot-shard assessment and proposed ring spec. The plan is only
  *executed* (through the existing reshard machinery) when an
  operator confirms its planId, or ``tsd.control.placement.auto``
  lets the loop confirm its own plan.

Failure semantics follow the lifecycle sweeper to the letter: an
actuator that throws is counted, trips the shared breaker, tags the
trace — and the data plane never notices. A broken control loop can
park every actuator and writes still ack, queries still answer.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

from opentsdb_tpu.control import shapes as shapes_mod
from opentsdb_tpu.control.miner import mine_shapes
from opentsdb_tpu.control.placement import build_plan, plan_id
from opentsdb_tpu.control.qos import TenantGovernor
from opentsdb_tpu.query.model import BadRequestError
from opentsdb_tpu.utils.faults import CircuitBreaker

LOG = logging.getLogger(__name__)


class ControlPlane:
    """(see module docstring)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        cfg = tsdb.config
        self.interval_s = cfg.get_float("tsd.control.interval_s",
                                        15.0)
        self.breaker = CircuitBreaker(
            "control.loop",
            failure_threshold=cfg.get_int(
                "tsd.control.breaker.failure_threshold", 3),
            reset_timeout_ms=cfg.get_float(
                "tsd.control.breaker.reset_timeout_ms", 60000.0))
        # actuator 1: adaptive materialization
        self.mat_enable = cfg.get_bool(
            "tsd.control.materialize.enable", True)
        self.mat_max = cfg.get_int("tsd.control.materialize.max", 8)
        self.mat_min_score = cfg.get_float(
            "tsd.control.materialize.min_score", 1.0)
        self.mat_hysteresis = max(cfg.get_int(
            "tsd.control.materialize.hysteresis", 3), 1)
        # fold-memory pressure knob (ROADMAP 4a follow-through): a
        # mined shape's score is divided by (1 + projected_bytes /
        # this), so between two equally hot shapes the cheaper ring
        # materializes first; shapes projecting past the tenant fold
        # budget are refused outright
        self.mat_mem_penalty_bytes = max(int(cfg.get_float(
            "tsd.control.materialize.mem_penalty_mb", 64.0)
            * (1 << 20)), 1)
        self.fold_budget_skips = 0
        # actuator 2: multi-tenant QoS
        self.qos = TenantGovernor(tsdb)
        # actuator 3: placement
        self.place_enable = cfg.get_bool(
            "tsd.control.placement.enable", True)
        self.place_auto = cfg.get_bool("tsd.control.placement.auto",
                                       False)
        self.hot_ratio = max(cfg.get_float(
            "tsd.control.placement.hot_ratio", 2.0), 1.0)
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        self._tick_lock = threading.Lock()
        self._lock = threading.Lock()
        # candidate -> {"id", "score", "registeredMs", "coldScans"}
        # tsdlint: allow[unbounded-growth] capped by mat_max live
        # entries; retired entries are deleted
        self._materialized: dict[str, dict[str, Any]] = {}
        # candidates the registry rejected — never retried
        # tsdlint: allow[unbounded-growth] bounded by distinct shapes
        # in one shape-log generation (the log itself rotates)
        self._blacklist: set[str] = set()
        self._plan: dict[str, Any] | None = None
        self._applied_plan_id = ""
        # counters
        self.ticks = 0
        self.tick_errors = 0
        self.materialized_total = 0
        self.retired_total = 0
        self.plans_applied = 0
        self.last_error = ""
        self.last_tick_time = 0.0
        self.last_tick_duration_ms = 0.0

    def wire(self) -> None:
        """Attach the per-tenant result-cache insert gate. Idempotent;
        the TSDB accessor calls this OUTSIDE its lazy-build lock —
        ``result_cache`` is itself lazy behind the same lock, so the
        attach cannot happen inside the constructor."""
        if not self.qos.enabled or self.qos.cache_budget_bytes <= 0:
            return
        cache = self.tsdb.result_cache
        if cache is not None and cache.insert_gate is None:
            # the gate consults the worker-thread tenant binding at
            # insert time
            cache.insert_gate = self.qos.cache_gate

    # ------------------------------------------------------------------
    # scheduler surface (started by TSDServer, stopped on shutdown)
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="tsd-control",
                             daemon=True)
        self._thread = t
        t.start()
        LOG.info("control plane ticking every %.0fs", self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.tick()  # never raises

    # ------------------------------------------------------------------
    # one tick
    # ------------------------------------------------------------------

    def tick(self, now_ms: int | None = None) -> dict[str, Any]:
        """Run every actuator once; returns a report. Never raises —
        this loop observes and steers, it must not be able to fail
        the data plane it steers."""
        if not self._tick_lock.acquire(blocking=False):
            return {"skipped": "tick already running"}
        t0 = time.monotonic()
        now = int(now_ms if now_ms is not None else
                  time.time() * 1000)
        report: dict[str, Any] = {"errors": {}}
        from opentsdb_tpu.obs import trace as trace_mod
        tracer = getattr(self.tsdb, "tracer", None)
        tctx = tracer.start_background("control.loop") \
            if tracer is not None and tracer.enabled else None
        try:
            if not self.breaker.allow():
                report["skipped"] = "breaker open"
                return report
            with trace_mod.use(tctx):
                for name, actuator in (
                        ("materialize", self._materialize_tick),
                        ("qos", self._qos_tick),
                        ("placement", self._placement_tick)):
                    try:
                        actuator(now, report)
                    except Exception as exc:  # noqa: BLE001 - park loudly
                        msg = f"{type(exc).__name__}: {exc}"
                        report["errors"][name] = msg
                        self.last_error = f"{name}: {msg}"
                        LOG.warning(
                            "control actuator %s failed (%s); the "
                            "data plane is unaffected", name, msg)
            if report["errors"]:
                self.tick_errors += 1
                self.breaker.record_failure()
                if tctx is not None:
                    tctx.set_error(RuntimeError(self.last_error))
            else:
                self.breaker.record_success()
            return report
        finally:
            self.ticks += 1
            self.last_tick_time = time.time()
            self.last_tick_duration_ms = \
                (time.monotonic() - t0) * 1e3
            report["durationMs"] = round(self.last_tick_duration_ms,
                                         1)
            if tctx is not None:
                if report.get("skipped"):
                    # breaker-open no-op ticks would churn request
                    # traces out of the ring (lifecycle-sweep rule)
                    tctx.sampled = False
                tctx.tag(materialized=len(self._materialized),
                         errors=len(report["errors"]))
                tracer.finish(tctx)
            self._tick_lock.release()

    # ------------------------------------------------------------------
    # actuator 1: adaptive materialization
    # ------------------------------------------------------------------

    def _materialize_tick(self, now_ms: int, report: dict) -> None:
        faults = getattr(self.tsdb, "faults", None)
        if faults is not None:
            faults.check("control.materialize")
        if not self.mat_enable:
            return
        registry = self.tsdb.streaming
        tracer = getattr(self.tsdb, "tracer", None)
        shape_path = getattr(tracer, "shape_path", "") \
            if tracer is not None else ""
        if registry is None or not shape_path:
            return
        scores = mine_shapes(shape_path)
        with self._lock:
            blacklist = set(self._blacklist)
        # streaming partial-size accounting (ROADMAP 4a): project
        # each candidate's standing ring cost from the live partials'
        # membership, penalize the score by it, and refuse shapes the
        # tenant fold budget could never admit — the miner must not
        # materialize a ring the QoS gate would have refused a tenant
        budget = 0
        if self.qos.enabled and self.qos.fold_budget_bytes > 0:
            budget = self.qos.fold_budget_bytes
        eligible = []
        over_budget = 0
        for s in scores:
            if s.candidate in blacklist:
                continue
            try:
                proj = registry.projected_fold_bytes(
                    shapes_mod.candidate_body(s.candidate))
            except Exception:  # noqa: BLE001 - projection is advisory
                proj = 0
            if budget and proj > budget:
                over_budget += 1
                self.fold_budget_skips += 1
                continue
            adj = s.score / (1.0 + proj / self.mat_mem_penalty_bytes)
            if adj >= self.mat_min_score:
                eligible.append((adj, s))
        eligible.sort(key=lambda p: -p[0])
        want = [s for _adj, s in eligible[:self.mat_max]]
        want_set = {s.candidate for s in want}
        registered = retired = 0
        for s in want:
            with self._lock:
                entry = self._materialized.get(s.candidate)
                if entry is not None:
                    entry["score"] = s.score
                    entry["coldScans"] = 0
                    continue
            cid = shapes_mod.auto_id(s.candidate)
            if registry.get(cid) is None:
                body = shapes_mod.candidate_body(s.candidate)
                body["id"] = cid
                try:
                    registry.register(body, now_ms=now_ms)
                except BadRequestError as exc:
                    # the registry is the authority on what can stand;
                    # a shape it rejects is never retried
                    with self._lock:
                        self._blacklist.add(s.candidate)
                    LOG.info("control: registry rejected mined shape "
                             "(%s); blacklisted", exc)
                    continue
            with self._lock:
                self._materialized[s.candidate] = {
                    "id": cid, "score": s.score,
                    "missCount": s.miss_count,
                    "registeredMs": now_ms, "coldScans": 0,
                }
            self.materialized_total += 1
            registered += 1
        # hysteresis retirement: a standing auto-CQ must score cold on
        # mat_hysteresis CONSECUTIVE scans before its ring memory is
        # released — one quiet scan must not thrash a hot dashboard
        with self._lock:
            cold = [(cand, entry) for cand, entry
                    in self._materialized.items()
                    if cand not in want_set]
        for cand, entry in cold:
            entry["coldScans"] += 1
            if entry["coldScans"] < self.mat_hysteresis:
                continue
            registry.delete(entry["id"])
            with self._lock:
                self._materialized.pop(cand, None)
            self.retired_total += 1
            retired += 1
        report["materialize"] = {
            "mined": len(scores), "standing": len(self._materialized),
            "registered": registered, "retired": retired,
            "overBudget": over_budget,
        }

    # ------------------------------------------------------------------
    # actuator 2: multi-tenant QoS
    # ------------------------------------------------------------------

    def _qos_tick(self, now_ms: int, report: dict) -> None:
        faults = getattr(self.tsdb, "faults", None)
        if faults is not None:
            faults.check("control.qos")
        if not self.qos.enabled:
            return
        penalties = self.qos.refresh(now_s=now_ms / 1000.0)
        report["qos"] = {
            "tenants": len(penalties),
            "penalized": sorted(t for t, p in penalties.items()
                                if p < 1.0),
        }

    # ------------------------------------------------------------------
    # actuator 3: placement
    # ------------------------------------------------------------------

    def _placement_tick(self, now_ms: int, report: dict) -> None:
        faults = getattr(self.tsdb, "faults", None)
        if faults is not None:
            faults.check("control.placement")
        if not self.place_enable:
            return
        router = self.tsdb.cluster
        if router is None:
            return
        plan = build_plan(router, self.hot_ratio, now_ms=now_ms)
        with self._lock:
            self._plan = plan
        report["placement"] = {"hotShards": plan["hotShards"],
                               "proposal": bool(plan["proposal"])}
        if not self.place_auto or not plan.get("proposal"):
            return
        if router.state.active:
            report["placement"]["deferred"] = "reshard in progress"
            return
        pid = plan.get("planId", "")
        if pid and pid == self._applied_plan_id:
            return  # already cutting over to this exact proposal
        result = self.apply_plan(pid)
        report["placement"]["applied"] = result

    def apply_plan(self, pid: str) -> dict[str, Any]:
        """Execute the CURRENT proposal through the existing reshard
        machinery. ``pid`` must match the standing plan — confirming
        a stale planId means the operator approved a different world
        and is rejected."""
        with self._lock:
            plan = self._plan
        if plan is None or not plan.get("proposal"):
            raise BadRequestError("no reshard proposal is standing")
        if not pid or pid != plan.get("planId"):
            raise BadRequestError(
                "planId does not match the standing proposal "
                "(re-read /api/control/plan and confirm that id)")
        router = self.tsdb.cluster
        if router is None:
            raise BadRequestError("this TSD is not a cluster router")
        proposal = plan["proposal"]
        result = router.begin_reshard(proposal["peers"],
                                      vnodes=proposal["vnodes"])
        self._applied_plan_id = pid
        self.plans_applied += 1
        LOG.info("control: reshard plan %s applied (vnodes=%d)",
                 pid, proposal["vnodes"])
        return result

    # ------------------------------------------------------------------
    # exposition
    # ------------------------------------------------------------------

    def materialized_info(self) -> list[dict[str, Any]]:
        registry = self.tsdb.streaming
        with self._lock:
            entries = sorted(self._materialized.items(),
                             key=lambda kv: kv[1]["id"])
        out = []
        for cand, entry in entries:
            doc = {"id": entry["id"], "score": entry["score"],
                   "missCount": entry.get("missCount", 0),
                   "registeredMs": entry["registeredMs"],
                   "coldScans": entry["coldScans"],
                   "body": shapes_mod.candidate_body(cand)}
            cq = registry.get(entry["id"]) \
                if registry is not None else None
            if cq is not None:
                doc["emitSeq"] = cq.emit_seq
            out.append(doc)
        return out

    def plan_info(self) -> dict[str, Any]:
        with self._lock:
            plan = self._plan
        if plan is None:
            return {"reason": "no assessment yet", "proposal": None,
                    "auto": self.place_auto}
        doc = dict(plan)
        doc["auto"] = self.place_auto
        doc["appliedPlanId"] = self._applied_plan_id
        return doc

    def describe(self) -> dict[str, Any]:
        with self._lock:
            standing = len(self._materialized)
            blacklisted = len(self._blacklist)
        return {
            "intervalS": self.interval_s,
            "running": self._thread is not None,
            "ticks": self.ticks,
            "tickErrors": self.tick_errors,
            "lastError": self.last_error,
            "lastTickDurationMs": round(self.last_tick_duration_ms,
                                        1),
            "breaker": self.breaker.state,
            "materialize": {
                "enabled": self.mat_enable, "max": self.mat_max,
                "minScore": self.mat_min_score,
                "hysteresis": self.mat_hysteresis,
                "standing": standing, "blacklisted": blacklisted,
                "total": self.materialized_total,
                "retired": self.retired_total,
                "foldBudgetSkips": self.fold_budget_skips,
            },
            "qos": self.qos.describe(),
            "placement": {
                "enabled": self.place_enable, "auto": self.place_auto,
                "hotRatio": self.hot_ratio,
                "plansApplied": self.plans_applied,
            },
        }

    def collect_stats(self, collector) -> None:
        collector.record("control.ticks", self.ticks)
        collector.record("control.tick_errors", self.tick_errors)
        with self._lock:
            collector.record("control.materialized",
                             len(self._materialized))
        collector.record("control.materialized.total",
                         self.materialized_total)
        collector.record("control.retired.total", self.retired_total)
        collector.record("control.plans_applied", self.plans_applied)
        collector.record("control.fold_budget_skips",
                         self.fold_budget_skips)
        self.qos.collect_stats(collector)


__all__ = ["ControlPlane"]
