"""Multi-tenant QoS: weighted fair shares over the admission idiom.

Tenant identity comes from a configured HTTP header
(``tsd.control.tenant.header``) at the server's admission seam, or —
for stats attribution only — from a configured tag
(``tsd.control.tenant.tag``) matched against a query's literal
filters. The governor turns the server's single in-flight budget
(``tsd.query.admission.max_inflight``) into weighted fair shares over
the tenants seen recently: a tenant at or past its share sheds with
the existing structured 503 + ``Retry-After`` (cause ``tenant``)
while under-share tenants keep being admitted — which is exactly the
noisy-dashboard-farm isolation the north star's multi-user traffic
needs.

SLO burn closes the loop: each tenant feeds its own
:class:`~opentsdb_tpu.obs.slo.SloTracker`, and the control loop's QoS
actuator (fault site ``control.qos``) multiplies the weight of any
tenant burning its availability budget by ``burn_penalty`` — burn
rate decides who sheds first. The actuator only ever updates
*penalties and windows*; admission decisions themselves are plain
locked dict arithmetic with no fault site and no I/O, so a broken (or
killed) control loop leaves admission running on the last computed
penalties — degraded staleness, never a failed request.

Byte budgets: ``tenant_cache_mb`` bounds how many result-cache bytes
one tenant may insert per control interval (the gate is consulted by
the cache's ``_put``; over-budget results still serve, they just
don't cache), and ``tenant_fold_mb`` bounds a tenant's standing
continuous-query ring bytes at registration time.
"""

from __future__ import annotations

import threading
import time
from typing import Any

from opentsdb_tpu.obs.slo import SloTracker

#: catch-all bucket once max_tenants distinct identities were seen
OVERFLOW_TENANT = "other"

#: a tenant is "active" (counted in the fair-share split) when seen
#: within this many seconds
ACTIVE_WINDOW_S = 30.0


class _Tenant:
    __slots__ = ("name", "inflight", "requests", "shed", "errors",
                 "last_seen_s", "cache_bytes", "slo", "penalty")

    def __init__(self, name: str, slo: SloTracker | None):
        self.name = name
        self.inflight = 0
        self.requests = 0
        self.shed = 0
        self.errors = 0
        self.last_seen_s = 0.0
        self.cache_bytes = 0       # result-cache inserts this window
        self.slo = slo
        self.penalty = 1.0         # burn-rate weight multiplier


class TenantGovernor:
    """(see module docstring)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        cfg = tsdb.config
        self.enabled = cfg.get_bool("tsd.control.qos.enable", False)
        self.header = cfg.get_string("tsd.control.tenant.header",
                                     "x-tsd-tenant").lower()
        self.tag = cfg.get_string("tsd.control.tenant.tag", "")
        self.max_tenants = cfg.get_int("tsd.control.qos.max_tenants",
                                       32)
        self.burn_penalty = min(max(cfg.get_float(
            "tsd.control.qos.burn_penalty", 0.5), 0.01), 1.0)
        self.cache_budget_bytes = cfg.get_int(
            "tsd.control.qos.tenant_cache_mb", 0) << 20
        self.fold_budget_bytes = cfg.get_int(
            "tsd.control.qos.tenant_fold_mb", 0) << 20
        self.weights: dict[str, float] = {}
        for part in cfg.get_string("tsd.control.qos.weights",
                                   "").split(","):
            part = part.strip()
            if not part or ":" not in part:
                continue
            name, _, w = part.rpartition(":")
            try:
                self.weights[name.strip()] = max(float(w), 0.01)
            except ValueError:
                continue
        self._lock = threading.Lock()
        # tsdlint: allow[unbounded-growth] capped at max_tenants
        # entries — the (max_tenants+1)th identity collapses into the
        # OVERFLOW_TENANT bucket (_get)
        self._tenants: dict[str, _Tenant] = {}
        self._local = threading.local()
        # counters
        self.shed_total = 0
        self.cache_gate_rejects = 0
        self.fold_budget_rejects = 0
        self.refreshes = 0

    # -- identity ------------------------------------------------------

    def tenant_of(self, headers) -> str | None:
        """Header-derived tenant identity, or None (untenanted
        requests ride plain global admission)."""
        if not self.enabled or not self.header:
            return None
        value = headers.get(self.header, "") if headers else ""
        if not value:
            return None
        return str(value)[:64]

    def tenant_of_query(self, tsq) -> str | None:
        """Tag-derived identity for stats attribution: the single
        literal value of a filter on the configured tenant tag."""
        if not self.enabled or not self.tag:
            return None
        for sub in getattr(tsq, "queries", ()):
            for f in getattr(sub, "filters", ()):
                doc = f.to_json()
                if doc.get("tagk") != self.tag:
                    continue
                value = str(doc.get("filter", ""))
                if value and "*" not in value and "|" not in value:
                    return value[:64]
        return None

    # -- request-scoped binding (result-cache gate) --------------------

    def bind(self, tenant: str) -> None:
        self._local.tenant = tenant

    def unbind(self) -> None:
        self._local.tenant = None

    def bound_tenant(self) -> str | None:
        return getattr(self._local, "tenant", None)

    # -- admission -----------------------------------------------------

    def _get(self, name: str, now_s: float) -> _Tenant:
        """Caller holds the lock."""
        t = self._tenants.get(name)
        if t is None:
            if len(self._tenants) >= self.max_tenants and \
                    name != OVERFLOW_TENANT:
                return self._get(OVERFLOW_TENANT, now_s)
            slo = None
            if self.tsdb.slo.enabled:
                slo = SloTracker(self.tsdb.config)
            t = self._tenants[name] = _Tenant(name, slo)
        t.last_seen_s = now_s
        return t

    def _share(self, tenant: _Tenant, max_inflight: int,
               now_s: float) -> int:
        """This tenant's fair in-flight share: its (penalty-adjusted)
        weight's fraction of ``max_inflight`` over the recently-seen
        tenants. Caller holds the lock."""
        w_self = 0.0
        w_total = 0.0
        for t in self._tenants.values():
            if now_s - t.last_seen_s > ACTIVE_WINDOW_S:
                continue
            w = self.weights.get(t.name, 1.0) * t.penalty
            w_total += w
            if t is tenant:
                w_self = w
        if w_total <= 0.0 or w_self <= 0.0:
            return max_inflight
        return max(int(max_inflight * w_self / w_total), 1)

    def try_admit(self, tenant_name: str, max_inflight: int,
                  now_s: float | None = None) -> str | None:
        """``"tenant"`` when this tenant is at/past its fair share of
        the in-flight budget, else None. With no global in-flight
        limit configured there is nothing to share — every tenant is
        admitted (attribution still updates)."""
        now = now_s if now_s is not None else time.time()
        with self._lock:
            t = self._get(tenant_name, now)
            t.requests += 1
            if max_inflight <= 0:
                return None
            if t.inflight >= self._share(t, max_inflight, now):
                t.shed += 1
                self.shed_total += 1
                return "tenant"
            return None

    def started(self, tenant_name: str) -> None:
        with self._lock:
            t = self._tenants.get(tenant_name)
            if t is not None:
                t.inflight += 1

    def finished(self, tenant_name: str) -> None:
        with self._lock:
            t = self._tenants.get(tenant_name)
            if t is not None and t.inflight > 0:
                t.inflight -= 1

    # -- SLO attribution ----------------------------------------------

    def record(self, tenant_name: str, latency_ms: float,
               errored: bool, now_s: float | None = None) -> None:
        now = now_s if now_s is not None else time.time()
        with self._lock:
            t = self._get(tenant_name, now)
            if errored:
                t.errors += 1
            slo = t.slo
        if slo is not None:
            slo.record("query", latency_ms, errored, now_s=now)

    # -- byte budgets --------------------------------------------------

    def cache_gate(self, nbytes: int) -> bool:
        """Result-cache insert gate: False when the bound tenant has
        already inserted its per-interval byte budget (the result
        still serves; it just isn't retained on this tenant's dime).
        Untenanted inserts always pass."""
        if not self.enabled or self.cache_budget_bytes <= 0:
            return True
        tenant = self.bound_tenant()
        if tenant is None:
            return True
        with self._lock:
            t = self._tenants.get(tenant)
            if t is None:
                return True
            if t.cache_bytes + nbytes > self.cache_budget_bytes:
                self.cache_gate_rejects += 1
                return False
            t.cache_bytes += nbytes
            return True

    def fold_budget_allows(self, tenant: str | None, registry,
                           body: dict | None = None) -> bool:
        """Whether this tenant may register another continuous query
        under its standing ring-byte budget. Accounts the ACTUAL
        resident ring bytes of the tenant's registrations
        (``registry.tenant_fold_bytes`` — the streaming partial-size
        surface, not the old windows-x-series guess) plus, when the
        candidate ``body`` is given, the projected fold memory the
        new registration would add — so one oversized shape is
        refused up front instead of landing and starving the tenant's
        next register. Auto-materialized CQs (owned by the control
        plane) are capped by ``tsd.control.materialize.max`` and the
        miner's memory penalty instead."""
        if not self.enabled or self.fold_budget_bytes <= 0 \
                or tenant is None:
            return True
        held = registry.tenant_fold_bytes(tenant)
        projected = 0
        if body is not None and held > 0:
            # a tenant holding nothing may always register once (the
            # quota's first-use contract); after that the projection
            # refuses shapes that would blow through the budget
            # instead of letting them land first
            try:
                projected = registry.projected_fold_bytes(body)
            except Exception:  # noqa: BLE001 - projection is advisory
                projected = 0
        if held >= self.fold_budget_bytes \
                or (held > 0
                    and held + projected > self.fold_budget_bytes):
            self.fold_budget_rejects += 1
            return False
        return True

    # -- the control-loop actuator ------------------------------------

    def refresh(self, now_s: float | None = None) -> dict[str, float]:
        """One QoS tick: derive each tenant's burn penalty from its
        short-window availability burn and reset the per-interval
        cache-byte windows. Returns {tenant: penalty} for the tick
        report. Runs under the ``control.qos`` fault site (armed =
        penalties go stale; admission keeps running)."""
        now = now_s if now_s is not None else time.time()
        with self._lock:
            tenants = list(self._tenants.values())
        penalties: dict[str, float] = {}
        for t in tenants:
            penalty = 1.0
            if t.slo is not None:
                burns = t.slo.burn_rates(now_s=now)
                avail = burns.get("query", {}).get("availability", {})
                worst = max(avail.values(), default=0.0)
                if worst > 1.0:
                    penalty = self.burn_penalty
            penalties[t.name] = penalty
        with self._lock:
            for t in tenants:
                t.penalty = penalties.get(t.name, 1.0)
                t.cache_bytes = 0
            self.refreshes += 1
        return penalties

    # -- exposition ----------------------------------------------------

    def describe(self, now_s: float | None = None) -> dict[str, Any]:
        now = now_s if now_s is not None else time.time()
        with self._lock:
            tenants = list(self._tenants.values())
            doc: dict[str, Any] = {
                "enabled": self.enabled,
                "header": self.header,
                "tag": self.tag,
                "shedTotal": self.shed_total,
                "cacheGateRejects": self.cache_gate_rejects,
                "foldBudgetRejects": self.fold_budget_rejects,
                "refreshes": self.refreshes,
            }
        per: dict[str, Any] = {}
        for t in sorted(tenants, key=lambda x: x.name):
            entry: dict[str, Any] = {
                "inflight": t.inflight,
                "requests": t.requests,
                "shed": t.shed,
                "errors": t.errors,
                "weight": self.weights.get(t.name, 1.0),
                "penalty": t.penalty,
                "activeAgeS": round(max(now - t.last_seen_s, 0.0), 1),
            }
            if t.slo is not None:
                burns = t.slo.burn_rates(now_s=now)
                entry["burn"] = burns.get("query", {})
            per[t.name] = entry
        doc["tenants"] = per
        return doc

    def collect_stats(self, collector) -> None:
        if not self.enabled:
            return
        collector.record("control.qos.shed", self.shed_total)
        collector.record("control.qos.cache_gate_rejects",
                         self.cache_gate_rejects)
        with self._lock:
            tenants = list(self._tenants.values())
        for t in sorted(tenants, key=lambda x: x.name):
            collector.record("control.tenant.requests", t.requests,
                             tenant=t.name)
            collector.record("control.tenant.shed", t.shed,
                             tenant=t.name)
            collector.record("control.tenant.inflight", t.inflight,
                             tenant=t.name)
            if t.slo is not None:
                burns = t.slo.burn_rates()
                avail = burns.get("query", {}).get("availability", {})
                for label, burn in avail.items():
                    collector.record("control.tenant.burn_rate", burn,
                                     tenant=t.name, window=label)


__all__ = ["ACTIVE_WINDOW_S", "OVERFLOW_TENANT", "TenantGovernor"]
