"""Canonical continuous-query candidates for the shape miner.

The query-shape log (obs/trace.py) records per-request tags, not raw
bodies — so the HTTP layer tags each *materializable* query with a
canonical CQ-candidate body at serve time (:func:`cq_candidate`), and
the miner groups log lines on that tag. The candidate is a compact
sorted-key JSON string: byte-equal candidates ARE the same standing
query, which is what makes the miner deterministic (same shape log ⇒
same materialization set) and lets the auto-registered CQ serve the
repeat pull through the registry's normal ``(metric, identity_key)``
match.

Derivation is deliberately CONSERVATIVE and cheap: it mirrors the
registry's validation rules (fixed-interval decomposable downsample,
no tsuids/explicitTags/delete/calendar) but the registry stays the
authority — a candidate it still rejects is blacklisted by the
materializer, never retried.
"""

from __future__ import annotations

import hashlib
import json

from opentsdb_tpu.query.result_cache import _is_relative
from opentsdb_tpu.streaming.plan import DECOMPOSABLE_DS

#: auto-registered continuous-query id prefix — the materializer owns
#: (and only ever retires) ids under this prefix
AUTO_ID_PREFIX = "auto-"


def cq_candidate(tsq) -> str | None:
    """The canonical standing-query body for one served TSQuery, or
    None when the shape cannot be maintained as a continuous query.
    Only the live-dashboard shape (relative start) qualifies: an
    absolute historical window never repeats as ingest advances, so
    materializing it buys nothing the result cache doesn't."""
    if tsq.delete or tsq.timezone or tsq.use_calendar:
        return None
    if not tsq.queries:
        return None
    if not _is_relative(tsq.start) or not _is_relative(tsq.end):
        return None
    subs = []
    for sub in tsq.queries:
        if sub.tsuids or not sub.metric or sub.explicit_tags:
            return None
        spec = sub.ds_spec
        if spec is None or spec.run_all or spec.use_calendar \
                or spec.unit in ("n", "y") or spec.interval_ms <= 0:
            return None
        if spec.function not in DECOMPOSABLE_DS:
            return None
        body = {
            "aggregator": sub.aggregator,
            "metric": sub.metric,
            "downsample": sub.downsample,
            # filter ORDER is preserved: the registry's serve match
            # keys on identity_key(), whose filter tuple is ordered —
            # a sorted candidate would register a CQ the original
            # query could never hit
            "filters": [json.dumps(f.to_json(), sort_keys=True)
                        for f in sub.filters],
        }
        if sub.rate:
            body["rate"] = True
            body["rateOptions"] = sub.rate_options.to_json()
        if sub.percentiles:
            # order preserved, same identity_key reasoning as filters
            body["percentiles"] = list(sub.percentiles)
        subs.append(body)
    doc = {"start": tsq.start, "end": tsq.end or "",
           "queries": subs}
    return json.dumps(doc, sort_keys=True, separators=(",", ":"))


def candidate_body(candidate: str) -> dict:
    """Rebuild the registration body for one canonical candidate
    string (the inverse of :func:`cq_candidate`'s packing)."""
    doc = json.loads(candidate)
    queries = []
    for sub in doc["queries"]:
        q = {
            "aggregator": sub["aggregator"],
            "metric": sub["metric"],
            "downsample": sub["downsample"],
            "filters": [json.loads(f) for f in sub["filters"]],
        }
        if sub.get("rate"):
            q["rate"] = True
            q["rateOptions"] = sub.get("rateOptions") or {}
        if sub.get("percentiles"):
            q["percentiles"] = list(sub["percentiles"])
        queries.append(q)
    body = {"start": doc["start"], "queries": queries}
    if doc.get("end"):
        body["end"] = doc["end"]
    return body


def auto_id(candidate: str) -> str:
    """Deterministic registry id for one candidate: the same mined
    shape maps to the same CQ id on every node and every restart."""
    digest = hashlib.sha256(candidate.encode()).hexdigest()[:12]
    return f"{AUTO_ID_PREFIX}{digest}"


__all__ = ["AUTO_ID_PREFIX", "auto_id", "candidate_body",
           "cq_candidate"]
