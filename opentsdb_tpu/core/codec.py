"""Byte-level storage codec: row keys, qualifiers, values.

Implements the reference storage format (ref: ``src/core/Internal.java``,
``src/core/RowKey.java``) so that bulk import/export, ``fsck`` and on-disk
snapshots are bit-compatible with OpenTSDB 2.4 tables:

- row key   = ``[salt][metric_uid][base_time(4B)][tagk_uid tagv_uid]*``
  with ``base_time`` aligned down to :data:`const.MAX_TIMESPAN` (3600 s)
  (ref: src/core/IncomingDataPoints.java, RowKey.java:115-165)
- qualifier = 2 bytes for second precision (12-bit delta << 4 | flags) or
  4 bytes for ms precision (0xF nibble, 22-bit ms delta << 6 | flags)
  (ref: src/core/Internal.java:848-864)
- value     = 1/2/4/8-byte big-endian int, or 4/8-byte IEEE float, with
  flags = (FLAG_FLOAT if float) | (length - 1)

The hot query path never touches this codec — series live in the columnar
host store (:mod:`opentsdb_tpu.core.store`) as contiguous numpy arrays —
but the codec is the interoperability and durability contract.
"""

from __future__ import annotations

import struct
from typing import Iterator, NamedTuple

from opentsdb_tpu.core import const


class IllegalDataError(ValueError):
    """Corrupt or malformed stored data (ref: src/core/IllegalDataException.java)."""


# ---------------------------------------------------------------------------
# Value encoding (ref: src/core/Internal.java value extraction + TSDB.java)
# ---------------------------------------------------------------------------

def encode_value(value: int | float) -> tuple[bytes, int]:
    """Encode a datapoint value, returning ``(value_bytes, flags)``.

    Integers use variable-length encoding (1/2/4/8 bytes, big-endian,
    two's-complement); floats always encode as IEEE-754 (4 bytes when
    exactly representable in single precision, else 8).
    (ref: src/core/TSDB.java addPointInternal value handling)
    """
    if isinstance(value, bool):
        raise ValueError("boolean is not a valid datapoint value")
    if isinstance(value, int):
        if -(1 << 7) <= value < (1 << 7):
            return struct.pack(">b", value), 0
        if -(1 << 15) <= value < (1 << 15):
            return struct.pack(">h", value), 1
        if -(1 << 31) <= value < (1 << 31):
            return struct.pack(">i", value), 3
        if -(1 << 63) <= value < (1 << 63):
            return struct.pack(">q", value), 7
        raise ValueError(f"integer value out of int64 range: {value}")
    fval = float(value)
    as_f32 = struct.unpack(">f", struct.pack(">f", fval))[0]
    if as_f32 == fval or fval != fval:  # exact in f32, or NaN
        return struct.pack(">f", fval), const.FLAG_FLOAT | 3
    return struct.pack(">d", fval), const.FLAG_FLOAT | 7


def decode_value(value: bytes, flags: int) -> int | float:
    """Decode a value given its qualifier flags (ref: Internal.java:216-334)."""
    vlen = (flags & const.LENGTH_MASK) + 1
    if len(value) != vlen:
        raise IllegalDataError(
            f"value length {len(value)} does not match flags {flags:#x}")
    if flags & const.FLAG_FLOAT:
        if vlen == 4:
            return struct.unpack(">f", value)[0]
        if vlen == 8:
            return struct.unpack(">d", value)[0]
        raise IllegalDataError(f"invalid float length {vlen}")
    if vlen == 1:
        return struct.unpack(">b", value)[0]
    if vlen == 2:
        return struct.unpack(">h", value)[0]
    if vlen == 4:
        return struct.unpack(">i", value)[0]
    if vlen == 8:
        return struct.unpack(">q", value)[0]
    raise IllegalDataError(f"invalid integer length {vlen}")


# ---------------------------------------------------------------------------
# Timestamps
# ---------------------------------------------------------------------------

def is_ms_timestamp(timestamp: int) -> bool:
    """True when a unix timestamp is in milliseconds (ref: Const SECOND_MASK)."""
    return (timestamp & const.SECOND_MASK) != 0


def to_ms(timestamp: int) -> int:
    """Normalize a second-or-ms unix timestamp to milliseconds."""
    return timestamp if is_ms_timestamp(timestamp) else timestamp * 1000


def base_time(timestamp: int) -> int:
    """Row base time in *seconds*, aligned down to MAX_TIMESPAN.

    (ref: src/core/TSDB.java addPointInternal / Internal.java:850-856)
    """
    ts_sec = timestamp // 1000 if is_ms_timestamp(timestamp) else timestamp
    return ts_sec - (ts_sec % const.MAX_TIMESPAN)


# ---------------------------------------------------------------------------
# Qualifiers (ref: src/core/Internal.java:848-864)
# ---------------------------------------------------------------------------

def build_qualifier(timestamp: int, flags: int) -> bytes:
    """Build a 2-byte (seconds) or 4-byte (ms) column qualifier."""
    if is_ms_timestamp(timestamp):
        bt = base_time(timestamp)
        qual = ((int(timestamp - bt * 1000) << const.MS_FLAG_BITS) | flags
                | const.MS_FLAG) & 0xFFFFFFFF
        return struct.pack(">I", qual)
    bt = base_time(timestamp)
    qual = ((timestamp - bt) << const.FLAG_BITS) | flags
    return struct.pack(">H", qual)


def qualifier_is_ms(qualifier: bytes, offset: int = 0) -> bool:
    return (qualifier[offset] & const.MS_BYTE_FLAG) == const.MS_BYTE_FLAG


def qualifier_length(qualifier: bytes, offset: int = 0) -> int:
    return 4 if qualifier_is_ms(qualifier, offset) else 2

def parse_qualifier(qualifier: bytes, offset: int = 0) -> tuple[int, int]:
    """Parse one qualifier at ``offset``, returning ``(offset_ms, flags)``.

    ``offset_ms`` is the delta from the row base time in milliseconds
    (ref: Internal.java getOffsetFromQualifier).
    """
    if qualifier_is_ms(qualifier, offset):
        qual = struct.unpack_from(">I", qualifier, offset)[0]
        offset_ms = (qual & ~const.MS_FLAG) >> const.MS_FLAG_BITS
        flags = qual & ((1 << const.MS_FLAG_BITS) - 1) & const.FLAGS_MASK
        return offset_ms, flags
    qual = struct.unpack_from(">H", qualifier, offset)[0]
    offset_s = qual >> const.FLAG_BITS
    flags = qual & const.FLAGS_MASK
    return offset_s * 1000, flags


# ---------------------------------------------------------------------------
# Row keys (ref: src/core/RowKey.java, IncomingDataPoints.java)
# ---------------------------------------------------------------------------

class ParsedRowKey(NamedTuple):
    salt: bytes
    metric_uid: bytes
    base_time: int  # seconds
    tags: tuple[tuple[bytes, bytes], ...]  # ((tagk_uid, tagv_uid), ...) sorted


def build_row_key(metric_uid: bytes, timestamp: int,
                  tags: dict[bytes, bytes] | list[tuple[bytes, bytes]],
                  salt_width: int | None = None,
                  salt_buckets: int | None = None) -> bytes:
    """Build ``[salt][metric][base_time][tagk tagv]*`` (tags sorted by tagk).

    (ref: src/core/IncomingDataPoints.java rowKeyTemplate +
    RowKey.prefixKeyWithSalt, RowKey.java:141-165)
    """
    sw = const.salt_width() if salt_width is None else salt_width
    sb = const.salt_buckets() if salt_buckets is None else salt_buckets
    pairs = sorted(tags.items() if isinstance(tags, dict) else tags)
    body = bytearray(metric_uid)
    body += struct.pack(">I", base_time(timestamp))
    for tagk, tagv in pairs:
        body += tagk
        body += tagv
    if sw == 0:
        return bytes(body)
    bucket = salt_bucket(bytes(body), len(metric_uid), sb)
    return bucket.to_bytes(sw, "big") + bytes(body)


def salt_bucket(key_body: bytes, metric_width: int,
                buckets: int | None = None) -> int:
    """Salt bucket for an (unsalted) key: hash of metric+tags modulo buckets.

    (ref: RowKey.prefixKeyWithSalt, RowKey.java:141-165 — Java
    ``Arrays.hashCode`` over the key minus the timestamp, mod buckets.)
    The TPU build also uses this as the series→shard mapping.
    """
    sb = const.salt_buckets() if buckets is None else buckets
    # Java Arrays.hashCode over metric + tags bytes (signed bytes).
    h = 1
    for b in key_body[:metric_width]:
        sb8 = b - 256 if b > 127 else b
        h = (31 * h + sb8) & 0xFFFFFFFF
    for b in key_body[metric_width + const.TIMESTAMP_BYTES:]:
        sb8 = b - 256 if b > 127 else b
        h = (31 * h + sb8) & 0xFFFFFFFF
    if h >= 0x80000000:
        h -= 0x100000000
    return abs(h) % sb


def parse_row_key(key: bytes, metric_width: int = const.METRICS_WIDTH,
                  tagk_width: int = const.TAG_NAME_WIDTH,
                  tagv_width: int = const.TAG_VALUE_WIDTH,
                  salt_width: int | None = None) -> ParsedRowKey:
    """Split a row key into salt / metric / base_time / tag pairs."""
    sw = const.salt_width() if salt_width is None else salt_width
    salt = key[:sw]
    pos = sw
    metric = key[pos:pos + metric_width]
    pos += metric_width
    (bt,) = struct.unpack_from(">I", key, pos)
    pos += const.TIMESTAMP_BYTES
    tags = []
    pair_w = tagk_width + tagv_width
    if (len(key) - pos) % pair_w != 0:
        raise IllegalDataError(f"row key length {len(key)} is not aligned")
    while pos < len(key):
        tags.append((key[pos:pos + tagk_width],
                     key[pos + tagk_width:pos + pair_w]))
        pos += pair_w
    return ParsedRowKey(salt, metric, bt, tuple(tags))


def tsuid_from_row_key(key: bytes, salt_width: int | None = None) -> bytes:
    """TSUID = metric uid + tag uids (timestamp and salt stripped).

    (ref: src/uid/UniqueId.java getTSUIDFromKey)
    """
    parsed = parse_row_key(key, salt_width=salt_width)
    out = bytearray(parsed.metric_uid)
    for tagk, tagv in parsed.tags:
        out += tagk
        out += tagv
    return bytes(out)


# ---------------------------------------------------------------------------
# Cells and compaction (ref: src/core/CompactionQueue.java:340,
# src/core/Internal.java:216-334)
# ---------------------------------------------------------------------------

class Cell(NamedTuple):
    """One (qualifier, value) storage cell, possibly compacted."""
    qualifier: bytes
    value: bytes

    def datapoints(self, row_base_time: int) -> Iterator[tuple[int, int | float]]:
        """Yield ``(timestamp_ms, value)`` for every point in this cell."""
        for ts_ms, _flags, val in iter_cell(self.qualifier, self.value,
                                            row_base_time):
            yield ts_ms, val


def iter_cell(qualifier: bytes, value: bytes,
              row_base_time: int) -> Iterator[tuple[int, int, int | float]]:
    """Iterate ``(timestamp_ms, flags, value)`` over a single or compacted cell.

    Compacted cells concatenate qualifiers and values; when second- and
    ms-precision points are mixed, a trailing MS_MIXED_COMPACT byte is
    appended to the value (ref: CompactionQueue.java:340, Internal.java).
    """
    n_quals = 0
    qpos = 0
    vlen_total = 0
    while qpos < len(qualifier):
        _, flags = parse_qualifier(qualifier, qpos)
        vlen_total += (flags & const.LENGTH_MASK) + 1
        qpos += qualifier_length(qualifier, qpos)
        n_quals += 1
    vbytes = value
    if vlen_total == len(vbytes) - 1:
        # mixed-precision compacted cell: trailing flag byte
        if vbytes[-1] != const.MS_MIXED_COMPACT:
            raise IllegalDataError(
                f"unexpected trailing value byte {vbytes[-1]:#x}")
        vbytes = vbytes[:-1]
    elif vlen_total != len(vbytes):
        raise IllegalDataError(
            f"value length {len(vbytes)} does not match qualifiers "
            f"({vlen_total} expected)")
    qpos = 0
    vpos = 0
    while qpos < len(qualifier):
        offset_ms, flags = parse_qualifier(qualifier, qpos)
        vlen = (flags & const.LENGTH_MASK) + 1
        val = decode_value(vbytes[vpos:vpos + vlen], flags)
        yield row_base_time * 1000 + offset_ms, flags, val
        qpos += qualifier_length(qualifier, qpos)
        vpos += vlen


def compact_cells(cells: list[Cell]) -> Cell:
    """Merge N single-point cells into one compacted cell.

    Points are sorted by time offset; on duplicate timestamps the
    *last-written* cell wins (matches the reference's fix-up semantics,
    ref: CompactionQueue.java:340-500). A trailing MS_MIXED_COMPACT byte is
    appended when precisions are mixed.
    """
    points: dict[int, tuple[int, bytes, bool]] = {}
    for cell in cells:
        qpos = 0
        vpos = 0
        while qpos < len(cell.qualifier):
            offset_ms, flags = parse_qualifier(cell.qualifier, qpos)
            is_ms = qualifier_is_ms(cell.qualifier, qpos)
            vlen = (flags & const.LENGTH_MASK) + 1
            points[offset_ms] = (flags, cell.value[vpos:vpos + vlen], is_ms)
            qpos += qualifier_length(cell.qualifier, qpos)
            vpos += vlen
    quals = bytearray()
    vals = bytearray()
    any_ms = False
    any_sec = False
    for offset_ms in sorted(points):
        flags, vbytes, is_ms = points[offset_ms]
        if is_ms:
            any_ms = True
            qual = ((offset_ms << const.MS_FLAG_BITS) | flags
                    | const.MS_FLAG) & 0xFFFFFFFF
            quals += struct.pack(">I", qual)
        else:
            any_sec = True
            qual = ((offset_ms // 1000) << const.FLAG_BITS) | flags
            quals += struct.pack(">H", qual)
        vals += vbytes
    if any_ms and any_sec:
        vals.append(const.MS_MIXED_COMPACT)
    return Cell(bytes(quals), bytes(vals))
