"""Storage-format constants.

Byte-level compatibility contract with the reference storage schema
(ref: ``src/core/Const.java``). The TPU build keeps the same logical data
model — UID-encoded series, salted row keys, hourly rows, 2/4-byte
qualifiers — so that import/export, fsck and the wire formats stay
compatible, even though the in-memory column store does not need the byte
encoding on its hot path.
"""

# Number of bytes on which a timestamp is encoded in a row key
# (ref: src/core/Const.java:25).
TIMESTAMP_BYTES = 4

# Number of LSBs in time_deltas reserved for flags (seconds qualifiers)
# (ref: src/core/Const.java:62).
FLAG_BITS = 4

# Number of LSBs in time_deltas reserved for flags (ms qualifiers)
# (ref: src/core/Const.java:65).
MS_FLAG_BITS = 6

# Flag set in a qualifier when the value is a float (ref: Const.java:71).
FLAG_FLOAT = 0x8

# Mask for extracting (value_length - 1) from qualifier flags
# (ref: Const.java:74).
LENGTH_MASK = 0x7

# Mask selecting all flag bits (ref: Const.java:86).
FLAGS_MASK = FLAG_FLOAT | LENGTH_MASK

# 4-byte qualifier prefix marking a millisecond-precision cell
# (ref: Const.java:80).
MS_FLAG = 0xF0000000

# First byte of a 4-byte ms qualifier has its top nibble set
# (ref: Const.java "MS_BYTE_FLAG").
MS_BYTE_FLAG = 0xF0

# Flag appended to a compacted cell value when it mixes second and ms
# precision points (ref: Const.java:83).
MS_MIXED_COMPACT = 1

# Row width in seconds: one storage row covers one hour of one series
# (ref: Const.java:95). This is the reference's time-blocking unit; the TPU
# build reuses it as the chunk length of the host column store.
MAX_TIMESPAN = 3600

# Maximum number of tags allowed per data point (ref: Const.java:28-36).
MAX_NUM_TAGS = 8

# Any unix timestamp strictly above this is in milliseconds
# (ref: Const.java "SECOND_MASK" usage: ts & 0xFFFFFFFF00000000L != 0).
SECOND_MASK = 0xFFFFFFFF00000000

# Max unix epoch in seconds that fits the 4-byte row-key timestamp.
MAX_SECOND_TIMESTAMP = 0xFFFFFFFF

# Salting: the reference prefixes row keys with hash(series) % SALT_BUCKETS
# to spread load over HBase regions and scan 20-way in parallel
# (ref: Const.java:127-176, src/core/RowKey.java:141). In the TPU build the
# salt bucket doubles as the *shard index*: series land on mesh devices by
# the same hash, so the salt axis literally becomes the device axis.
DEFAULT_SALT_BUCKETS = 20
DEFAULT_SALT_WIDTH = 0  # 0 = salting disabled (reference default)

# Annotation cells use a 1-byte 0x01 qualifier prefix
# (ref: src/meta/Annotation.java:86).
ANNOTATION_QUAL_PREFIX = 0x01

# Append-mode cells use qualifier 0x05 0x00 0x00
# (ref: src/core/AppendDataPoints.java:45-49).
APPEND_COLUMN_PREFIX = 0x05
APPEND_COLUMN_QUALIFIER = bytes((0x05, 0x00, 0x00))

# Histogram cells use a 0x06 qualifier prefix
# (ref: src/core/HistogramDataPoint.java:30).
HISTOGRAM_PREFIX = 0x06

# Default UID widths in bytes for metric / tagk / tagv
# (ref: src/uid/UniqueId.java, src/core/TSDB.java:245-250).
METRICS_WIDTH = 3
TAG_NAME_WIDTH = 3
TAG_VALUE_WIDTH = 3


class _SaltConfig:
    """Mutable salt configuration (ref: Const.java:127-176).

    Kept as module state behind accessors like the reference so tests can
    flip salting on/off (the reference's Salted test twins do exactly this).
    """

    def __init__(self) -> None:
        self.width = DEFAULT_SALT_WIDTH
        self.buckets = DEFAULT_SALT_BUCKETS


_salt = _SaltConfig()


def salt_width() -> int:
    return _salt.width


def salt_buckets() -> int:
    return _salt.buckets


def set_salt_width(width: int) -> None:
    if width < 0 or width > 8:
        raise ValueError(f"Invalid salt width: {width}")
    _salt.width = width


def set_salt_buckets(buckets: int) -> None:
    if buckets < 1:
        raise ValueError(f"Invalid salt buckets: {buckets}")
    _salt.buckets = buckets
