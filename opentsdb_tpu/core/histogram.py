"""Histogram / sketch datapoints
(ref: ``src/core/SimpleHistogram.java``, ``HistogramCodecManager.java``).

Distribution-valued series: each datapoint is a bucketed histogram blob.
Query-time aggregation merges histograms bucket-wise (SUM — the only
aggregation the reference supports, ``HistogramAggregation.java:20``)
then extracts percentiles (``SimpleHistogram.percentile`` :133). On the
TPU path a column of histograms becomes a dense ``[series, buckets]``
matrix so merge is a segment-sum and percentile extraction a vectorized
cumsum-searchsorted — see :mod:`opentsdb_tpu.ops.percentile`.

Wire format: first byte of the stored blob is the codec id (matching the
reference's ``HistogramDataPointCodecManager`` contract); the built-in
:class:`SimpleHistogramCodec` (id 0x01) encodes bucket bounds + counts
with struct packing (the reference uses Kryo, a Java-only serde; the
framing byte and semantics are preserved, the payload encoding is not
Java-compatible by construction).
"""

from __future__ import annotations

import struct
from typing import Sequence

import numpy as np


class SimpleHistogram:
    """Explicit-bucket histogram (ref: SimpleHistogram.java:43).

    Buckets are [lo, hi) pairs with counts, plus underflow/overflow
    counters. Percentile uses linear interpolation position = rank
    weighted into the bucket, matching the reference's midpoint
    convention (SimpleHistogram.java:133-170: the bucket whose cumulative
    count crosses the rank contributes its midpoint).
    """

    def __init__(self, bounds: Sequence[float] | None = None):
        # bounds: ascending edges; bucket i = [bounds[i], bounds[i+1])
        self.bounds: list[float] = list(bounds) if bounds is not None else []
        n = max(0, len(self.bounds) - 1)
        self.counts: list[int] = [0] * n
        self.underflow = 0
        self.overflow = 0
        # query-path caches (the engine walks hundreds of thousands of
        # stored histograms per cold query; recomputing these per point
        # dominated that walk). Mutators reset them.
        self._row: np.ndarray | None = None
        self._bkey: tuple | None = None

    def add(self, value: float, count: int = 1) -> None:
        if not self.bounds:
            raise ValueError("histogram has no buckets")
        if value < self.bounds[0]:
            self.underflow += count
            return
        if value >= self.bounds[-1]:
            self.overflow += count
            return
        idx = int(np.searchsorted(self.bounds, value, side="right")) - 1
        self.counts[idx] += count
        self._invalidate()

    def set_bucket(self, lo: float, hi: float, count: int) -> None:
        """Set a bucket count by its bounds, adding the bucket if new."""
        self._invalidate()
        if not self.bounds:
            self.bounds = [lo, hi]
            self.counts = [count]
            return
        for i in range(len(self.counts)):
            if self.bounds[i] == lo and self.bounds[i + 1] == hi:
                self.counts[i] = count
                return
        if lo >= self.bounds[-1]:
            if lo != self.bounds[-1]:
                self.bounds.append(lo)
                self.counts.append(0)
            self.bounds.append(hi)
            self.counts.append(count)
        elif hi <= self.bounds[0]:
            if hi != self.bounds[0]:
                self.bounds.insert(0, hi)
                self.counts.insert(0, 0)
            self.bounds.insert(0, lo)
            self.counts.insert(0, count)
        else:
            raise ValueError(
                f"bucket [{lo},{hi}) overlaps existing bounds {self.bounds}")

    def total_count(self) -> int:
        return sum(self.counts) + self.underflow + self.overflow

    def merge(self, other: "SimpleHistogram") -> None:
        """Bucket-wise SUM (ref: HistogramAggregation SUM)."""
        if self.bounds and other.bounds and self.bounds != other.bounds:
            raise ValueError("cannot merge histograms with different buckets")
        if not self.bounds:
            self.bounds = list(other.bounds)
            self.counts = list(other.counts)
        else:
            for i, c in enumerate(other.counts):
                self.counts[i] += c
        self.underflow += other.underflow
        self.overflow += other.overflow
        self._invalidate()

    def percentile(self, perc: float) -> float:
        """(ref: SimpleHistogram.percentile :133) Returns the midpoint of
        the bucket containing the requested rank; overflow returns the
        top bound, underflow the bottom."""
        if not 0 <= perc <= 100:
            raise ValueError(f"invalid percentile {perc}")
        total = self.total_count()
        if total == 0:
            return 0.0
        target = total * perc / 100.0
        acc = self.underflow
        if acc >= target and self.underflow:
            return float(self.bounds[0]) if self.bounds else 0.0
        for i, c in enumerate(self.counts):
            acc += c
            if acc >= target:
                return (self.bounds[i] + self.bounds[i + 1]) / 2.0
        return float(self.bounds[-1]) if self.bounds else 0.0

    # -- vector form for the TPU path ----------------------------------

    def counts_array(self) -> np.ndarray:
        if self._row is None:
            self._row = np.asarray(self.counts, dtype=np.float64)
        return self._row

    def bounds_key(self) -> tuple:
        """Hashable bounds identity (cached) for uniformity checks."""
        if self._bkey is None:
            self._bkey = tuple(self.bounds)
        return self._bkey

    def _invalidate(self) -> None:
        self._row = None
        self._bkey = None

    def to_json(self) -> dict:
        return {
            "buckets": {f"{self.bounds[i]},{self.bounds[i+1]}": c
                        for i, c in enumerate(self.counts)},
            "underflow": self.underflow,
            "overflow": self.overflow,
        }


class HistogramArena:
    """Columnar store of one metric's histogram points.

    The reference keeps histogram cells beside scalar cells and walks
    them through HistogramSpan/HistogramRowSeq iterators; the first
    TPU build mirrored that with per-series Python lists of
    ``SimpleHistogram`` objects, which made a 200k-point cold query
    spend ~1.6s in a per-point host loop. Here points append into flat
    parallel arrays (ts, series id, counts row) grouped by bucket
    bounds — a query slices with vectorized masks, no per-point (or
    per-series) Python at all. One sub-arena per distinct bounds
    tuple: the uniform fast path is ``len(groups) == 1``.
    """

    class _Sub:
        __slots__ = ("bounds", "ts", "sid", "rows", "under", "over",
                     "n")

        def __init__(self, bounds: tuple, nb: int):
            self.bounds = bounds
            cap = 1024
            self.ts = np.empty(cap, dtype=np.int64)
            self.sid = np.empty(cap, dtype=np.int64)
            # float64 rows: exact for counts up to 2^53 (the codec's
            # u64 realistic range); float32 would silently round past
            # 2^24. Device kernels downcast to f32 at upload.
            self.rows = np.empty((cap, nb), dtype=np.float64)
            self.under = np.empty(cap, dtype=np.int64)
            self.over = np.empty(cap, dtype=np.int64)
            self.n = 0

        def _grow(self, need: int) -> None:
            cap = max(need, len(self.ts) * 2)
            self.ts = np.resize(self.ts, cap)
            self.sid = np.resize(self.sid, cap)
            self.rows = np.resize(self.rows, (cap, self.rows.shape[1]))
            self.under = np.resize(self.under, cap)
            self.over = np.resize(self.over, cap)

        def append(self, ts_ms: int, sid: int, row: np.ndarray,
                   under: int = 0, over: int = 0) -> None:
            if self.n == len(self.ts):
                self._grow(self.n + 1)
            self.ts[self.n] = ts_ms
            self.sid[self.n] = sid
            self.rows[self.n] = row
            self.under[self.n] = under
            self.over[self.n] = over
            self.n += 1

        def append_many(self, ts: np.ndarray, sid: np.ndarray,
                        rows: np.ndarray, under=None, over=None) -> None:
            k = len(ts)
            need = self.n + k
            if need > len(self.ts):
                self._grow(need)
            self.ts[self.n:need] = ts
            self.sid[self.n:need] = sid
            self.rows[self.n:need] = rows
            self.under[self.n:need] = 0 if under is None else under
            self.over[self.n:need] = 0 if over is None else over
            self.n = need

        def snapshot(self):
            """(ts[n], sid[n], rows[n, NB]) — stable views.

            MUST be captured under the owning TSDB's _histogram_lock
            (appends run under it): the refs + n are read atomically,
            and append-only semantics mean rows [0, n) of the captured
            arrays never mutate afterwards (np.resize on growth
            REPLACES the arrays, leaving captured ones intact)."""
            ts, sid, rows, n = self.ts, self.sid, self.rows, self.n
            return ts[:n], sid[:n], rows[:n]

        def view(self):
            """Alias of :meth:`snapshot` (same locking contract)."""
            return self.snapshot()

    def __init__(self):
        self.groups: dict[tuple, HistogramArena._Sub] = {}
        self.total_points = 0

    def append(self, ts_ms: int, sid: int,
               hist: SimpleHistogram) -> None:
        key = hist.bounds_key()
        sub = self.groups.get(key)
        if sub is None:
            sub = self.groups[key] = HistogramArena._Sub(
                key, max(1, len(key) - 1))
        sub.append(ts_ms, sid, hist.counts_array(),
                   hist.underflow, hist.overflow)
        self.total_points += 1

    def iter_points(self):
        """(ts, sid, bounds, counts_row) over every point — the slow
        generic walk, for persistence and small admin paths."""
        for sub in self.groups.values():
            ts, sid, rows = sub.view()
            for i in range(sub.n):
                yield int(ts[i]), int(sid[i]), sub.bounds, rows[i]

    def purge_before(self, cutoff_ms: int) -> int:
        """Lifecycle retention: drop every point with ts < cutoff_ms,
        shrinking the arrays to fit. Returns points removed. MUST run
        under the owning TSDB's ``_histogram_lock`` (the same contract
        as append/snapshot); the filtered arrays REPLACE the old ones,
        so previously captured snapshot views stay intact."""
        removed = 0
        for key in list(self.groups):
            sub = self.groups[key]
            keep = sub.ts[:sub.n] >= cutoff_ms
            kept = int(keep.sum())
            if kept == sub.n:
                continue
            removed += sub.n - kept
            if kept == 0:
                del self.groups[key]
                continue
            sub.ts = sub.ts[:sub.n][keep].copy()
            sub.sid = sub.sid[:sub.n][keep].copy()
            sub.rows = sub.rows[:sub.n][keep].copy()
            sub.under = sub.under[:sub.n][keep].copy()
            sub.over = sub.over[:sub.n][keep].copy()
            sub.n = kept
        self.total_points -= removed
        return removed


class HistogramCodec:
    """Codec ABI (ref: ``HistogramDataPointCodec.java``)."""

    id: int = 0

    def encode(self, hist: SimpleHistogram, include_id: bool) -> bytes:
        raise NotImplementedError

    def decode(self, data: bytes, includes_id: bool) -> SimpleHistogram:
        raise NotImplementedError


class SimpleHistogramCodec(HistogramCodec):
    """Built-in codec, id 0x01. Payload: u16 n_edges, f64*edges,
    u64*counts(n_edges-1), u64 underflow, u64 overflow."""

    id = 0x01

    def encode(self, hist: SimpleHistogram, include_id: bool = True) -> bytes:
        n = len(hist.bounds)
        out = bytearray()
        if include_id:
            out.append(self.id)
        out += struct.pack(">H", n)
        out += struct.pack(f">{n}d", *hist.bounds)
        out += struct.pack(f">{max(0, n - 1)}Q", *hist.counts)
        out += struct.pack(">QQ", hist.underflow, hist.overflow)
        return bytes(out)

    def decode(self, data: bytes, includes_id: bool = True) -> SimpleHistogram:
        pos = 1 if includes_id else 0
        (n,) = struct.unpack_from(">H", data, pos)
        pos += 2
        bounds = struct.unpack_from(f">{n}d", data, pos)
        pos += 8 * n
        counts = struct.unpack_from(f">{max(0, n - 1)}Q", data, pos)
        pos += 8 * max(0, n - 1)
        under, over = struct.unpack_from(">QQ", data, pos)
        hist = SimpleHistogram(bounds)
        hist.counts = list(counts)
        hist.underflow = under
        hist.overflow = over
        return hist


class HistogramCodecManager:
    """id -> codec registry (ref: HistogramCodecManager.java:47).

    Configured via ``tsd.core.histograms.config`` as a JSON map of
    ``{"dotted.CodecClass": id}`` like the reference; the built-in simple
    codec is always registered at id 1.
    """

    def __init__(self, config=None):
        self._by_id: dict[int, HistogramCodec] = {}
        self.register(SimpleHistogramCodec())
        if config is not None:
            spec = config.get_string("tsd.core.histograms.config", "")
            if spec:
                import json
                from opentsdb_tpu.utils.plugin import load_class
                mapping = json.loads(spec)
                for path, codec_id in mapping.items():
                    codec = load_class(path)()
                    codec.id = int(codec_id)
                    self.register(codec)

    def register(self, codec: HistogramCodec) -> None:
        self._by_id[codec.id] = codec

    def codec(self, codec_id: int) -> HistogramCodec:
        try:
            return self._by_id[codec_id]
        except KeyError:
            raise ValueError(f"no histogram codec with id {codec_id}") from None

    def decode(self, blob: bytes) -> SimpleHistogram:
        if not blob:
            raise ValueError("empty histogram blob")
        return self.codec(blob[0]).decode(blob, includes_id=True)

    def encode(self, hist: SimpleHistogram, codec_id: int = 1) -> bytes:
        return self.codec(codec_id).encode(hist, include_id=True)
