"""Storage durability: snapshot/restore of the host column store.

The reference delegates durability to HBase's WAL and keeps the TSD
stateless (SURVEY.md §5.4); this build's analogue is a persistent host
store directory (``tsd.storage.data_dir``): UID tables as JSON, series
index + point columns as ``.npy`` blobs, written atomically
(tmp + rename) on ``flush``/``shutdown`` and loaded on startup. CLI
tools (import/scan/fsck/uid) operate on the same directory the daemon
serves from — the moral equivalent of tools talking to the same HBase
tables.

Snapshots are also the checkpoint/resume story: restart rebuilds
device arrays lazily from the host store, exactly like the reference
rebuilds UID caches lazily after a restart.
"""

from __future__ import annotations

import base64
import json
import os
import tempfile

import numpy as np

_FORMAT_VERSION = 1


def save_store(tsdb, data_dir: str) -> int:
    """Write a full snapshot. Returns the WAL sequence the snapshot
    covers (captured BEFORE content capture, so a concurrent write can
    only be double-covered — replay duplicates are dedupe-tolerant —
    never lost)."""
    faults = getattr(tsdb, "faults", None)
    if faults is not None:
        # fault-injection point for the snapshot flush path
        # (tsd.faults.store.flush_*); TSDB.flush retries around this
        faults.check("store.flush")
    wal = getattr(tsdb, "wal", None)
    wal_seq = wal.last_seq() if wal is not None else 0
    os.makedirs(data_dir, exist_ok=True)
    _save_uids(tsdb.uids, data_dir)
    _save_timeseries(tsdb.store, os.path.join(data_dir, "data"))
    if tsdb.rollup_store is not None:
        for (interval, agg), store in tsdb.rollup_store._tiers.items():
            _save_timeseries(store, os.path.join(
                data_dir, f"rollup-{interval}-{agg}"))
        _save_timeseries(tsdb.rollup_store.preagg_store(),
                         os.path.join(data_dir, "rollup-preagg"))
    _save_annotations(tsdb.annotations, data_dir)
    _save_histograms(tsdb, data_dir)
    _save_meta(tsdb, data_dir)
    _save_trees(tsdb, data_dir)
    meta = {"format": _FORMAT_VERSION,
            "points_written": tsdb.store.points_written,
            "wal_applied_seq": wal_seq}
    _atomic_write(os.path.join(data_dir, "META.json"),
                  json.dumps(meta).encode())
    return wal_seq


def load_store(tsdb, data_dir: str) -> bool:
    """Load a snapshot into a fresh TSDB. Returns False when absent."""
    if not os.path.isfile(os.path.join(data_dir, "META.json")):
        return False
    with open(os.path.join(data_dir, "META.json"), encoding="utf-8") as fh:
        meta = json.load(fh)
    if meta.get("format") != _FORMAT_VERSION:
        raise ValueError(f"unsupported snapshot format {meta.get('format')}")
    tsdb._wal_applied_seq = int(meta.get("wal_applied_seq", 0))
    _load_uids(tsdb.uids, data_dir)
    _load_timeseries(tsdb.store, os.path.join(data_dir, "data"))
    if tsdb.rollup_store is not None:
        prefix = "rollup-"
        for name in os.listdir(data_dir):
            full = os.path.join(data_dir, name)
            if not (name.startswith(prefix) and os.path.isdir(full)):
                continue
            rest = name[len(prefix):]
            if rest == "preagg":
                _load_timeseries(tsdb.rollup_store.preagg_store(), full)
            else:
                interval, _, agg = rest.rpartition("-")
                try:
                    _load_timeseries(tsdb.rollup_store.tier(interval, agg),
                                     full)
                except ValueError:
                    pass  # tier no longer configured
    _load_annotations(tsdb.annotations, data_dir)
    _load_histograms(tsdb, data_dir)
    _load_meta(tsdb, data_dir)
    _load_trees(tsdb, data_dir)
    return True


def _save_trees(tsdb, data_dir: str) -> None:
    """Tree DEFINITIONS (name + rules; ref: tsdb-tree table rows).
    Branches are materialized views — rebuilt by realtime processing or
    `tsdb treesync`, like the reference's TreeSync."""
    mgr = getattr(tsdb, "_tree_manager", None)
    if mgr is None:
        return
    _atomic_write(os.path.join(data_dir, "trees.json"),
                  json.dumps([t.to_json()
                              for t in mgr.all_trees()]).encode())


def _load_trees(tsdb, data_dir: str) -> None:
    path = os.path.join(data_dir, "trees.json")
    if not os.path.isfile(path):
        return
    from opentsdb_tpu.tree.tree import tree_manager
    mgr = tree_manager(tsdb)
    from opentsdb_tpu.tree.tree import Tree, TreeRule
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    with mgr._lock:
        for obj in doc:
            tree = Tree(int(obj["treeId"]))
            tree.update(obj, overwrite=True)
            tree.created = int(obj.get("created", 0))
            for robj in obj.get("rules", []):
                tree.set_rule(TreeRule.from_json(robj))
            mgr.trees[tree.tree_id] = tree
            mgr._next_id = max(mgr._next_id, tree.tree_id)


def _save_meta(tsdb, data_dir: str) -> None:
    """TSMeta/UIDMeta documents + counters (ref: tsdb-meta/tsdb-uid
    meta rows — user edits like displayName must survive restarts)."""
    import dataclasses
    m = tsdb.meta
    if m is None:
        return
    with m._lock:
        doc = {
            "ts_counters": dict(m.ts_counters),
            "uid_meta": [dataclasses.asdict(v) | {"_key": list(k)}
                         for k, v in m.uid_meta.items()],
            "ts_meta": [dataclasses.asdict(v)
                        for v in m.ts_meta.values()],
        }
    _atomic_write(os.path.join(data_dir, "meta.json"),
                  json.dumps(doc).encode())


def _load_meta(tsdb, data_dir: str) -> None:
    path = os.path.join(data_dir, "meta.json")
    m = tsdb.meta
    if m is None or not os.path.isfile(path):
        return
    from opentsdb_tpu.meta.meta_store import TSMeta, UIDMeta
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    with m._lock:
        for e in doc.get("uid_meta", []):
            key = tuple(e.pop("_key"))
            m.uid_meta[key] = UIDMeta(**e)
        for e in doc.get("ts_meta", []):
            metric = e.pop("metric", None)
            tags = e.pop("tags", None) or []
            t = TSMeta(**e)
            t.metric = UIDMeta(**metric) if metric else None
            t.tags = [UIDMeta(**x) for x in tags]
            m.ts_meta[t.tsuid] = t
        m.ts_counters.update(doc.get("ts_counters", {}))


def _save_histograms(tsdb, data_dir: str) -> None:
    """Distribution-valued series: identity + columnar arena arrays
    (v2 format — base64 of the raw ts/sid/rows buffers; the v1 format
    re-encoded one blob per point, which walked every stored point).
    (ref: histogram cells beside scalar cells in the data table)."""
    with tsdb._histogram_lock:
        # under the lock: only capture stable snapshot views (see
        # _Sub.snapshot) — the O(total bytes) base64 work runs outside
        # so ingestion never stalls on a flush
        raw = [(mid, sub.bounds, *sub.snapshot(),
                sub.under[:sub.n], sub.over[:sub.n])
               for mid, arena in tsdb._histogram_arenas.items()
               for sub in arena.groups.values()]
    arenas = []
    seen_sids: set[int] = set()
    for mid, bounds, ts, sid, rows, under, over in raw:
        arenas.append({
            "metric": mid,
            "bounds": list(bounds),
            "n": int(len(ts)),
            "ts": base64.b64encode(
                np.ascontiguousarray(ts).tobytes()).decode(),
            "sid": base64.b64encode(
                np.ascontiguousarray(sid).tobytes()).decode(),
            "rows": base64.b64encode(
                np.ascontiguousarray(rows).tobytes()).decode(),
            "under": base64.b64encode(
                np.ascontiguousarray(under).tobytes()).decode(),
            "over": base64.b64encode(
                np.ascontiguousarray(over).tobytes()).decode(),
        })
        seen_sids.update(int(s) for s in np.unique(sid))
    series = {}
    for s in sorted(seen_sids):
        rec = tsdb.histogram_store.series(s)
        series[str(s)] = {"metric": rec.metric_id,
                          "tags": [list(p) for p in rec.tags]}
    doc = {"v": 2, "series": series, "arenas": arenas}
    _atomic_write(os.path.join(data_dir, "histograms.json"),
                  json.dumps(doc).encode())


def _load_histograms(tsdb, data_dir: str) -> None:
    from opentsdb_tpu.core.histogram import HistogramArena
    path = os.path.join(data_dir, "histograms.json")
    if not os.path.isfile(path):
        return
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if isinstance(doc, list):
        # v1 legacy: per-series blob lists
        for entry in doc:
            for ts, blob in entry["points"]:
                hist = tsdb.histogram_manager.decode(
                    base64.b64decode(blob))
                sid = tsdb.histogram_store.get_or_create_series(
                    entry["metric"], [tuple(p) for p in entry["tags"]])
                arena = tsdb._histogram_arenas.setdefault(
                    entry["metric"], HistogramArena())
                arena.append(int(ts), sid, hist)
        return
    # v2: rebuild series ids first (old sid -> new sid remap), then
    # bulk-append the columnar arrays
    sid_map: dict[int, int] = {}
    for old_sid, ident in doc.get("series", {}).items():
        sid_map[int(old_sid)] = tsdb.histogram_store \
            .get_or_create_series(ident["metric"],
                                  [tuple(p) for p in ident["tags"]])
    # dense LUT remap, built once (vectorized; a per-element dict call
    # would re-add the per-point Python walk this layout removed)
    if sid_map:
        old_ids = np.fromiter(sid_map, dtype=np.int64,
                              count=len(sid_map))
        lut = np.zeros(int(old_ids.max()) + 1, dtype=np.int64)
        lut[old_ids] = np.fromiter(sid_map.values(), dtype=np.int64,
                                   count=len(sid_map))
    for entry in doc.get("arenas", []):
        n = int(entry["n"])
        nb = max(1, len(entry["bounds"]) - 1)
        ts = np.frombuffer(base64.b64decode(entry["ts"]),
                           dtype=np.int64)[:n]
        sid = np.frombuffer(base64.b64decode(entry["sid"]),
                            dtype=np.int64)[:n]
        rows = np.frombuffer(base64.b64decode(entry["rows"]),
                             dtype=np.float64).reshape(-1, nb)[:n]
        under = np.frombuffer(base64.b64decode(entry.get("under", "")),
                              dtype=np.int64)[:n] \
            if entry.get("under") else None
        over = np.frombuffer(base64.b64decode(entry.get("over", "")),
                             dtype=np.int64)[:n] \
            if entry.get("over") else None
        arena = tsdb._histogram_arenas.setdefault(
            entry["metric"], HistogramArena())
        key = tuple(entry["bounds"])
        sub = arena.groups.get(key)
        if sub is None:
            sub = arena.groups[key] = HistogramArena._Sub(key, nb)
        remap = lut[sid] if len(sid) else sid
        sub.append_many(ts, remap, rows, under, over)
        arena.total_points += n


# ---------------------------------------------------------------------------

def _atomic_write(path: str, data: bytes) -> None:
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
    try:
        with os.fdopen(fd, "wb") as fh:
            fh.write(data)
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise


def _save_uids(uids, data_dir: str) -> None:
    doc = {}
    for kind in ("metric", "tagk", "tagv"):
        registry = uids.by_kind(kind)
        doc[kind] = {"width": registry.width,
                     "max_id": registry.max_id(),
                     "names": dict(registry.items())}
    _atomic_write(os.path.join(data_dir, "uids.json"),
                  json.dumps(doc).encode())


def _load_uids(uids, data_dir: str) -> None:
    path = os.path.join(data_dir, "uids.json")
    if not os.path.isfile(path):
        return
    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    for kind in ("metric", "tagk", "tagv"):
        registry = uids.by_kind(kind)
        entry = doc.get(kind, {})
        with registry._lock:
            registry._name_to_id = {n: int(i)
                                    for n, i in entry.get("names",
                                                          {}).items()}
            registry._id_to_name = {i: n
                                    for n, i in
                                    registry._name_to_id.items()}
            registry._max_id = int(entry.get("max_id", 0))


def _save_timeseries(store, directory: str) -> None:
    os.makedirs(directory, exist_ok=True)
    index = []
    ts_parts, val_parts, int_parts = [], [], []
    offset = 0
    for sid in range(store.num_series()):
        rec = store.series(sid)
        ts, vals, ints = rec.buffer.view_full()
        index.append({"metric": rec.metric_id,
                      "tags": [list(p) for p in rec.tags],
                      "offset": offset, "count": len(ts)})
        ts_parts.append(ts.copy())
        val_parts.append(vals.copy())
        int_parts.append(ints.copy())
        offset += len(ts)
    _atomic_write(os.path.join(directory, "series.json"),
                  json.dumps(index).encode())
    all_ts = (np.concatenate(ts_parts) if ts_parts
              else np.empty(0, np.int64))
    all_vals = (np.concatenate(val_parts) if val_parts
                else np.empty(0, np.float64))
    all_ints = (np.concatenate(int_parts) if int_parts
                else np.empty(0, bool))
    with open(os.path.join(directory, "points.npz"), "wb") as fh:
        np.savez_compressed(fh, ts=all_ts, vals=all_vals, ints=all_ints)


def _load_timeseries(store, directory: str) -> None:
    index_path = os.path.join(directory, "series.json")
    if not os.path.isfile(index_path):
        return
    with open(index_path, encoding="utf-8") as fh:
        index = json.load(fh)
    npz = np.load(os.path.join(directory, "points.npz"))
    all_ts, all_vals, all_ints = npz["ts"], npz["vals"], npz["ints"]
    for entry in index:
        sid = store.get_or_create_series(
            entry["metric"], [tuple(p) for p in entry["tags"]])
        lo, n = entry["offset"], entry["count"]
        if n:
            store.append_many(sid, all_ts[lo:lo + n],
                              all_vals[lo:lo + n],
                              is_int=all_ints[lo:lo + n])


def _save_annotations(annotations, data_dir: str) -> None:
    doc = []
    with annotations._lock:
        for tsuid, by_time in annotations._by_tsuid.items():
            for note in by_time.values():
                doc.append(note.to_json() | {"tsuid": tsuid})
    _atomic_write(os.path.join(data_dir, "annotations.json"),
                  json.dumps(doc).encode())


def _load_annotations(annotations, data_dir: str) -> None:
    path = os.path.join(data_dir, "annotations.json")
    if not os.path.isfile(path):
        return
    from opentsdb_tpu.meta.annotation import Annotation
    with open(path, encoding="utf-8") as fh:
        for obj in json.load(fh):
            annotations.store(Annotation.from_json(obj))
