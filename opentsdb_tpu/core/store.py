"""Host column store: the TPU build's storage engine.

Replaces the reference's HBase tables + asynchbase client
(ref: ``third_party/hbase``, ``src/core/SaltScanner.java``). Instead of
byte-encoded rows scanned over TCP, series live in process memory as
contiguous numpy columns — append is O(1) amortized, and query-time
"scan" is a vectorized gather that materializes a flat point batch
``(series_idx, timestamp, value)`` ready for device upload. The
reference's scan→Span→SpanGroup assembly (Span.java, SpanGroup.java,
SaltScanner.java) collapses into :meth:`TimeSeriesStore.materialize`.

Sharding: each series is assigned ``shard = salt_hash % num_shards``
exactly like the reference salts row keys (RowKey.java:141-165); the
shard index is the device-mesh axis used by :mod:`opentsdb_tpu.parallel`.

The ``StorageBackend`` protocol preserves the reference's swap point
(asynchbase -> asyncbigtable -> asynccassandra, Makefile.am:267-279):
`MemoryBackend` here, a C++ arena store in
:mod:`opentsdb_tpu.native` as the second backend.
"""

from __future__ import annotations

import threading
from typing import Iterable, NamedTuple, Protocol, Sequence

import numpy as np

from opentsdb_tpu.core import const

_INITIAL_CAPACITY = 16


class SeriesBuffer:
    """One series' points: growable parallel numpy columns.

    The reference materializes a series as compacted HBase cells parsed
    into ``RowSeq`` objects (RowSeq.java:39); here the canonical form is
    already columnar. Out-of-order and duplicate writes are accepted;
    the buffer is lazily sorted + deduped (last write wins — matching
    ``tsd.storage.fix_duplicates`` semantics, CompactionQueue.java) the
    first time it is read after a write.
    """

    __slots__ = ("ts", "vals", "is_int", "n", "_sorted", "lock",
                 "_ts_base", "_ts_scale")

    def __init__(self) -> None:
        self.ts = np.empty(_INITIAL_CAPACITY, dtype=np.int64)
        self.vals = np.empty(_INITIAL_CAPACITY, dtype=np.float64)
        self.is_int = np.empty(_INITIAL_CAPACITY, dtype=bool)
        self.n = 0
        self._sorted = True
        self.lock = threading.Lock()
        # packed-timestamp compaction state (see compact()): when
        # _ts_scale > 0, ``ts`` holds int32 offsets and the true ms
        # value is _ts_base + ts[i] * _ts_scale. Readers materialize
        # int64 through _ts64_locked(); writers unpack first.
        self._ts_base = 0
        self._ts_scale = 0

    def append(self, ts_ms: int, value: float, is_int: bool) -> None:
        with self.lock:
            self._unpack_locked()
            if self.n == len(self.ts):
                # max() guards a compacted-empty buffer (capacity 0)
                new_cap = max(self.n * 2, _INITIAL_CAPACITY)
                self.ts = np.resize(self.ts, new_cap)
                self.vals = np.resize(self.vals, new_cap)
                self.is_int = np.resize(self.is_int, new_cap)
            i = self.n
            self.ts[i] = ts_ms
            self.vals[i] = value
            self.is_int[i] = is_int
            if self._sorted and i > 0 and ts_ms <= self.ts[i - 1]:
                self._sorted = False
            self.n = i + 1

    def append_many(self, ts_ms: np.ndarray, values: np.ndarray,
                    is_int: np.ndarray | bool = False) -> None:
        """Bulk append (import path). Arrays must be 1-D, same length."""
        k = len(ts_ms)
        if k == 0:
            return
        with self.lock:
            self._unpack_locked()
            need = self.n + k
            if need > len(self.ts):
                new_cap = max(need, len(self.ts) * 2)
                self.ts = np.resize(self.ts, new_cap)
                self.vals = np.resize(self.vals, new_cap)
                self.is_int = np.resize(self.is_int, new_cap)
            self.ts[self.n:need] = ts_ms
            self.vals[self.n:need] = values
            self.is_int[self.n:need] = is_int
            if self._sorted:
                first = ts_ms[0]
                if (self.n > 0 and first <= self.ts[self.n - 1]) or \
                        k > 1 and bool(np.any(np.diff(ts_ms) <= 0)):
                    self._sorted = False
            self.n = need

    def _unpack_locked(self) -> None:
        """Restore the plain int64 timestamp column before a mutation
        (packed buffers are immutable snapshots of compacted data)."""
        if self._ts_scale:
            self.ts = (self._ts_base
                       + self.ts[:self.n].astype(np.int64)
                       * self._ts_scale)
            self._ts_base = 0
            self._ts_scale = 0

    def _ts64_locked(self) -> np.ndarray:
        """The live timestamps as int64 ms (materialized when packed;
        a view otherwise). Caller holds ``lock``."""
        if self._ts_scale:
            return (self._ts_base
                    + self.ts[:self.n].astype(np.int64)
                    * self._ts_scale)
        return self.ts[:self.n]

    def _ensure_sorted_locked(self) -> None:
        if self._sorted:
            return
        ts = self.ts[:self.n]
        order = np.argsort(ts, kind="stable")
        ts_sorted = ts[order]
        vals_sorted = self.vals[:self.n][order]
        ints_sorted = self.is_int[:self.n][order]
        # dedupe: last write wins (stable sort keeps write order per ts)
        if self.n > 1:
            keep = np.empty(self.n, dtype=bool)
            keep[:-1] = ts_sorted[1:] != ts_sorted[:-1]
            keep[-1] = True
            if not keep.all():
                ts_sorted = ts_sorted[keep]
                vals_sorted = vals_sorted[keep]
                ints_sorted = ints_sorted[keep]
        m = len(ts_sorted)
        self.ts[:m] = ts_sorted
        self.vals[:m] = vals_sorted
        self.is_int[:m] = ints_sorted
        self.n = m
        self._sorted = True

    def view(self) -> tuple[np.ndarray, np.ndarray]:
        """Sorted, deduped (ts, vals) views. Do not mutate."""
        with self.lock:
            self._ensure_sorted_locked()
            return self._ts64_locked(), self.vals[:self.n]

    def view_full(self) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
        with self.lock:
            self._ensure_sorted_locked()
            return (self._ts64_locked(), self.vals[:self.n],
                    self.is_int[:self.n])

    def slice_range(self, start_ms: int, end_ms: int) -> tuple[np.ndarray,
                                                               np.ndarray]:
        """Points with start_ms <= ts <= end_ms (inclusive ends, matching
        the reference's getScanEndTimeSeconds semantics)."""
        ts, vals = self.view()
        lo = np.searchsorted(ts, start_ms, side="left")
        hi = np.searchsorted(ts, end_ms, side="right")
        return ts[lo:hi], vals[lo:hi]

    def delete_range(self, start_ms: int, end_ms: int) -> int:
        """Remove points with start_ms <= ts <= end_ms; returns how many
        (ref: TsdbQuery delete=true issuing DeleteRequests per scanned
        row)."""
        with self.lock:
            self._ensure_sorted_locked()
            ts = self._ts64_locked()
            lo = int(np.searchsorted(ts, start_ms, side="left"))
            hi = int(np.searchsorted(ts, end_ms, side="right"))
            k = hi - lo
            if k <= 0:
                return 0
            self._unpack_locked()
            self.ts[lo:self.n - k] = self.ts[hi:self.n]
            self.vals[lo:self.n - k] = self.vals[hi:self.n]
            self.is_int[lo:self.n - k] = self.is_int[hi:self.n]
            self.n -= k
            return k

    def compact(self, pack_ts: bool = True,
                pack_before_ms: int | None = None) -> int:
        """Lifecycle compaction: sort/dedupe, shrink the columns to
        exactly ``n`` elements (growth doubling can strand ~2x dead
        capacity), and — when ``pack_ts`` and lossless — pack the
        timestamp column to int32 offsets from the first timestamp
        (scale 1000 when every ts is second-aligned, else 1), halving
        its resident bytes. Packing is transparent: readers
        materialize int64 on access, the first write unpacks.

        ``pack_before_ms`` restricts packing to COLD buffers (newest
        point older than the horizon): packing a buffer that is still
        being written just buys a full unpack copy on its next append.
        A buffer that is already exact-capacity and either packed or
        ineligible for packing returns 0 without copying anything —
        repeat sweeps over compacted data are free. Returns bytes
        reclaimed."""
        with self.lock:
            before = (self.ts.nbytes + self.vals.nbytes
                      + self.is_int.nbytes)
            self._ensure_sorted_locked()
            n = self.n
            want_pack = (pack_ts and n > 0 and self._ts_scale == 0)
            if want_pack and pack_before_ms is not None:
                # self.ts is plain int64 here (_ts_scale == 0)
                want_pack = int(self.ts[n - 1]) < pack_before_ms
            if want_pack and (int(self.ts[n - 1]) - int(self.ts[0])
                              > np.iinfo(np.int32).max * 1000):
                want_pack = False  # unpackable at any scale
            if not want_pack and len(self.vals) == n:
                return 0  # already compact: no copies
            self.vals = self.vals[:n].copy()
            self.is_int = self.is_int[:n].copy()
            ts = self._ts64_locked()
            packed = self._ts_scale > 0
            if want_pack:
                base = int(ts[0])
                scale = 1000 if (base % 1000 == 0
                                 and not (ts % 1000).any()) else 1
                span = (int(ts[-1]) - base) // scale
                if span <= np.iinfo(np.int32).max:
                    self.ts = ((ts - base) // scale).astype(np.int32)
                    self._ts_base = base
                    self._ts_scale = scale
                    packed = True
            if not packed:
                self.ts = ts[:n].copy()
                self._ts_base = 0
                self._ts_scale = 0
            after = (self.ts.nbytes + self.vals.nbytes
                     + self.is_int.nbytes)
            return max(before - after, 0)

    @property
    def resident_bytes(self) -> int:
        """Allocated column bytes (capacity-based, all three columns)."""
        return self.ts.nbytes + self.vals.nbytes + self.is_int.nbytes

    @property
    def live_bytes(self) -> int:
        """Bytes the ``n`` live points occupy in the CURRENT
        representation (packed timestamps count their packed width)."""
        return self.n * (self.ts.itemsize + self.vals.itemsize
                         + self.is_int.itemsize)

    def __len__(self) -> int:
        return self.n


class SeriesRecord(NamedTuple):
    series_id: int
    metric_id: int
    tags: tuple[tuple[int, int], ...]  # ((tagk_id, tagv_id), ...) sorted
    shard: int
    buffer: SeriesBuffer


class PointBatch(NamedTuple):
    """Flat materialized points for a set of series — the device-upload
    format consumed by :mod:`opentsdb_tpu.ops.pipeline`.

    ``series_idx[i]`` indexes into ``series_ids`` (dense 0..S-1), NOT the
    global series id — so the array program sees a compact series axis.
    """
    series_ids: np.ndarray    # int64 [S] global series ids
    series_idx: np.ndarray    # int32 [N] dense position of each point
    ts_ms: np.ndarray         # int64 [N]
    values: np.ndarray        # float64 [N]

    @property
    def num_series(self) -> int:
        return len(self.series_ids)

    @property
    def num_points(self) -> int:
        return len(self.ts_ms)


def pad_mask(counts: np.ndarray, pmax: int) -> np.ndarray:
    """Boolean [S, Pmax] mask of PAD cells (col >= row count) — the one
    place the padding convention is written down."""
    return np.arange(pmax)[None, :] >= counts[:, None]


class PaddedBatch(NamedTuple):
    """Row-padded materialized points: series i's points occupy columns
    ``0..counts[i]-1`` of row i, time-ascending; the rest is NaN padding.

    This is the TPU-preferred layout — the ragged->dense transposition
    happens during materialization (one contiguous write per series, no
    extra pass), and downstream bucketization needs no scatter at all
    (see :func:`opentsdb_tpu.ops.downsample.bucketize_padded`).
    """
    series_ids: np.ndarray    # int64 [S] global series ids
    values2d: np.ndarray      # float64 [S, Pmax], NaN-padded
    ts2d: np.ndarray          # int64 [S, Pmax], 0-padded
    counts: np.ndarray        # int64 [S] points per row

    @property
    def num_series(self) -> int:
        return len(self.series_ids)

    @property
    def num_points(self) -> int:
        return int(self.counts.sum())


def padded_from_batch(batch: PointBatch) -> PaddedBatch:
    """Row-pad a flat :class:`PointBatch` (series_idx grouped,
    per-series time-ascending — the materialize contract). Shared by
    the read views that build their padded form from a merged flat
    batch (stitched store, cold stat view)."""
    s = len(batch.series_ids)
    counts = np.bincount(batch.series_idx, minlength=s) \
        .astype(np.int64) if s else np.empty(0, dtype=np.int64)
    pmax = max(1, int(counts.max())) if s else 1
    values2d = np.full((s, pmax), np.nan)
    ts2d = np.zeros((s, pmax), dtype=np.int64)
    if batch.num_points:
        row_starts = np.zeros(s, dtype=np.int64)
        np.cumsum(counts[:-1], out=row_starts[1:])
        col = np.arange(batch.num_points, dtype=np.int64) \
            - np.repeat(row_starts, counts)
        values2d[batch.series_idx, col] = batch.values
        ts2d[batch.series_idx, col] = batch.ts_ms
    return PaddedBatch(batch.series_ids, values2d, ts2d, counts)


class StorageBackend(Protocol):
    """The storage swap point (ref: build-bigtable.sh / build-cassandra.sh)."""

    def get_or_create_series(self, metric_id: int,
                             tags: Sequence[tuple[int, int]]) -> int: ...
    def append(self, series_id: int, ts_ms: int, value: float,
               is_int: bool) -> None: ...
    def materialize(self, series_ids: Sequence[int], start_ms: int,
                    end_ms: int) -> PointBatch: ...
    def count_range(self, series_ids: Sequence[int], start_ms: int,
                    end_ms: int) -> np.ndarray: ...
    def materialize_padded(self, series_ids: Sequence[int],
                           start_ms: int, end_ms: int) -> PaddedBatch: ...


class MetricIndex:
    """Per-metric vectorized tag index.

    The reference filters series by compiling literal tag filters into
    scanner row-key regexes and running the rest post-scan
    (TsdbQuery.findSpans :804, SaltScanner:660). Here every metric keeps
    columnar arrays (series_id, tagk_id, tagv_id triples) so a filter
    evaluates as numpy set/mask operations over all series of the metric
    at once.
    """

    def __init__(self, metric_id: int):
        self.metric_id = metric_id
        # tsdlint: allow[unbounded-growth] the store's own series
        # index — bounded by live series cardinality (lifecycle
        # releases the BUFFERS; index-row reclamation rides the
        # demotion-aware UID reclamation ROADMAP item)
        self.series_ids: list[int] = []
        # tsdlint: allow[unbounded-growth] see series_ids
        self._tag_rows: list[tuple[int, int, int]] = []  # (sid, tagk, tagv)
        self._dirty = False
        self._sid_arr = np.empty(0, dtype=np.int64)
        self._tags_arr = np.empty((0, 3), dtype=np.int64)

    def add(self, series_id: int, tags: Sequence[tuple[int, int]]) -> None:
        self.series_ids.append(series_id)
        for tagk, tagv in tags:
            self._tag_rows.append((series_id, tagk, tagv))
        self._dirty = True

    def add_bulk(self, series_ids: Sequence[int],
                 tags_list: Sequence[Sequence[tuple[int, int]]]) -> None:
        """Bulk twin of :meth:`add`: one list extend instead of N calls."""
        self.series_ids.extend(series_ids)
        self._tag_rows.extend(
            (sid, tagk, tagv)
            for sid, tags in zip(series_ids, tags_list)
            for tagk, tagv in tags)
        self._dirty = True

    def arrays(self) -> tuple[np.ndarray, np.ndarray]:
        """(sids[int64 S], tag_triples[int64 T x 3]) snapshot."""
        if self._dirty:
            self._sid_arr = np.asarray(self.series_ids, dtype=np.int64)
            self._tags_arr = (np.asarray(self._tag_rows, dtype=np.int64)
                              .reshape(-1, 3))
            self._dirty = False
        return self._sid_arr, self._tags_arr


# process-wide monotonic store instance ids (shared with the native
# backend): cache keys built from them can never alias a freed store
# the way id(store) could after address reuse
import itertools as _itertools

STORE_INSTANCE_IDS = _itertools.count()


class TimeSeriesStore:
    """In-memory storage engine: all series of all metrics.

    Concurrency: a single writer lock guards series creation and index
    updates; per-series appends take only the series' own lock. Readers
    snapshot indices without blocking writes (numpy arrays are replaced,
    never mutated in place once published).
    """

    # fault-injection hook for the scan path (tsd.faults.store_*);
    # set by the owning TSDB, None everywhere else. Rollup tier /
    # preagg stores override fault_site with "rollup.store" so a
    # degraded tier is armable/observable independently of the raw
    # store (tsd.faults.rollup.store_*).
    fault_injector = None
    fault_site = "store"

    def __init__(self, num_shards: int | None = None):
        self.instance_id = next(STORE_INSTANCE_IDS)
        self.num_shards = num_shards or const.salt_buckets()
        self._lock = threading.Lock()
        # tsdlint: allow[unbounded-growth] THE in-RAM store: bounded
        # by live series cardinality; retention/demotion release and
        # shrink the buffers, full row reclamation is the ROADMAP
        # UID-reclamation item
        self._series: list[SeriesRecord] = []
        # tsdlint: allow[unbounded-growth] see _series
        self._key_to_sid: dict[tuple, int] = {}
        # tsdlint: allow[unbounded-growth] see _series
        self._metric_index: dict[int, MetricIndex] = {}
        self.points_written = 0
        # bumped on destructive ops (delete_range); together with
        # points_written it versions the store for read-side caches
        self.mutation_epoch = 0
        # bumped by compact_series (resident bytes changed without a
        # data change — versions the memory_info cache only)
        self.compactions = 0
        self._memory_info_cache: tuple | None = None

    # -- write path -------------------------------------------------------

    def get_or_create_series(self, metric_id: int,
                             tags: Sequence[tuple[int, int]]) -> int:
        key = (metric_id, tuple(sorted(tags)))
        sid = self._key_to_sid.get(key)
        if sid is not None:
            return sid
        with self._lock:
            sid = self._key_to_sid.get(key)
            if sid is not None:
                return sid
            sid = len(self._series)
            shard = self._shard_for(metric_id, key[1])
            rec = SeriesRecord(sid, metric_id, key[1], shard, SeriesBuffer())
            self._series.append(rec)
            idx = self._metric_index.get(metric_id)
            if idx is None:
                idx = self._metric_index[metric_id] = MetricIndex(metric_id)
            idx.add(sid, key[1])
            self._key_to_sid[key] = sid
            return sid

    def get_or_create_series_bulk(
            self, metric_id: int,
            tags_list: Sequence[Sequence[tuple[int, int]]]) -> np.ndarray:
        """Vectorized get_or_create_series for N series of one metric.

        One lock take and one index update for the whole batch instead
        of N — the write-path analogue of the reference's batched
        ``IncomingDataPoints`` row-template reuse
        (src/core/BatchedDataPoints.java:34). Essential on a 1-CPU host
        where 100k+ per-series Python calls dominate bulk ingest.
        """
        keys = [(metric_id, tuple(sorted(t))) for t in tags_list]
        out = np.empty(len(keys), dtype=np.int64)
        missing: list[int] = []
        get = self._key_to_sid.get
        for i, key in enumerate(keys):
            sid = get(key)
            if sid is None:
                missing.append(i)
                out[i] = -1
            else:
                out[i] = sid
        if not missing:
            return out
        with self._lock:
            new_sids: list[int] = []
            new_tags: list[tuple[tuple[int, int], ...]] = []
            idx = self._metric_index.get(metric_id)
            if idx is None:
                idx = self._metric_index[metric_id] = MetricIndex(metric_id)
            for i in missing:
                key = keys[i]
                sid = self._key_to_sid.get(key)
                if sid is None:
                    sid = len(self._series)
                    shard = self._shard_for(metric_id, key[1])
                    self._series.append(SeriesRecord(
                        sid, metric_id, key[1], shard, SeriesBuffer()))
                    self._key_to_sid[key] = sid
                    new_sids.append(sid)
                    new_tags.append(key[1])
                out[i] = sid
            if new_sids:
                idx.add_bulk(new_sids, new_tags)
        return out

    def _shard_for(self, metric_id: int,
                   tags: tuple[tuple[int, int], ...]) -> int:
        # Same hash family as the salt bucket (RowKey.java:141): series of
        # one metric+tags always land on the same shard/device.
        h = hash((metric_id, tags))
        return h % self.num_shards

    def append(self, series_id: int, ts_ms: int, value: float,
               is_int: bool = False) -> None:
        self._series[series_id].buffer.append(ts_ms, value, is_int)
        self.points_written += 1

    def append_many(self, series_id: int, ts_ms: np.ndarray,
                    values: np.ndarray,
                    is_int: np.ndarray | bool = False) -> None:
        self._series[series_id].buffer.append_many(ts_ms, values, is_int)
        self.points_written += len(ts_ms)

    def append_grid(self, series_ids, bucket_ts: np.ndarray,
                    grid: np.ndarray, mask: np.ndarray) -> int:
        """Bulk write one [S, B] grid: mask-selected cells of row i
        append onto series_ids[i] (portable twin of the native store's
        threaded ``tss_append_grid``)."""
        sids = np.asarray(series_ids, dtype=np.int64)
        if len(sids) and ((sids < 0) | (sids >= len(self._series))).any():
            raise IndexError("invalid series id in append_grid")
        written = 0
        for i, sid in enumerate(sids):
            m = mask[i]
            if not m.any():
                continue
            self._series[sid].buffer.append_many(bucket_ts[m],
                                                 grid[i][m])
            written += int(m.sum())
        self.points_written += written
        return written

    def delete_range(self, series_ids: Sequence[int], start_ms: int,
                     end_ms: int) -> int:
        """Delete all points of ``series_ids`` within the inclusive
        range; returns the number removed."""
        deleted = 0
        for sid in series_ids:
            deleted += self._series[int(sid)].buffer.delete_range(
                start_ms, end_ms)
        if deleted:
            self.mutation_epoch += 1
        return deleted

    def repair_series(self, series_id: int, min_ts: int, max_ts: int,
                      drop_nonfinite: bool = True) -> int:
        """fsck in-place repair (ref: Fsck.java:99-119): drop points
        with out-of-range timestamps and (optionally) non-finite
        values. Returns points removed."""
        buf = self._series[series_id].buffer
        with buf.lock:
            buf._ensure_sorted_locked()
            buf._unpack_locked()
            m = buf.n
            keep = (buf.ts[:m] >= min_ts) & (buf.ts[:m] <= max_ts)
            if drop_nonfinite:
                keep &= np.isfinite(buf.vals[:m])
            kept = int(keep.sum())
            if kept != m:
                buf.ts[:kept] = buf.ts[:m][keep]
                buf.vals[:kept] = buf.vals[:m][keep]
                buf.is_int[:kept] = buf.is_int[:m][keep]
                buf.n = kept
        removed = m - kept
        if removed:
            self.mutation_epoch += 1
        return removed

    def patch_value(self, series_id: int, ts_ms: int, value: float,
                    is_int: bool = False) -> None:
        """fsck in-place repair: overwrite the value at an exact
        timestamp (raises KeyError when absent)."""
        buf = self._series[series_id].buffer
        with buf.lock:
            buf._ensure_sorted_locked()
            buf._unpack_locked()
            i = int(np.searchsorted(buf.ts[:buf.n], ts_ms))
            if i >= buf.n or buf.ts[i] != ts_ms:
                raise KeyError(f"series {series_id} has no point at "
                               f"{ts_ms}")
            buf.vals[i] = value
            buf.is_int[i] = is_int
        self.mutation_epoch += 1

    # -- read path --------------------------------------------------------

    def series(self, series_id: int) -> SeriesRecord:
        return self._series[series_id]

    def num_series(self) -> int:
        return len(self._series)

    def metric_ids(self) -> list[int]:
        with self._lock:
            return list(self._metric_index)

    def metric_index(self, metric_id: int) -> MetricIndex | None:
        return self._metric_index.get(metric_id)

    def series_ids_for_metric(self, metric_id: int) -> np.ndarray:
        idx = self._metric_index.get(metric_id)
        if idx is None:
            return np.empty(0, dtype=np.int64)
        sids, _ = idx.arrays()
        return sids

    def materialize(self, series_ids: Sequence[int], start_ms: int,
                    end_ms: int) -> PointBatch:
        """Gather all points of ``series_ids`` in [start_ms, end_ms].

        This is the moral equivalent of the reference's 20-way SaltScanner
        fan-out + Span assembly (SaltScanner.java:269) — except the output
        is a flat columnar batch, not a tree of iterators.
        """
        if self.fault_injector is not None:
            self.fault_injector.check(self.fault_site)
        sids = np.asarray(series_ids, dtype=np.int64)
        ts_parts: list[np.ndarray] = []
        val_parts: list[np.ndarray] = []
        counts = np.empty(len(sids), dtype=np.int64)
        for i, sid in enumerate(sids):
            ts, vals = self._series[sid].buffer.slice_range(start_ms, end_ms)
            counts[i] = len(ts)
            if len(ts):
                ts_parts.append(ts)
                val_parts.append(vals)
        if ts_parts:
            all_ts = np.concatenate(ts_parts)
            all_vals = np.concatenate(val_parts)
        else:
            all_ts = np.empty(0, dtype=np.int64)
            all_vals = np.empty(0, dtype=np.float64)
        series_idx = np.repeat(
            np.arange(len(sids), dtype=np.int32), counts)
        return PointBatch(sids, series_idx, all_ts, all_vals)

    def count_range(self, series_ids: Sequence[int], start_ms: int,
                    end_ms: int) -> np.ndarray:
        """Points per series in [start_ms, end_ms] without copying them
        — lets the engine judge padding skew before materializing."""
        out = np.empty(len(series_ids), dtype=np.int64)
        for i, sid in enumerate(np.asarray(series_ids, dtype=np.int64)):
            ts, _ = self._series[sid].buffer.view()
            lo = np.searchsorted(ts, start_ms, side="left")
            hi = np.searchsorted(ts, end_ms, side="right")
            out[i] = hi - lo
        return out

    def materialize_padded(self, series_ids: Sequence[int],
                           start_ms: int, end_ms: int) -> PaddedBatch:
        """Row-padded variant of :meth:`materialize` — same per-series
        slice cost, but each series lands in its own row."""
        if self.fault_injector is not None:
            self.fault_injector.check(self.fault_site)
        sids = np.asarray(series_ids, dtype=np.int64)
        slices = [self._series[sid].buffer.slice_range(start_ms, end_ms)
                  for sid in sids]
        counts = np.asarray([len(ts) for ts, _ in slices],
                            dtype=np.int64)
        pmax = max(1, int(counts.max())) if len(counts) else 1
        values2d = np.full((len(sids), pmax), np.nan)
        ts2d = np.zeros((len(sids), pmax), dtype=np.int64)
        for i, (ts, vals) in enumerate(slices):
            n = len(ts)
            if n:
                ts2d[i, :n] = ts
                values2d[i, :n] = vals
        return PaddedBatch(sids, values2d, ts2d, counts)

    def append_lines(self, sids, ts_ms, values, is_int) -> int:
        """Portable twin of the native scatter-append: element i lands
        on series ``sids[i]`` (negative skips)."""
        sid_arr = np.asarray(sids, dtype=np.int64)
        ts_arr = np.asarray(ts_ms, dtype=np.int64)
        val_arr = np.asarray(values, dtype=np.float64)
        int_arr = np.asarray(is_int, dtype=bool)
        # one kept-and-sorted index, applied once per array
        kept = np.flatnonzero(sid_arr >= 0)
        idx = kept[np.argsort(sid_arr[kept], kind="stable")]
        sid_s = sid_arr[idx]
        ts_s, val_s, int_s = ts_arr[idx], val_arr[idx], int_arr[idx]
        bounds = np.nonzero(np.diff(sid_s))[0] + 1
        written = 0
        for lo, hi in zip(np.r_[0, bounds], np.r_[bounds, len(sid_s)]):
            if lo == hi:
                continue
            self.append_many(int(sid_s[lo]), ts_s[lo:hi], val_s[lo:hi],
                             int_s[lo:hi])
            written += hi - lo
        return written

    def bucket_reduce(self, series_ids, start_ms: int, end_ms: int,
                      t0: int, interval_ms: int, nbuckets: int,
                      want_minmax: bool = False):
        """Portable twin of the native store's fused range-scan +
        fixed-interval pre-reduction: [S, B] sum/count (+min/max)
        grids over [start_ms, end_ms], bucket = (ts - t0)//interval_ms.
        NaN stored values are skipped like the device bucketize."""
        batch = self.materialize(series_ids, start_ms, end_ms)
        s = len(batch.series_ids)
        b = (batch.ts_ms - t0) // interval_ms
        ok = (b >= 0) & (b < nbuckets) & ~np.isnan(batch.values)
        seg = batch.series_idx[ok].astype(np.int64) * nbuckets + b[ok]
        vals = batch.values[ok]
        n = s * nbuckets
        sums = np.bincount(seg, weights=vals, minlength=n).reshape(
            s, nbuckets)
        cnts = np.bincount(seg, minlength=n).astype(np.float64) \
            .reshape(s, nbuckets)
        mins = maxs = None
        if want_minmax:
            mins = np.full(n, np.inf)
            np.minimum.at(mins, seg, vals)
            maxs = np.full(n, -np.inf)
            np.maximum.at(maxs, seg, vals)
            mins = mins.reshape(s, nbuckets)
            maxs = maxs.reshape(s, nbuckets)
        return sums, cnts, mins, maxs

    def shards_of(self, series_ids: Iterable[int]) -> np.ndarray:
        return np.asarray([self._series[s].shard for s in series_ids],
                          dtype=np.int32)

    def total_points(self) -> int:
        return sum(len(rec.buffer) for rec in self._series)

    # -- lifecycle surface -------------------------------------------------

    def compact_series(self, series_ids: Sequence[int] | None = None,
                       pack_ts: bool = True,
                       pack_before_ms: int | None = None
                       ) -> tuple[int, int]:
        """Compact the given series' buffers (all series when None):
        sort/dedupe/shrink-to-fit + lossless timestamp packing (see
        :meth:`SeriesBuffer.compact`; ``pack_before_ms`` limits
        packing to cold buffers). Returns (bytes reclaimed, series
        released) where released = buffers that compacted down to
        zero live points (ghost series keep their sid — numbering is
        positional — but their columns are freed)."""
        if series_ids is None:
            series_ids = range(len(self._series))
        reclaimed = 0
        released = 0
        for sid in series_ids:
            buf = self._series[int(sid)].buffer
            got = buf.compact(pack_ts=pack_ts,
                              pack_before_ms=pack_before_ms)
            reclaimed += got
            if got and buf.n == 0:
                released += 1
        if reclaimed:
            self.compactions += 1
        return reclaimed, released

    def memory_info(self) -> dict:
        """Resident/live/dead column bytes + series/point counts for
        the /api/health and /api/stats memory-footprint report. Cached
        on the store's write/delete/compaction counters so health
        polls do not re-walk a million buffers."""
        key = (self.points_written, self.mutation_epoch,
               len(self._series), self.compactions)
        cached = self._memory_info_cache
        if cached is not None and cached[0] == key:
            return cached[1]
        resident = live = points = 0
        for rec in self._series:
            buf = rec.buffer
            resident += buf.resident_bytes
            live += buf.live_bytes
            points += buf.n
        info = {"series": len(self._series), "points": points,
                "resident_bytes": resident, "live_bytes": live,
                "dead_bytes": max(resident - live, 0)}
        self._memory_info_cache = (key, info)
        return info

    def collect_stats(self, collector) -> None:
        collector.record("storage.series.count", self.num_series())
        collector.record("storage.points.written", self.points_written)
        collector.record("storage.shards", self.num_shards)
        mi = self.memory_info()
        collector.record("storage.resident_bytes",
                         mi["resident_bytes"])
        collector.record("storage.live_bytes", mi["live_bytes"])
        collector.record("storage.dead_bytes", mi["dead_bytes"])
