"""Tag parsing and validation (ref: ``src/core/Tags.java``).

String rules match Tags.validateString (Tags.java:549): ASCII
alphanumerics, ``-  _  .  /``, plus any Unicode letter.
"""

from __future__ import annotations

from opentsdb_tpu.core import const

_ALLOWED_PUNCT = set("-_./")


def validate_string(what: str, s: str) -> None:
    """(ref: Tags.java:549-566)"""
    if s is None:
        raise ValueError(f"Invalid {what}: null")
    if s == "":
        raise ValueError(f"Invalid {what}: empty string")
    for c in s:
        if not (c.isalnum() and c.isascii()
                or c in _ALLOWED_PUNCT or c.isalpha()):
            raise ValueError(
                f"Invalid {what} (\"{s}\"): illegal character: {c}")


def parse_put_value(raw: str, allow_special: bool = False
                    ) -> int | float:
    """Strictly parse a put value string (ref: Tags.parseLong and the
    reference's value parse in PutDataPointRpc). Python's bare
    ``int()``/``float()`` accept underscore digit separators,
    surrounding whitespace, and non-ASCII digits (``int("1_0")`` is
    10), so a malformed value would silently WRITE the wrong number
    instead of erroring. ``allow_special`` additionally admits the
    nan/inf spellings (telnet parity)."""
    if not raw or not raw.isascii() or "_" in raw \
            or raw != raw.strip():
        raise ValueError(f"invalid value: {raw!r}")
    low = raw.lower()
    if low in ("nan", "-nan", "inf", "-inf", "infinity", "-infinity"):
        if allow_special:
            return float(raw)
        raise ValueError(f"invalid value: {raw!r}")
    try:
        if "." in raw or "e" in low:
            return float(raw)
        return int(raw)
    except ValueError:
        raise ValueError(f"invalid value: {raw!r}") from None


def parse(tag: str) -> tuple[str, str]:
    """Parse one ``name=value`` tag (ref: Tags.parse, Tags.java:60)."""
    eq = tag.find("=")
    if eq <= 0 or eq != tag.rfind("=") or eq == len(tag) - 1:
        raise ValueError(f"invalid tag: {tag}")
    return tag[:eq], tag[eq + 1:]


def parse_with_metric(arg: str) -> tuple[str, dict[str, str]]:
    """Parse ``metric{tag=value,...}`` (ref: Tags.parseWithMetric)."""
    brace = arg.find("{")
    if brace < 0:
        return arg, {}
    if not arg.endswith("}"):
        raise ValueError(f"missing '}}' in {arg!r}")
    metric = arg[:brace]
    tags: dict[str, str] = {}
    body = arg[brace + 1:-1].strip()
    if body:
        for part in body.split(","):
            k, v = parse(part.strip())
            tags[k] = v
    return metric, tags


def check_metric_and_tags(metric: str, tags: dict[str, str]) -> None:
    """Validate a write (ref: IncomingDataPoints.checkMetricAndTags)."""
    if not tags:
        raise ValueError(
            f"Need at least one tag (metric={metric}, tags={tags})")
    if len(tags) > const.MAX_NUM_TAGS:
        raise ValueError(
            f"Too many tags: {len(tags)} maximum allowed: "
            f"{const.MAX_NUM_TAGS} (metric={metric})")
    validate_string("metric name", metric)
    for k, v in tags.items():
        validate_string("tag name", k)
        validate_string("tag value", v)
