"""Tag parsing and validation (ref: ``src/core/Tags.java``).

String rules match Tags.validateString (Tags.java:549): ASCII
alphanumerics, ``-  _  .  /``, plus any Unicode letter.

The batch surface (:func:`check_metric_and_tags_batch`) screens a
whole put batch's distinct series in one columnar charset pass — one
byte-lookup over the concatenated names instead of a Python loop per
character — and falls back to the scalar validators only for series
the screen cannot prove valid (illegal bytes, non-ASCII letters,
non-string values), so error MESSAGES and the accept set stay
bit-identical to the scalar path.
"""

from __future__ import annotations

import numpy as np

from opentsdb_tpu.core import const

_ALLOWED_PUNCT = set("-_./")

# byte -> allowed, for the batched ASCII fast path (the scalar rule
# minus unicode letters, which fall back to validate_string)
_ASCII_OK = np.zeros(256, dtype=bool)
for _ch in ("0123456789abcdefghijklmnopqrstuvwxyz"
            "ABCDEFGHIJKLMNOPQRSTUVWXYZ-_./"):
    _ASCII_OK[ord(_ch)] = True
del _ch


def validate_string(what: str, s: str) -> None:
    """(ref: Tags.java:549-566)"""
    if s is None:
        raise ValueError(f"Invalid {what}: null")
    if s == "":
        raise ValueError(f"Invalid {what}: empty string")
    for c in s:
        if not (c.isalnum() and c.isascii()
                or c in _ALLOWED_PUNCT or c.isalpha()):
            raise ValueError(
                f"Invalid {what} (\"{s}\"): illegal character: {c}")


def parse_put_value(raw: str, allow_special: bool = False
                    ) -> int | float:
    """Strictly parse a put value string (ref: Tags.parseLong and the
    reference's value parse in PutDataPointRpc). Python's bare
    ``int()``/``float()`` accept underscore digit separators,
    surrounding whitespace, and non-ASCII digits (``int("1_0")`` is
    10), so a malformed value would silently WRITE the wrong number
    instead of erroring. ``allow_special`` additionally admits the
    nan/inf spellings (telnet parity)."""
    if not raw or not raw.isascii() or "_" in raw \
            or raw != raw.strip():
        raise ValueError(f"invalid value: {raw!r}")
    low = raw.lower()
    if low in ("nan", "-nan", "inf", "-inf", "infinity", "-infinity"):
        if allow_special:
            return float(raw)
        raise ValueError(f"invalid value: {raw!r}")
    try:
        if "." in raw or "e" in low:
            return float(raw)
        return int(raw)
    except ValueError:
        raise ValueError(f"invalid value: {raw!r}") from None


def parse(tag: str) -> tuple[str, str]:
    """Parse one ``name=value`` tag (ref: Tags.parse, Tags.java:60)."""
    eq = tag.find("=")
    if eq <= 0 or eq != tag.rfind("=") or eq == len(tag) - 1:
        raise ValueError(f"invalid tag: {tag}")
    return tag[:eq], tag[eq + 1:]


def parse_with_metric(arg: str) -> tuple[str, dict[str, str]]:
    """Parse ``metric{tag=value,...}`` (ref: Tags.parseWithMetric)."""
    brace = arg.find("{")
    if brace < 0:
        return arg, {}
    if not arg.endswith("}"):
        raise ValueError(f"missing '}}' in {arg!r}")
    metric = arg[:brace]
    tags: dict[str, str] = {}
    body = arg[brace + 1:-1].strip()
    if body:
        for part in body.split(","):
            k, v = parse(part.strip())
            tags[k] = v
    return metric, tags


def check_metric_and_tags(metric: str, tags: dict[str, str]) -> None:
    """Validate a write (ref: IncomingDataPoints.checkMetricAndTags)."""
    if not tags:
        raise ValueError(
            f"Need at least one tag (metric={metric}, tags={tags})")
    if len(tags) > const.MAX_NUM_TAGS:
        raise ValueError(
            f"Too many tags: {len(tags)} maximum allowed: "
            f"{const.MAX_NUM_TAGS} (metric={metric})")
    validate_string("metric name", metric)
    for k, v in tags.items():
        validate_string("tag name", k)
        validate_string("tag value", v)


def check_metric_and_tags_batch(series: list[tuple[str, dict]]
                                ) -> list[str | None]:
    """Batched :func:`check_metric_and_tags` over distinct series:
    returns one error message (or ``None``) per input, byte-for-byte
    what the scalar check raises. The common all-ASCII case is ONE
    lookup-table pass over the concatenated strings; anything the
    screen cannot prove valid re-runs the scalar validators for the
    exact message and the unicode-letter allowance."""
    n = len(series)
    out: list[str | None] = [None] * n
    strs: list[str] = []
    owner: list[int] = []    # strs index -> series index
    fallback: set[int] = set()
    for i, (metric, tags) in enumerate(series):
        if not tags or not isinstance(tags, dict) \
                or len(tags) > const.MAX_NUM_TAGS \
                or not isinstance(metric, str):
            fallback.add(i)
            continue
        row = [metric]
        ok_types = True
        for k, v in tags.items():
            if not (isinstance(k, str) and isinstance(v, str)):
                ok_types = False
                break
            row.append(k)
            row.append(v)
        if not ok_types:
            fallback.add(i)
            continue
        strs.extend(row)
        owner.extend([i] * len(row))
    if strs:
        joined = "".join(strs)
        lens = np.fromiter((len(s) for s in strs), dtype=np.int64,
                           count=len(strs))
        if joined.isascii():
            buf = np.frombuffer(joined.encode("ascii"),
                                dtype=np.uint8)
            bad = ~_ASCII_OK[buf]
            cbad = np.concatenate(
                (np.zeros(1, dtype=np.int64), np.cumsum(bad)))
            ends = np.cumsum(lens)
            str_ok = (cbad[ends] - cbad[ends - lens]) == 0
            str_ok &= lens > 0
        else:
            # mixed batch: screen each still-ASCII string, punt the
            # unicode ones (letters may be legal) to the scalar path
            str_ok = np.zeros(len(strs), dtype=bool)
            for j, s in enumerate(strs):
                if s and s.isascii():
                    b = np.frombuffer(s.encode("ascii"),
                                      dtype=np.uint8)
                    str_ok[j] = bool(_ASCII_OK[b].all())
        for j in np.nonzero(~str_ok)[0]:
            fallback.add(owner[j])
    for i in fallback:
        metric, tags = series[i]
        try:
            check_metric_and_tags(metric, tags)
        except (KeyError, TypeError, ValueError) as exc:
            out[i] = str(exc)
    return out
