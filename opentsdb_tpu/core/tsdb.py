"""The TSDB facade (ref: ``src/core/TSDB.java:87``).

Central object owning the UID registry, the storage backend, plugin
slots, and rollup configuration. Mirrors the reference surface:
``add_point`` (TSDB.java:1012-1097), ``add_aggregate_point`` (:1320),
``new_query`` (:963), ``suggest_*`` (:1762-1816), ``assign_uid``
(:1838), ``flush`` (:1603), ``shutdown`` (:1632), plus operating modes
rw/ro/wo (:103).
"""

from __future__ import annotations

import contextlib
import logging
import os
import threading
import time
from typing import Any, Callable, Sequence

import numpy as np

from opentsdb_tpu.core import codec, const, tags as tags_mod
from opentsdb_tpu.core.store import PointBatch, TimeSeriesStore
from opentsdb_tpu.core.uid import UidRegistry
from opentsdb_tpu.utils.config import Config


class PartialWriteError(Exception):
    """A bulk write landed ``written`` points before one failed.

    Raised by the per-point hook fallback in :meth:`TSDB.add_points` so
    batch callers replay only the remainder — re-running already-landed
    points would double realtime-publisher events and meta counters
    (the store itself dedupes the cells, but the hooks are not
    idempotent)."""

    def __init__(self, written: int, cause: Exception):
        super().__init__(str(cause))
        self.written = written
        self.cause = cause


class TSDB:
    """(ref: src/core/TSDB.java:87)"""

    def __init__(self, config: Config | None = None):
        self.config = config or Config()
        # startup hygiene: a typo'd tsd.* knob used to be silently
        # ignored — warn about every configured key nothing reads
        # (the declared-key registry in utils/config.py is enforced
        # by tsdlint, so "undeclared" really means "unread")
        self.config.warn_unknown_keys()
        # Force the JAX platform when configured (tsd.tpu.platform =
        # cpu|tpu|axon|""). Needed because site customizations may pin
        # JAX_PLATFORMS before our process can set env vars.
        platform = self.config.get_string("tsd.tpu.platform", "")
        if platform:
            import jax
            jax.config.update("jax_platforms", platform)
            # config.update alone is ignored once backends are
            # initialized — drop them so the override actually takes
            try:
                import jax.extend.backend
                if jax.extend.backend.backends():
                    jax.extend.backend.clear_backends()
            except Exception:  # noqa: BLE001
                logging.getLogger(__name__).warning(
                    "could not reset JAX backends; tsd.tpu.platform=%s "
                    "may not take effect", platform)
        # multi-host (DCN) rendezvous must precede any backend touch
        # (ref-analogue: multi-TSD scale-out, RpcManager.java:274-327)
        if self.config.get_string("tsd.mesh.coordinator", ""):
            from opentsdb_tpu.parallel.distributed import \
                initialize_from_config
            initialize_from_config(self.config)
        const.set_salt_width(self.config.get_int("tsd.storage.salt.width", 0))
        const.set_salt_buckets(
            self.config.get_int("tsd.storage.salt.buckets", 20))
        self.uids = UidRegistry(
            metric_width=self.config.get_int("tsd.storage.uid.width.metric", 3),
            tagk_width=self.config.get_int("tsd.storage.uid.width.tagk", 3),
            tagv_width=self.config.get_int("tsd.storage.uid.width.tagv", 3),
            random_metrics=self.config.get_bool(
                "tsd.core.uid.random_metrics"))
        # deterministic fault-injection layer (armed via tsd.faults.*
        # keys; a no-op dict miss per injection point when disarmed)
        from opentsdb_tpu.utils.faults import (CircuitBreaker,
                                               FaultInjector)
        self.faults = FaultInjector(self.config)
        from opentsdb_tpu.native.store_backend import make_store
        self.store = make_store(self.config,
                                num_shards=const.salt_buckets())
        self.store.fault_injector = self.faults
        self.mode = self.config.get_string("tsd.mode", "rw")
        self.auto_metric = self.config.get_bool("tsd.core.auto_create_metrics")
        self.auto_tagk = self.config.get_bool("tsd.core.auto_create_tagks",
                                              True)
        self.auto_tagv = self.config.get_bool("tsd.core.auto_create_tagvs",
                                              True)
        # plugin slots (ref: TSDB.java:146-167); populated by
        # initialize_plugins()
        self.rt_publisher = None
        self.search_plugin = None
        self.storage_exception_handler = None
        self.write_filters: list[Callable[..., bool]] = []
        self.uid_filter = None
        self.meta_cache = None
        self.authentication = None
        # rollups (ref: TSDB.java:170-185)
        self.rollup_config = None
        self.agg_tag_key = self.config.get_string("tsd.rollups.agg_tag_key",
                                                  "_aggregate")
        if self.config.get_bool("tsd.rollups.enable"):
            from opentsdb_tpu.rollup.config import RollupConfig
            path = self.config.get_string("tsd.rollups.config", "")
            self.rollup_config = (RollupConfig.from_file(path) if path
                                  else RollupConfig.default())
            from opentsdb_tpu.rollup.store import RollupStore
            self.rollup_store = RollupStore(
                self.rollup_config,
                store_factory=lambda: make_store(self.config),
                fault_injector=self.faults)
        else:
            self.rollup_store = None
        from opentsdb_tpu.core.histogram import HistogramCodecManager
        self.histogram_manager = HistogramCodecManager(self.config)
        self.histogram_store = TimeSeriesStore(num_shards=const.salt_buckets())
        # columnar per-metric histogram arenas (HistogramArena): flat
        # (ts, sid, counts-row) arrays grouped by bounds — queries
        # slice with vectorized masks instead of walking objects
        self._histogram_arenas: dict[int, Any] = {}
        # guards _histogram_arenas shape for snapshot-vs-write races
        self._histogram_lock = threading.Lock()
        # write version for read-side caches of histogram batches
        self._histogram_version = 0
        from opentsdb_tpu.meta.annotation import AnnotationStore
        self.annotations = AnnotationStore()
        from opentsdb_tpu.meta.meta_store import MetaStore
        self.meta = MetaStore(self)
        from opentsdb_tpu.query.limits import QueryLimitOverride
        self.query_limits = QueryLimitOverride(self.config)
        # multi-chip query execution (SURVEY §2.11: the reference's
        # 20-way salt-bucket scan fan-out, SaltScanner.java:70, mapped
        # onto a ('series','time') device mesh). Lazy: building the
        # mesh touches jax.devices().
        self._query_mesh_spec = self.config.get_string(
            "tsd.query.mesh", "")
        from opentsdb_tpu.parallel.mesh import parse_mesh_spec
        parse_mesh_spec(self._query_mesh_spec)  # fail fast on typos
        self._query_mesh = None
        # device-resident grid cache (HBM ≙ HBase block cache); lazy
        self._device_grid_cache = None
        self._device_cache_lock = threading.Lock()
        self._device_cache_mb = self.config.get_int(
            "tsd.query.device_cache_mb", 1024)
        # host-RAM twin for host-tail prepared batches: deliberately a
        # SEPARATE pool so host entries can never evict HBM-resident
        # grids (whose re-upload is the cost the device cache avoids)
        self._host_prep_cache = None
        self._host_cache_mb = self.config.get_int(
            "tsd.query.host_cache_mb", 512)
        # serve-path query RESULT cache (epoch-invalidated, single-
        # flight coalescing; opentsdb_tpu/query/result_cache.py); lazy
        self._result_cache = None
        self._result_cache_mb = self.config.get_int(
            "tsd.query.cache.mb", 256)
        # parallel sub-query fan-out pool: a DEDICATED executor, not
        # the server's _query_pool — parent queries RUN on that pool,
        # so fanning sub-queries back onto it deadlocks the moment
        # every worker holds a parent waiting on children that can
        # never be scheduled. Admission control still counts the whole
        # TSQuery once (per HTTP request, at the server); lazy.
        self._fanout_pool = None
        self._fanout_workers = self.config.get_int(
            "tsd.query.fanout.workers", 4)
        # continuous-query subsystem (opentsdb_tpu/streaming/): lazy —
        # created on first registration; the write path checks the raw
        # attribute so an idle TSD pays nothing
        self._streaming = None
        # data-lifecycle subsystem (opentsdb_tpu/lifecycle/): lazy —
        # the serve path reads the raw attribute, the `lifecycle`
        # property instantiates only when tsd.lifecycle.enable is set
        self._lifecycle = None
        # sharded cluster tier (opentsdb_tpu/cluster/): lazy — the
        # HTTP layer reads the `cluster` property per request; only a
        # tsd.cluster.role=router TSD instantiates the router
        self._cluster = None
        # self-driving control plane (opentsdb_tpu/control/): lazy —
        # the server's admission seam reads the raw attribute per
        # request; only tsd.control.enable instantiates the loop
        self._control = None
        # per-hook swallowed-error counters: post-write hooks (meta,
        # realtime publisher, external meta cache, stream tap) can
        # never fail an ACKNOWLEDGED write — see _run_hook
        # tsdlint: allow[unbounded-growth] keyed by hook name — a
        # closed, code-defined registry of ~6 hooks
        self.hook_errors: dict[str, int] = {}
        # host-side per-(store, metric) TagMatrix cache, invalidated by
        # series count (the metric index is append-only)
        self._tagmat_cache: dict = {}
        from opentsdb_tpu.stats.stats import (ServePayloadStats,
                                              StatsCollectorRegistry)
        self.stats = StatsCollectorRegistry()
        self.stats.register(self.faults)
        # serve-path payload aggregates (response bytes +
        # serialization time), fed by the /api/query handler
        self.payload_stats = ServePayloadStats()
        self.stats.register(self.payload_stats)
        # device-pipeline circuit breaker: repeated accelerator
        # failures (compile errors, OOM) trip it and queries route to
        # the host CPU fallback instead of 500ing per request;
        # tsd.query.breaker.failure_threshold = 0 disables it
        breaker_threshold = self.config.get_int(
            "tsd.query.breaker.failure_threshold")
        if breaker_threshold > 0:
            self.device_breaker = CircuitBreaker(
                "device.pipeline",
                failure_threshold=breaker_threshold,
                reset_timeout_ms=self.config.get_int(
                    "tsd.query.breaker.reset_timeout_ms"))
            self.stats.register(self.device_breaker)
        else:
            self.device_breaker = None
        self.datapoints_added = 0
        self.start_time = time.time()
        # durable snapshots (ref-analogue of HBase-backed persistence;
        # SURVEY.md §5.4): load on start, save on flush/shutdown.
        # The WAL on top makes every ACKNOWLEDGED write crash-durable,
        # like HBase's WAL does for the reference (IncomingDataPoints
        # .java:355-360); snapshot + replay-since-snapshot on startup.
        self.data_dir = self.config.get_string("tsd.storage.data_dir", "")
        # request tracing (opentsdb_tpu/obs/): ring-buffered sampled
        # span records over the ingest/query/background hot paths +
        # the query-shape log; feeds the per-stage latency histograms
        # in the stats registry. tsd.trace.enable=false makes every
        # instrumentation site a thread-local read returning None.
        from opentsdb_tpu.obs.trace import Tracer
        self.tracer = Tracer(self.config, data_dir=self.data_dir,
                             stats=self.stats)
        self.stats.register(self.tracer)
        # self-telemetry (obs/telemetry.py): the tsd.stats.self_interval
        # loop ingesting this TSD's own counters/gauges/percentiles as
        # tsd.* series through the normal write path (started by
        # TSDServer; pump() is directly callable for tests/operators)
        from opentsdb_tpu.obs.telemetry import SelfTelemetry
        self.telemetry = SelfTelemetry(self)
        self.stats.register(self.telemetry)
        # continuous sampling profiler (obs/profiler.py): a bounded
        # background thread folding sys._current_frames() into
        # per-role stack counts over the last tsd.profile.ring_s
        # seconds — GET /api/profile serves it flamegraph-ready.
        # Started by TSDServer; stopped (joined) by shutdown().
        from opentsdb_tpu.obs.profiler import SamplingProfiler
        self.profiler = SamplingProfiler(self)
        self.stats.register(self.profiler)
        # SLO burn-rate tracker (obs/slo.py): per-endpoint
        # latency/availability objectives from tsd.slo.*, fed by the
        # HTTP router per served request, exported at /metrics and
        # /api/health
        from opentsdb_tpu.obs.slo import SloTracker
        self.slo = SloTracker(self.config)
        self.stats.register(self.slo)
        # persistent XLA compilation cache: every jitted query program
        # survives restarts (before this, a restarted server re-paid
        # minutes of tunnel remote_compiles the reference's warm JVM
        # never pays — ref QueryRpc.java:128 cold path is ms)
        from opentsdb_tpu.utils.compile_cache import enable_from_config
        enable_from_config(self.config, self.data_dir)
        self.wal = None
        self._wal_applied_seq = 0
        if self.data_dir:
            from opentsdb_tpu.core import persist
            persist.load_store(self, self.data_dir)
            if self.config.get_bool("tsd.storage.wal.enable", True):
                from opentsdb_tpu.core.wal import WriteAheadLog
                from opentsdb_tpu.utils.faults import RetryPolicy
                wal = WriteAheadLog(
                    os.path.join(self.data_dir, "wal"),
                    fsync_mode=self.config.get_string(
                        "tsd.storage.wal.fsync", "always"),
                    segment_bytes=self.config.get_int(
                        "tsd.storage.wal.segment_mb", 64) << 20,
                    interval_ms=self.config.get_int(
                        "tsd.storage.wal.fsync_interval_ms", 200),
                    faults=self.faults,
                    retry=RetryPolicy.from_config(
                        self.config, "tsd.storage.wal.retry"),
                    resync_ms=self.config.get_int(
                        "tsd.storage.wal.resync_interval_ms"),
                    group_window_ms=self._wal_group_window_ms(),
                    group_max_records=self.config.get_int(
                        "tsd.storage.wal.group_max_records", 4096),
                    group_max_bytes=self.config.get_int(
                        "tsd.storage.wal.group_max_bytes", 4 << 20))
                self.stats.register(wal)
                # snapshot-covered sids keep their numbering on load
                # (histograms WAL by name, not sid — nothing to seed)
                wal.seed_known("data", self.store.num_series())
                if self.rollup_store is not None:
                    wal.seed_known(
                        "preagg",
                        self.rollup_store.preagg_store().num_series())
                    for (iv, agg), st in \
                            self.rollup_store._tiers.items():
                        wal.seed_known(f"tier:{iv}:{agg}",
                                       st.num_series())
                recovered = wal.replay(self, self._wal_applied_seq)
                if recovered:
                    logging.getLogger("tsdb").info(
                        "WAL replay recovered %d points", recovered)
                self.wal = wal
                self.annotations.wal = wal

    def _wal_group_window_ms(self) -> int:
        """``tsd.storage.wal.group_window_ms`` with the role-aware
        auto default: "" (unset) means 0 standalone but 2 ms when
        running as a cluster SHARD — behind a router every shard sees
        genuinely concurrent writers (one connection per client), so
        an opportunistic commit window amortizes fsyncs, while the
        window's quiet-log early exit (``idle_breaks``) keeps a lone
        writer's added latency at ~one poll slice. An explicit value
        (including 0) always wins."""
        raw = self.config.get_string("tsd.storage.wal.group_window_ms",
                                     "").strip()
        if raw:
            return int(raw)
        role = self.config.get_string("tsd.cluster.role", "").strip()
        return 2 if role == "shard" else 0

    # ------------------------------------------------------------------
    # plugins (ref: TSDB.java initializePlugins :390)
    # ------------------------------------------------------------------

    def initialize_plugins(self) -> None:
        from opentsdb_tpu.utils.plugin import load_plugin_instances
        cfg = self.config
        if cfg.get_bool("tsd.core.plugins.enable", False) or True:
            self.rt_publisher = load_plugin_instances(
                cfg, "tsd.rtpublisher", single=True, init_arg=self)
            self.search_plugin = load_plugin_instances(
                cfg, "tsd.search", single=True, init_arg=self)
            self.storage_exception_handler = load_plugin_instances(
                cfg, "tsd.core.storage_exception_handler", single=True,
                init_arg=self)
            raw_filters = load_plugin_instances(
                cfg, "tsd.core.write_filter", init_arg=self) or []
            # honor the filter's opt-out gate
            # (ref: WriteableDataPointFilterPlugin.filterDataPoints)
            self.write_filters = [
                f for f in raw_filters
                if not hasattr(f, "filter_data_points")
                or f.filter_data_points()]
            # UID auto-assignment gate (ref: UniqueIdFilterPlugin,
            # TSDB.java uid_filter slot)
            self.uid_filter = load_plugin_instances(
                cfg, "tsd.uid.filter", single=True, init_arg=self)
            # external TSMeta counter cache (ref: MetaDataCache,
            # TSDB.java:158)
            self.meta_cache = load_plugin_instances(
                cfg, "tsd.core.meta.cache", single=True, init_arg=self)
        if cfg.get_bool("tsd.core.authentication.enable"):
            from opentsdb_tpu.auth.simple import SimpleAuthentication
            self.authentication = SimpleAuthentication(cfg)

    # ------------------------------------------------------------------
    # write path (ref: TSDB.java:1012-1291)
    # ------------------------------------------------------------------

    def _wal_scope(self):
        """One ingest request's WAL batch scope: every record appended
        inside lands as a single framed write, and all the deferred
        ``sync()`` calls collapse into at most one group-committed
        fsync at scope exit (see :meth:`WriteAheadLog.batch`). No-op
        when the WAL is off. Callers must not acknowledge
        durability-requiring writes until the scope exits."""
        if self.wal is None:
            return contextlib.nullcontext()
        return self.wal.batch()

    def _run_hook(self, name: str, fn, *args) -> None:
        """Run one post-write hook (realtime publisher, meta tracking,
        external meta cache, streaming ingest tap) so that a
        misbehaving plugin can NEVER fail an acknowledged write: the
        point is already durable in the store (and WAL) when hooks
        run, so propagating a hook error would report a failure for a
        write that actually happened — clients would retry and
        double-write. Errors are swallowed with a per-hook counter
        (``hooks.errors`` in /api/stats) and a logring entry."""
        try:
            fn(*args)
        except Exception:  # noqa: BLE001 - deliberate firewall
            n = self.hook_errors.get(name, 0) + 1
            self.hook_errors[name] = n
            # first few at full traceback, then sampled — a hook
            # failing on every point must not flood the log ring
            if n <= 5 or n % 1000 == 0:
                logging.getLogger("tsdb").exception(
                    "%s hook failed (swallowed; %d total) — the "
                    "write itself succeeded", name, n)

    def add_point(self, metric: str, timestamp: int, value: int | float,
                  tags: dict[str, str], durable: bool = True) -> int:
        """Write one datapoint; returns the series id. ``durable=False``
        skips write-ahead logging (setDurable(false) parity).

        (ref: TSDB.addPoint :1012/:1057/:1097 -> addPointInternal :1150)
        """
        if self.mode == "ro":
            raise PermissionError("TSD is in read-only mode")
        self._check_timestamp(timestamp)
        tags_mod.check_metric_and_tags(metric, tags)
        is_int = isinstance(value, int) and not isinstance(value, bool)
        fval = float(value)
        for filt in self.write_filters:
            allow = getattr(filt, "allow_data_point", filt)
            if not allow(metric, timestamp, value, tags):
                return -1
        metric_id, tag_ids = self._resolve_write_uids(metric, tags)
        sid = self.store.get_or_create_series(metric_id, tag_ids)
        ts_ms = codec.to_ms(timestamp)
        self.store.append(sid, ts_ms, fval, is_int)
        if self.wal is not None and durable:
            self.wal.ensure_series("data", sid, metric, tags)
            self.wal.log_point("data", sid, ts_ms, fval, is_int)
            self.wal.sync()
        self.datapoints_added += 1
        if self._streaming is not None:
            # streaming v2 tap: an O(1) columnar enqueue into the
            # metric's shared partial buffers — folds run on the
            # shared worker pool, never here (a lagging plan degrades
            # to rebuild-on-serve instead of slowing this path)
            self._run_hook("stream.tap", self._streaming.offer,
                           metric_id, sid, ts_ms, fval)
        tsuid = (self.uids.tsuid(metric_id, tag_ids)
                 if self.meta_cache is not None
                 or self.rt_publisher is not None else None)
        if self.meta_cache is not None:
            # external counter service replaces built-in tracking
            # (ref: TSDB.java:1225-1245 meta_cache branch)
            self._run_hook("meta_cache",
                           self.meta_cache.increment_and_get_counter,
                           tsuid)
        elif self.meta is not None:
            self._run_hook("meta", self.meta.on_datapoint, metric_id,
                           tag_ids, sid)
        if self.rt_publisher is not None:
            self._run_hook("rt_publisher",
                           self.rt_publisher.publish_data_point,
                           metric, timestamp, value, tags, tsuid)
        return sid

    def _check_timestamp(self, timestamp: int) -> None:
        # ref: TSDB.java:1274 checkTimestampAndTags
        if timestamp <= 0:
            raise ValueError(f"invalid timestamp {timestamp}")
        if codec.is_ms_timestamp(timestamp) and timestamp > (1 << 47):
            raise ValueError(f"timestamp out of range: {timestamp}")

    def _resolve_write_uids(self, metric: str, tags: dict[str, str]
                            ) -> tuple[int, list[tuple[int, int]]]:
        from opentsdb_tpu.core.uid import (FailedToAssignUniqueIdError,
                                           NoSuchUniqueName)

        def create_allowed(kind: str, name: str) -> bool:
            # ref: UniqueIdFilterPlugin.allowUIDAssignment consulted
            # before any new UID is minted (UniqueId.getOrCreateIdAsync)
            if self.uid_filter is None:
                return True
            return self.uid_filter.allow_uid_assignment(
                kind, name, metric, tags)

        def resolve(registry, kind: str, name: str, auto: bool) -> int:
            if not auto:
                return registry.get_id(name)  # may raise
            try:
                return registry.get_id(name)
            except NoSuchUniqueName:
                if not create_allowed(kind, name):
                    raise FailedToAssignUniqueIdError(
                        f"UID filter rejected assignment of {kind} "
                        f"{name!r}") from None
                return registry.get_or_create_id(name)

        metric_id = resolve(self.uids.metrics, "metric", metric,
                            self.auto_metric)
        tag_ids = []
        for k, v in tags.items():
            kid = resolve(self.uids.tag_names, "tagk", k, self.auto_tagk)
            vid = resolve(self.uids.tag_values, "tagv", v, self.auto_tagv)
            tag_ids.append((kid, vid))
        return metric_id, tag_ids

    def add_points(self, metric: str, timestamps, values,
                   tags: dict[str, str], is_int=None) -> int:
        """Bulk write many points of ONE series; returns the series id.

        Vectorized twin of :meth:`add_point` — validation and UID
        resolution happen once, timestamps normalize in numpy, and the
        store takes one ``append_many``. The WHOLE batch is validated
        before anything is written, so a raise never leaves a partial
        batch behind. Per-point plugin hooks (write filters, realtime
        publisher, external meta counter) fall back to the per-point
        path after validation, matching the reference where those
        hooks are inherently per-datapoint (TSDB.java:1225-1253).

        ``is_int`` optionally carries per-point integer flags (bool
        [N]); by default the flag derives from the values' dtype.
        (ref: WritableDataPoints batching, IncomingDataPoints.java:36)
        """
        ts = np.asarray(timestamps, dtype=np.int64)
        vals = np.asarray(values)
        if ts.shape != vals.shape or ts.ndim != 1:
            raise ValueError("timestamps/values must be equal-length 1-D")
        if self.mode == "ro":
            raise PermissionError("TSD is in read-only mode")
        if len(ts) == 0:
            raise ValueError("empty point batch")
        if int(ts.min()) <= 0:
            raise ValueError(f"invalid timestamp {int(ts.min())}")
        # positive ts & SECOND_MASK != 0 <=> ts >= 2^32 (the mask
        # itself overflows signed int64 in numpy)
        is_ms = ts >= (1 << 32)
        if int(ts[is_ms].max(initial=0)) > (1 << 47):
            raise ValueError("timestamp out of range")
        tags_mod.check_metric_and_tags(metric, tags)
        if is_int is None:
            flags = np.full(len(ts),
                            np.issubdtype(vals.dtype, np.integer))
        else:
            flags = np.asarray(is_int, dtype=bool)
        if (self.write_filters or self.rt_publisher is not None
                or self.meta_cache is not None):
            # inherently per-point hooks; batch already validated.
            # The WAL scope commits durability ONCE at batch end
            # instead of one fsync per fallback point — and still
            # commits on a raise (PartialWriteError reports already-
            # landed points, so they must be on the durability path)
            sid = -1
            done = 0
            with self._wal_scope():
                for t, v, f in zip(ts.tolist(), vals.tolist(),
                                   flags.tolist()):
                    try:
                        sid = self.add_point(metric, t,
                                             int(v) if f else float(v),
                                             tags)
                    except Exception as e:  # noqa: BLE001
                        raise PartialWriteError(done, e) from e
                    done += 1
            return sid
        metric_id, tag_ids = self._resolve_write_uids(metric, tags)
        sid = self.store.get_or_create_series(metric_id, tag_ids)
        ts_ms = np.where(is_ms, ts, ts * 1000)
        fvals = vals.astype(np.float64)
        self.store.append_many(sid, ts_ms, fvals, flags)
        if self.wal is not None:
            # batch scope: identity + points + sync land as one framed
            # write under one lock take (joins any enclosing request
            # scope, e.g. add_point_groups')
            with self.wal.batch():
                self.wal.ensure_series("data", sid, metric, tags)
                self.wal.log_points("data", sid, ts_ms, fvals, flags)
                self.wal.sync()
        self.datapoints_added += len(ts)
        if self._streaming is not None:
            self._run_hook("stream.tap", self._streaming.offer_many,
                           metric_id, sid, ts_ms, fvals)
        if self.meta is not None:
            self._run_hook("meta", self.meta.on_datapoint, metric_id,
                           tag_ids, sid, len(ts))
        return sid

    def add_point_batch(self, points, on_error=None
                        ) -> tuple[int, list[str]]:
        """Bulk write a mixed batch of ``(metric, ts, value, tags)``
        tuples, grouping by series so UID resolution and store locking
        amortize. A group whose bulk write fails is replayed per point
        so every valid point still lands and errors stay per-point.
        Returns (points_written, error strings); ``on_error(i, exc)``
        additionally receives the input index of each failing point.
        """
        groups: dict[tuple, tuple] = {}
        for i, (metric, ts, value, tags) in enumerate(points):
            key = (metric, tuple(sorted(tags.items())))
            g = groups.get(key)
            if g is None:
                g = groups[key] = (metric, tags, [], [], [])
            g[2].append(i)
            g[3].append(ts)
            g[4].append(value)
        return self.add_point_groups(groups.values(),
                                     on_error=on_error)

    def add_point_groups(self, groups, on_error=None
                         ) -> tuple[int, list[str]]:
        """Columnar bulk write of points already grouped by series:
        ``groups`` yields ``(metric, tags, refs, timestamps, values)``
        where ``refs[i]`` is an opaque per-point handle handed back to
        ``on_error(ref, exc)`` for failing points. The whole request
        runs under ONE WAL batch scope — an N-group put body commits
        as a single framed WAL write and a single group-committed
        fsync instead of one sync per series-group. A group whose
        bulk write fails replays per point so every valid point still
        lands and errors stay per-point."""
        errors: list[str] = []
        written = 0

        def fail(ref, metric: str, ts, e: Exception) -> None:
            errors.append(f"{metric} @{ts}: {e}")
            if on_error is not None:
                on_error(ref, e)

        with self._wal_scope():
            for metric, tags, refs, ts_list, raw in groups:
                try:
                    n = len(ts_list)
                    ts_arr = np.asarray(ts_list, dtype=np.int64)
                    vals = np.asarray(raw, dtype=np.float64)
                    # type(v) is int: excludes bool, one pass
                    flags = np.fromiter((type(v) is int for v in raw),
                                        dtype=bool, count=n)
                    self.add_points(metric, ts_arr, vals, tags,
                                    is_int=flags)
                    written += n
                except PartialWriteError as pe:
                    # the hook-fallback loop landed pe.written points;
                    # the next one failed mid-hooks (don't retry it —
                    # hooks are not idempotent); the rest replay per
                    # point
                    written += pe.written
                    k = pe.written
                    fail(refs[k], metric, ts_list[k], pe.cause)
                    for j in range(k + 1, len(ts_list)):
                        try:
                            self.add_point(metric, ts_list[j], raw[j],
                                           tags)
                            written += 1
                        except Exception as e:  # noqa: BLE001
                            fail(refs[j], metric, ts_list[j], e)
                except Exception:  # noqa: BLE001
                    # bulk path failed before anything landed: per-
                    # point replay so valid points land and errors map
                    # back
                    for j in range(len(ts_list)):
                        try:
                            self.add_point(metric, ts_list[j], raw[j],
                                           tags)
                            written += 1
                        except Exception as e:  # noqa: BLE001
                            fail(refs[j], metric, ts_list[j], e)
        return written, errors

    def import_buffer(self, buf: bytes, on_error=None,
                      durable: bool = True) -> tuple[int, list[str]]:
        """Columnar bulk import of the reference's text line format
        (``metric ts value tagk=tagv ...``; ref: TextImporter.java:40).

        One native pass parses the whole buffer and labels every line
        with its distinct (metric, sorted tags) key, so UID resolution
        and series lookup run once per distinct SERIES and the points
        land via per-group ``append_many`` — the per-point Python loop
        only runs when per-point plugin hooks (write filters, realtime
        publisher, external meta counters) are active.

        Returns (points_written, error strings); ``on_error(lineno,
        exc)`` gets each failing 1-based line number.
        """
        if self.mode == "ro":
            raise PermissionError("TSD is in read-only mode")
        from opentsdb_tpu.native.store_backend import (IMPORT_ERRORS,
                                                       parse_import_buffer)
        from opentsdb_tpu.obs.trace import trace_begin, trace_end
        _h_dec = trace_begin("ingest.decode")
        parsed = parse_import_buffer(buf)
        errors: list[str] = []

        def fail(lineno: int, msg: str) -> None:
            errors.append(f"line {lineno}: {msg}")
            if on_error is not None:
                on_error(lineno, ValueError(msg))

        for i in np.nonzero(parsed.errors > 0)[0].tolist():
            fail(i + 1, IMPORT_ERRORS.get(int(parsed.errors[i]),
                                          "parse error"))
        # resolve each distinct series once. The parser already
        # enforced the reference's charset/shape rules (code 5), so no
        # per-name re-validation here.
        use_hooks = (bool(self.write_filters)
                     or self.rt_publisher is not None
                     or self.meta_cache is not None)
        gsid = np.full(parsed.num_groups, -1, dtype=np.int64)
        ginfo: list = [None] * parsed.num_groups
        for g, line in enumerate(parsed.rep_lines):
            try:
                text = line.decode("utf-8")
                words = text.split()
                metric = words[0]
                tags = {}
                for w in words[3:]:
                    k, _, v = w.partition("=")
                    tags[k] = v
                if not text.isascii():
                    # the native parser passes UTF-8 bytes through;
                    # precise unicode-letter validation happens here
                    # (rare path — once per distinct non-ASCII series)
                    tags_mod.check_metric_and_tags(metric, tags)
                if use_hooks:
                    ginfo[g] = (metric, tags, None, None)
                else:
                    metric_id, tag_ids = self._resolve_write_uids(
                        metric, tags)
                    gsid[g] = self.store.get_or_create_series(
                        metric_id, tag_ids)
                    ginfo[g] = (metric, tags, metric_id, tag_ids)
            except Exception as e:  # noqa: BLE001
                ginfo[g] = e

        failed = [g for g in range(parsed.num_groups)
                  if isinstance(ginfo[g], Exception)]
        for g in failed:
            for i in np.nonzero(parsed.group_ids == g)[0].tolist():
                fail(i + 1, str(ginfo[g]))
        if _h_dec is not None:
            _h_dec.tag(lines=int(parsed.num_lines)
                       if hasattr(parsed, "num_lines")
                       else len(parsed.ts),
                       groups=int(parsed.num_groups))
        trace_end(_h_dec)
        written = 0
        if use_hooks:
            # per-point hooks are inherently per-datapoint: group runs
            # still amortize the metric/tag resolution, and the WAL
            # scope commits ONE fsync for the whole buffer instead of
            # one per point
            with self._wal_scope():
                for g in range(parsed.num_groups):
                    if isinstance(ginfo[g], Exception):
                        continue
                    metric, tags, _, _ = ginfo[g]
                    members = np.nonzero(parsed.group_ids == g)[0]
                    for i, t, v, f in zip(
                            members.tolist(),
                            parsed.ts[members].tolist(),
                            parsed.values[members].tolist(),
                            parsed.is_int[members].tolist()):
                        try:
                            self.add_point(metric, t,
                                           int(v) if f else v, tags,
                                           durable=durable)
                            written += 1
                        except Exception as e:  # noqa: BLE001
                            fail(i + 1, str(e))
            return written, errors
        if parsed.num_groups == 0:
            return 0, errors
        # one scatter-append call lands every line on its series
        gids = parsed.group_ids
        line_sids = np.where(gids >= 0,
                             gsid[np.maximum(gids, 0)], -1)
        ts_ms = np.where(parsed.ts >= (1 << 32), parsed.ts,
                         parsed.ts * 1000)
        _h_sc = trace_begin("store.scatter")
        written = self.store.append_lines(line_sids, ts_ms,
                                          parsed.values, parsed.is_int)
        trace_end(_h_sc)
        if self.wal is not None and durable:
            # durable=False ≙ the reference's batch-import WAL opt-out
            # (PutRequest.setDurable(false), IncomingDataPoints:355-360)
            # batch scope: N ensure_series + the lines record land as
            # one framed write under one lock take, one fsync
            with self.wal.batch():
                for g in range(parsed.num_groups):
                    info = ginfo[g]
                    if isinstance(info, Exception):
                        continue
                    self.wal.ensure_series("data", int(gsid[g]),
                                           info[0], info[1])
                self.wal.log_lines("data", line_sids, ts_ms,
                                   parsed.values, parsed.is_int)
                self.wal.sync()
        self.datapoints_added += written
        if self._streaming is not None and written:
            _h_tap = trace_begin("stream.tap")
            for g in range(parsed.num_groups):
                info = ginfo[g]
                if isinstance(info, Exception):
                    continue
                m = parsed.group_ids == g
                if m.any():
                    self._run_hook("stream.tap",
                                   self._streaming.offer_many,
                                   info[2], int(gsid[g]), ts_ms[m],
                                   parsed.values[m])
            trace_end(_h_tap)
        if self.meta is not None and written:
            counts = np.bincount(gids[gids >= 0],
                                 minlength=parsed.num_groups)
            for g in range(parsed.num_groups):
                info = ginfo[g]
                if isinstance(info, Exception) or not counts[g]:
                    continue
                self._run_hook("meta", self.meta.on_datapoint,
                               info[2], info[3], int(gsid[g]),
                               int(counts[g]))
        return written, errors

    def add_aggregate_point(self, metric: str, timestamp: int,
                            value: int | float, tags: dict[str, str],
                            is_groupby: bool, interval: str | None,
                            rollup_agg: str | None,
                            groupby_agg: str | None = None) -> None:
        """Write a rollup / pre-aggregated point (ref: TSDB.java:1320-1418).

        Pre-aggregates (``is_groupby``) are tagged with the agg-tag
        (``tsd.rollups.agg_tag_key``) exactly like the reference.
        """
        if self.rollup_store is None:
            raise RuntimeError("rollups are not enabled "
                               "(tsd.rollups.enable=false)")
        tags = dict(tags)
        if is_groupby:
            agg = (groupby_agg or rollup_agg or "").upper()
            if not agg:
                raise ValueError("missing group-by aggregator")
            tags[self.agg_tag_key] = agg
        tags_mod.check_metric_and_tags(metric, tags)
        metric_id, tag_ids = self._resolve_write_uids(metric, tags)
        ts_ms = codec.to_ms(timestamp)
        if interval is None:
            # pure pre-agg point: store in the pre-agg ("groupby") table
            kind = "preagg"
            store_obj = self.rollup_store.preagg_store()
        else:
            if rollup_agg is None:
                raise ValueError("missing rollup aggregator")
            kind = f"tier:{interval}:{rollup_agg.lower()}"
            store_obj = self.rollup_store.tier(interval,
                                               rollup_agg.lower())
        sid = store_obj.get_or_create_series(metric_id, tag_ids)
        store_obj.append(sid, ts_ms, float(value))
        if self.wal is not None:
            self.wal.ensure_series(kind, sid, metric, tags)
            self.wal.log_point(kind, sid, ts_ms, float(value), False)
            self.wal.sync()
        self.datapoints_added += 1

    def add_histogram_batch(self, points, on_error=None
                            ) -> tuple[int, list[str]]:
        """Bulk write ``(metric, timestamp, raw_blob, tags)`` histogram
        tuples, grouping by series so validation + UID resolution run
        once per series instead of once per point (the histogram twin
        of :meth:`add_point_batch`; per-point work that remains —
        codec decode + arena append — is inherent). WAL-synced once
        per batch. Returns (written, error strings)."""
        from opentsdb_tpu.core.histogram import HistogramArena
        groups: dict[tuple, list] = {}
        errors: list[str] = []
        written = 0

        def fail(idx: int, metric: str, ts, e: Exception) -> None:
            errors.append(f"{metric} @{ts}: {e}")
            if on_error is not None:
                on_error(idx, e)

        for i, (metric, ts, blob, tags) in enumerate(points):
            key = (metric, tuple(sorted(tags.items())))
            groups.setdefault(key, []).append((i, ts, blob, tags))
        with self._wal_scope():
            for (metric, _), items in groups.items():
                tags = items[0][3]
                try:
                    tags_mod.check_metric_and_tags(metric, tags)
                except Exception as e:  # noqa: BLE001
                    for idx, ts, _b, _t in items:
                        fail(idx, metric, ts, e)
                    continue
                # validate + decode every point BEFORE touching the
                # UID tables: a fully-invalid group must not pollute
                # UID space or create an empty series (matches
                # add_histogram_point, which validates first and
                # creates nothing on failure)
                valid: list[tuple] = []
                for idx, ts, blob, _t in items:
                    try:
                        self._check_timestamp(ts)
                        hist = self.histogram_manager.decode(blob)
                        valid.append((idx, ts, blob,
                                      codec.to_ms(ts), hist))
                    except Exception as e:  # noqa: BLE001
                        fail(idx, metric, ts, e)
                if not valid:
                    continue
                try:
                    metric_id, tag_ids = self._resolve_write_uids(
                        metric, tags)
                    sid = self.histogram_store.get_or_create_series(
                        metric_id, tag_ids)
                except Exception as e:  # noqa: BLE001
                    for idx, ts, _b, _tm, _h in valid:
                        fail(idx, metric, ts, e)
                    continue
                # one lock take for the whole group's appends
                with self._histogram_lock:
                    arena = self._histogram_arenas.get(metric_id)
                    if arena is None:
                        arena = self._histogram_arenas[metric_id] = \
                            HistogramArena()
                    for _idx, _ts, _b, ts_ms, hist in valid:
                        arena.append(ts_ms, sid, hist)
                    self._histogram_version += 1
                if self.wal is not None:
                    for _idx, ts, blob, _tm, _h in valid:
                        self.wal.log_histogram(metric, tags, ts, blob)
                self.datapoints_added += len(valid)
                written += len(valid)
            if written and self.wal is not None:
                self.wal.sync()
        return written, errors

    def add_histogram_point(self, metric: str, timestamp: int,
                            raw_blob: bytes, tags: dict[str, str],
                            _wal: bool = True) -> int:
        """Write an encoded histogram datapoint (ref: TSDB.java:1132)."""
        tags_mod.check_metric_and_tags(metric, tags)
        self._check_timestamp(timestamp)
        hist = self.histogram_manager.decode(raw_blob)
        metric_id, tag_ids = self._resolve_write_uids(metric, tags)
        sid = self.histogram_store.get_or_create_series(metric_id, tag_ids)
        ts_ms = codec.to_ms(timestamp)
        with self._histogram_lock:
            from opentsdb_tpu.core.histogram import HistogramArena
            arena = self._histogram_arenas.get(metric_id)
            if arena is None:
                arena = self._histogram_arenas[metric_id] = \
                    HistogramArena()
            arena.append(ts_ms, sid, hist)
            self._histogram_version += 1
        if _wal and self.wal is not None:
            self.wal.log_histogram(metric, tags, timestamp, raw_blob)
            self.wal.sync()
        self.datapoints_added += 1
        return sid

    def purge_histograms_before(self, metric_id: int,
                                cutoff_ms: int) -> int:
        """Lifecycle retention for histogram arenas: drop one metric's
        histogram points older than the cutoff and bump the histogram
        version + store epoch so every read-side cache (result cache,
        streaming plans) invalidates. Returns points removed."""
        with self._histogram_lock:
            arena = self._histogram_arenas.get(metric_id)
            if arena is None:
                return 0
            removed = arena.purge_before(cutoff_ms)
            if removed:
                if not arena.groups:
                    del self._histogram_arenas[metric_id]
                self._histogram_version += 1
                self.histogram_store.mutation_epoch += 1
        return removed

    # ------------------------------------------------------------------
    # read path entry (ref: TSDB.java newQuery :963)
    # ------------------------------------------------------------------

    @property
    def query_mesh(self):
        """The ('series','time') device mesh ``/api/query`` executes
        over, or None for single-device execution. Configured with
        ``tsd.query.mesh`` (ref: SaltScanner.java:70 — the fixed 20-way
        scan fan-out this replaces with a device-mesh shard_map)."""
        if self._query_mesh is None and self._query_mesh_spec:
            from opentsdb_tpu.parallel.mesh import mesh_from_spec
            try:
                self._query_mesh = mesh_from_spec(self._query_mesh_spec)
            except ValueError:
                # e.g. spec wants more devices than exist: degrade to
                # single-device once, loudly — NOT a 500 on every query
                import logging
                logging.getLogger("tsdb").exception(
                    "tsd.query.mesh=%r unusable; queries run "
                    "single-device", self._query_mesh_spec)
            if self._query_mesh is None:  # single device: stop retrying
                self._query_mesh_spec = ""
        return self._query_mesh

    @property
    def device_grid_cache(self):
        """Device-resident [S, B] grid cache (see
        :mod:`opentsdb_tpu.query.device_cache`), or None when disabled
        (``tsd.query.device_cache_mb = 0``)."""
        if self._device_grid_cache is None and self._device_cache_mb:
            with self._device_cache_lock:
                if self._device_grid_cache is None:
                    from opentsdb_tpu.query.device_cache import \
                        DeviceGridCache
                    cache = DeviceGridCache(
                        self._device_cache_mb * (1 << 20))
                    self.stats.register(cache)
                    self._device_grid_cache = cache
        return self._device_grid_cache

    @property
    def host_prep_cache(self):
        """Host-RAM prepared-batch cache for host-tail queries (warm
        repeats skip materialize + union-grid construction), or None
        when disabled (``tsd.query.host_cache_mb = 0``)."""
        if self._host_prep_cache is None and self._host_cache_mb:
            with self._device_cache_lock:
                if self._host_prep_cache is None:
                    from opentsdb_tpu.query.device_cache import \
                        DeviceGridCache
                    cache = DeviceGridCache(
                        self._host_cache_mb * (1 << 20),
                        stat_prefix="query.hostcache")
                    self.stats.register(cache)
                    self._host_prep_cache = cache
        return self._host_prep_cache

    @property
    def result_cache(self):
        """Serve-path query result cache
        (:mod:`opentsdb_tpu.query.result_cache`), or None when
        disabled. ``tsd.query.cache.enable`` is consulted per call so
        operators (and the bench) can toggle it at runtime without
        losing the populated cache."""
        if self._result_cache_mb <= 0 or not self.config.get_bool(
                "tsd.query.cache.enable", True):
            return None
        if self._result_cache is None:
            with self._device_cache_lock:
                if self._result_cache is None:
                    from opentsdb_tpu.query.result_cache import \
                        QueryResultCache
                    cache = QueryResultCache(
                        self._result_cache_mb * (1 << 20),
                        shards=self.config.get_int(
                            "tsd.query.cache.shards", 8))
                    self.stats.register(cache)
                    self._result_cache = cache
        return self._result_cache

    @property
    def streaming(self):
        """Continuous-query registry
        (:mod:`opentsdb_tpu.streaming.registry`), or None when
        disabled (``tsd.streaming.enable = false``). Lazy: the write
        path's tap check reads the raw ``_streaming`` attribute, so a
        TSD with no registered continuous queries pays one attribute
        read per write."""
        if not self.config.get_bool("tsd.streaming.enable", True):
            return None
        if self._streaming is None:
            with self._device_cache_lock:
                if self._streaming is None:
                    from opentsdb_tpu.streaming.registry import \
                        ContinuousQueryRegistry
                    reg = ContinuousQueryRegistry(self)
                    self.stats.register(reg)
                    self._streaming = reg
        return self._streaming

    @property
    def lifecycle(self):
        """Data-lifecycle manager
        (:mod:`opentsdb_tpu.lifecycle.manager`), or None when disabled
        (``tsd.lifecycle.enable = false``, the default). The query
        engine consults it per sub-query for demotion-boundary
        stitching; the server starts its sweeper thread."""
        if not self.config.get_bool("tsd.lifecycle.enable", False):
            return None
        if self._lifecycle is None:
            with self._device_cache_lock:
                if self._lifecycle is None:
                    from opentsdb_tpu.lifecycle.manager import \
                        LifecycleManager
                    lc = LifecycleManager(self)
                    self.stats.register(lc)
                    self._lifecycle = lc
        return self._lifecycle

    @property
    def cluster(self):
        """Cluster router (:mod:`opentsdb_tpu.cluster.router`), or
        None unless this TSD runs as ``tsd.cluster.role = router``.
        The HTTP layer branches ``/api/put`` and ``/api/query``
        through it; shards and standalone TSDs serve locally."""
        if self.config.get_string("tsd.cluster.role", "") != "router":
            return None
        if self._cluster is None:
            with self._device_cache_lock:
                if self._cluster is None:
                    from opentsdb_tpu.cluster.router import \
                        ClusterRouter
                    router = ClusterRouter(self)
                    self.stats.register(router)
                    self._cluster = router
        return self._cluster

    @property
    def control(self):
        """Self-driving control plane
        (:mod:`opentsdb_tpu.control.plane`), or None when disabled
        (``tsd.control.enable = false``, the default). The server's
        admission seam reads the raw ``_control`` attribute so an
        uncontrolled TSD pays one attribute read per request."""
        if not self.config.get_bool("tsd.control.enable", False):
            return None
        if self._control is None:
            with self._device_cache_lock:
                if self._control is None:
                    from opentsdb_tpu.control.plane import \
                        ControlPlane
                    ctl = ControlPlane(self)
                    self.stats.register(ctl)
                    self._control = ctl
        # outside the lock: wire() builds the lazy result_cache, which
        # takes the same lock
        self._control.wire()
        return self._control

    @property
    def query_fanout_pool(self):
        """Executor independent sub-queries of one TSQuery fan out
        onto (None = serial; ``tsd.query.fanout.workers``). See the
        constructor comment for why this is NOT the server's
        _query_pool."""
        if self._fanout_pool is None and self._fanout_workers > 0:
            with self._device_cache_lock:
                if self._fanout_pool is None:
                    import concurrent.futures
                    self._fanout_pool = \
                        concurrent.futures.ThreadPoolExecutor(
                            max_workers=self._fanout_workers,
                            thread_name_prefix="tsd-subq")
        return self._fanout_pool

    def storage_memory_info(self) -> dict:
        """Per-store memory footprint (resident/live/dead bytes,
        series and point counts) for /api/health and /api/stats —
        makes lifecycle reclamation observable before/after sweeps.
        Per-store entries are cached inside each store; totals sum
        whatever stores exist."""
        out: dict = {}
        if hasattr(self.store, "memory_info"):
            out["raw"] = self.store.memory_info()
        if hasattr(self.histogram_store, "memory_info"):
            out["histogram"] = self.histogram_store.memory_info()
        if self.rollup_store is not None:
            rs = self.rollup_store
            preagg = rs.preagg_store()
            if hasattr(preagg, "memory_info"):
                out["rollup:preagg"] = preagg.memory_info()
            with rs._tiers_lock:
                tiers = list(rs._tiers.items())
            for (interval, agg), store in sorted(tiers):
                if hasattr(store, "memory_info"):
                    out[f"rollup:{interval}:{agg}"] = \
                        store.memory_info()
        # cold tier: disk-resident mmap segments, reported separately
        # from RAM (the whole point is that they are NOT resident)
        lc = self._lifecycle
        cold = getattr(lc, "coldstore", None) if lc is not None \
            else None
        if cold is not None:
            out["cold"] = cold.memory_info()
        totals = {"resident_bytes": 0, "live_bytes": 0,
                  "dead_bytes": 0, "series": 0, "points": 0}
        for info in out.values():
            for k in totals:
                totals[k] += info.get(k, 0)
        totals["cold_bytes"] = (out["cold"]["disk_bytes"]
                                if cold is not None else 0)
        out["total"] = totals
        return out

    def serve_version(self) -> tuple:
        """Version tuple over every store the query surface can read
        (raw + rollup tiers + preagg + histograms + annotations):
        cheap counter reads, bumped by every write and every
        destructive op. Read-side caches key their entries on it, so
        a version mismatch <=> the data MAY have changed — no cached
        result can ever outlive a write it should reflect."""
        s = self.store
        parts: list = [
            s.points_written, getattr(s, "mutation_epoch", 0),
            self._histogram_version,
            self.histogram_store.points_written,
            self.histogram_store.mutation_epoch,
            getattr(self.annotations, "version", 0),
        ]
        if self.rollup_store is not None:
            parts.append(self.rollup_store.version())
        return tuple(parts)

    def new_query(self):
        from opentsdb_tpu.query.engine import QueryEngine
        return QueryEngine(self)

    def execute_query(self, ts_query) -> list:
        """Run a validated TSQuery end-to-end, returning result groups."""
        return self.new_query().run(ts_query)

    # ------------------------------------------------------------------
    # suggest / uid surface (ref: TSDB.java:1762-1846)
    # ------------------------------------------------------------------

    def suggest_metrics(self, search: str = "", max_results: int = 25):
        return self.uids.metrics.suggest(search, max_results)

    def suggest_tag_names(self, search: str = "", max_results: int = 25):
        return self.uids.tag_names.suggest(search, max_results)

    def suggest_tag_values(self, search: str = "", max_results: int = 25):
        return self.uids.tag_values.suggest(search, max_results)

    def assign_uid(self, kind: str, name: str) -> int:
        tags_mod.validate_string(f"{kind} name", name)
        uid = self.uids.by_kind(kind).assign_id(name)
        if self.wal is not None:
            self.wal.log_uid(kind, name)
            self.wal.sync()
        return uid

    # ------------------------------------------------------------------
    # lifecycle (ref: TSDB.java flush :1603, shutdown :1632)
    # ------------------------------------------------------------------

    def flush(self) -> None:
        if self.data_dir:
            from opentsdb_tpu.core import persist
            from opentsdb_tpu.utils.faults import (RetryPolicy,
                                                   call_with_retries)
            # a slow/flaky disk under the snapshot directory gets the
            # same retry-with-backoff discipline as the WAL fsync path
            wal_seq = call_with_retries(
                lambda: persist.save_store(self, self.data_dir),
                RetryPolicy.from_config(self.config,
                                        "tsd.storage.flush.retry"),
                retryable=(OSError,),
                on_retry=lambda attempt, exc: logging.getLogger(
                    "tsdb").warning(
                        "snapshot flush failed (attempt %d: %s); "
                        "retrying", attempt, exc))
            if self.wal is not None:
                # snapshot covers seq <= wal_seq: those segments are done
                self.wal.truncate(wal_seq)

    def shutdown(self) -> None:
        # the control plane steers every other subsystem, so it stops
        # FIRST — a tick must not race a registry/router teardown
        if self._control is not None:
            self._control.stop()
        self.telemetry.stop()
        self.profiler.stop()
        if self._cluster is not None:
            self._cluster.stop()
        if self._lifecycle is not None:
            self._lifecycle.stop()
        self.tracer.close()
        self.flush()
        if self._streaming is not None:
            self._streaming.shutdown()
        if self._fanout_pool is not None:
            self._fanout_pool.shutdown(wait=False)
        if self.wal is not None:
            self.wal.close()
        if self.rt_publisher is not None:
            self.rt_publisher.shutdown()
        if self.search_plugin is not None:
            self.search_plugin.shutdown()

    def drop_caches(self) -> None:
        """(ref: TSDB.dropCaches) UID caches are authoritative here;
        the device-resident grid cache and its host-RAM prepared-batch
        twin are droppable."""
        if self._device_grid_cache is not None:
            self._device_grid_cache.clear()
        if self._host_prep_cache is not None:
            self._host_prep_cache.clear()
        if self._result_cache is not None:
            self._result_cache.clear()
        if self._streaming is not None:
            # continuous-query plans re-seed from the store on their
            # next serve/pump (operator escape hatch)
            self._streaming.invalidate()

    # ------------------------------------------------------------------
    # stats (ref: TSDB.collectStats :753)
    # ------------------------------------------------------------------

    def collect_stats(self, collector) -> None:
        self.uids.metrics.collect_stats(collector)
        self.uids.tag_names.collect_stats(collector)
        self.uids.tag_values.collect_stats(collector)
        self.store.collect_stats(collector)
        lc = self._lifecycle
        cold = getattr(lc, "coldstore", None) if lc is not None \
            else None
        collector.record("storage.cold_bytes",
                         cold.cold_bytes() if cold is not None else 0)
        collector.record("datapoints.added", self.datapoints_added)
        for hook, n in sorted(self.hook_errors.items()):
            collector.record("hooks.errors", n, hook=hook)
        collector.record("uptime.seconds",
                         int(time.time() - self.start_time))
