"""UID service: bidirectional name <-> fixed-width-UID dictionary.

(ref: ``src/uid/UniqueId.java``) The reference stores the mapping in the
``tsdb-uid`` HBase table and allocates ids with an atomic increment on
MAXID_ROW followed by two CAS writes (UniqueId.java:596-625). The TPU
build keeps the same semantics — monotonically increasing ids per kind,
width-limited, assignment-is-idempotent, pending-assignment dedupe
(UniqueId.java:117) — on top of a process-local store guarded by a lock.
Horizontal scale-out of assignment moves to the storage backend the same
way the reference delegates to HBase atomics.

Also supports random UID assignment for metrics
(ref: ``src/uid/RandomUniqueId.java``) and UID-filter plugins
(ref: ``src/uid/UniqueIdFilterPlugin.java``).
"""

from __future__ import annotations

import random
import threading
from typing import Callable, Iterable

from opentsdb_tpu.core import const

UID_KINDS = ("metric", "tagk", "tagv")


class NoSuchUniqueName(LookupError):
    """Name has no assigned UID (ref: src/uid/NoSuchUniqueName.java)."""

    def __init__(self, kind: str, name: str):
        super().__init__(f"No such name for '{kind}': '{name}'")
        self.kind = kind
        self.name = name


class NoSuchUniqueId(LookupError):
    """UID has no assigned name (ref: src/uid/NoSuchUniqueId.java)."""

    def __init__(self, kind: str, uid: bytes):
        super().__init__(f"No such unique ID for '{kind}': {uid.hex()}")
        self.kind = kind
        self.uid = uid


class FailedToAssignUniqueIdError(RuntimeError):
    """Assignment rejected (filter veto or id space exhausted)
    (ref: src/uid/FailedToAssignUniqueIdException.java)."""


class UniqueId:
    """One UID dictionary for one kind ('metric' | 'tagk' | 'tagv').

    ids are exposed both as ints (used by the array compute path, where a
    series' group-by key is its tagv id) and as big-endian fixed-width
    bytes (the storage codec form). id 0 is never assigned (matches the
    reference, where 0 is reserved).
    """

    def __init__(self, kind: str, width: int = 3,
                 random_ids: bool = False,
                 filter_fn: Callable[[str, str], bool] | None = None):
        if kind not in UID_KINDS:
            raise ValueError(f"unknown UID kind {kind!r}")
        if not 1 <= width <= 8:
            raise ValueError(f"invalid UID width {width}")
        self.kind = kind
        self.width = width
        self.random_ids = random_ids
        self.max_possible_id = (1 << (8 * width)) - 1
        self._filter = filter_fn
        self._lock = threading.Lock()
        self._name_to_id: dict[str, int] = {}
        self._id_to_name: dict[int, str] = {}
        self._sorted_names: list[str] | None = None  # suggest index
        self._max_id = 0
        self._rng = random.Random(0xC0FFEE)
        # cache-statistics parity with UniqueId.java:105-114
        self.cache_hits = 0
        self.cache_misses = 0
        self.random_id_collisions = 0

    # -- lookups ----------------------------------------------------------

    def get_id(self, name: str) -> int:
        with self._lock:
            uid = self._name_to_id.get(name)
        if uid is None:
            self.cache_misses += 1
            raise NoSuchUniqueName(self.kind, name)
        self.cache_hits += 1
        return uid

    def get_name(self, uid: int | bytes) -> str:
        iid = self.uid_to_int(uid) if isinstance(uid, bytes) else uid
        with self._lock:
            name = self._id_to_name.get(iid)
        if name is None:
            raise NoSuchUniqueId(self.kind, self.int_to_uid(iid))
        return name

    def has_name(self, name: str) -> bool:
        with self._lock:
            return name in self._name_to_id

    # -- assignment (ref: UniqueId.java:596-625, :865) --------------------

    def get_or_create_id(self, name: str) -> int:
        with self._lock:
            uid = self._name_to_id.get(name)
            if uid is not None:
                return uid
            return self._assign_locked(name)

    def assign_id(self, name: str) -> int:
        """Explicit assignment (``tsdb mkmetric`` / ``/api/uid/assign``).

        Fails if the name already has a UID (matches UidManager semantics).
        """
        with self._lock:
            if name in self._name_to_id:
                raise FailedToAssignUniqueIdError(
                    f"Name already exists with UID: "
                    f"{self.int_to_uid(self._name_to_id[name]).hex()}")
            return self._assign_locked(name)

    def _assign_locked(self, name: str) -> int:
        if self._filter is not None and not self._filter(self.kind, name):
            raise FailedToAssignUniqueIdError(
                f"UID filter rejected assignment of {self.kind} '{name}'")
        if self.random_ids:
            # ref: RandomUniqueId.java — random id, retry on collision
            for _ in range(10):
                cand = self._rng.randint(1, self.max_possible_id)
                if cand not in self._id_to_name:
                    uid = cand
                    break
                self.random_id_collisions += 1
            else:
                raise FailedToAssignUniqueIdError(
                    f"could not find a free random UID for '{name}'")
        else:
            if self._max_id >= self.max_possible_id:
                raise FailedToAssignUniqueIdError(
                    f"all {self.max_possible_id} UIDs of kind "
                    f"{self.kind} are assigned")
            self._max_id += 1
            uid = self._max_id
        self._name_to_id[name] = uid
        self._id_to_name[uid] = name
        self._sorted_names = None
        return uid

    def rename(self, old_name: str, new_name: str) -> None:
        """(ref: UniqueId.java rename)"""
        with self._lock:
            if old_name not in self._name_to_id:
                raise NoSuchUniqueName(self.kind, old_name)
            if new_name in self._name_to_id:
                raise FailedToAssignUniqueIdError(
                    f"cannot rename to existing name '{new_name}'")
            uid = self._name_to_id.pop(old_name)
            self._name_to_id[new_name] = uid
            self._id_to_name[uid] = new_name
            self._sorted_names = None

    def delete(self, name: str) -> None:
        """(ref: UniqueId.java deleteAsync, 2.2+)"""
        with self._lock:
            if name not in self._name_to_id:
                raise NoSuchUniqueName(self.kind, name)
            uid = self._name_to_id.pop(name)
            self._id_to_name.pop(uid, None)
            self._sorted_names = None

    # -- suggest (ref: UniqueId.java suggest / TSDB.java:1762-1816) -------

    def suggest(self, search: str, max_results: int = 25) -> list[str]:
        """Prefix seek over a cached sorted index — the analogue of the
        reference's scanner with a start row on the sorted name CF
        (sorting all names per keystroke is O(N log N) at 1M+ UIDs)."""
        import bisect
        with self._lock:
            names = self._sorted_names
            if names is None:
                names = self._sorted_names = sorted(self._name_to_id)
            lo = bisect.bisect_left(names, search)
            out = []
            for n in names[lo:lo + max_results]:
                if not n.startswith(search):
                    break
                out.append(n)
        return out

    def grep(self, regex: str) -> list[str]:
        import re
        pat = re.compile(regex)
        with self._lock:
            return sorted(n for n in self._name_to_id if pat.search(n))

    # -- codecs -----------------------------------------------------------

    def int_to_uid(self, uid: int) -> bytes:
        return uid.to_bytes(self.width, "big")

    def uid_to_int(self, uid: bytes) -> int:
        if len(uid) != self.width:
            raise ValueError(
                f"wrong UID length {len(uid)}, expected {self.width}")
        return int.from_bytes(uid, "big")

    # -- introspection ----------------------------------------------------

    def __len__(self) -> int:
        with self._lock:
            return len(self._name_to_id)

    def names(self) -> list[str]:
        with self._lock:
            return list(self._name_to_id)

    def items(self) -> list[tuple[str, int]]:
        with self._lock:
            return list(self._name_to_id.items())

    def max_id(self) -> int:
        with self._lock:
            return self._max_id

    def collect_stats(self, collector) -> None:
        collector.record("uid.cache-hit", self.cache_hits, kind=self.kind)
        collector.record("uid.cache-miss", self.cache_misses, kind=self.kind)
        # (ref: UniqueId.java random_id_collisions stat — bumped here
        # since the random-metric path landed but never exported until
        # tsdlint's counter-export pass flagged it)
        collector.record("uid.random-id-collisions",
                         self.random_id_collisions, kind=self.kind)
        collector.record("uid.cache-size", len(self), kind=self.kind)
        collector.record("uid.ids-used", self.max_id(), kind=self.kind)
        collector.record("uid.ids-available",
                         self.max_possible_id - self.max_id(), kind=self.kind)


class UidRegistry:
    """The three UID dictionaries owned by a TSDB (ref: TSDB.java:125-129)."""

    def __init__(self, metric_width: int = const.METRICS_WIDTH,
                 tagk_width: int = const.TAG_NAME_WIDTH,
                 tagv_width: int = const.TAG_VALUE_WIDTH,
                 random_metrics: bool = False):
        self.metrics = UniqueId("metric", metric_width,
                                random_ids=random_metrics)
        self.tag_names = UniqueId("tagk", tagk_width)
        self.tag_values = UniqueId("tagv", tagv_width)

    def by_kind(self, kind: str) -> UniqueId:
        if kind in ("metric", "metrics"):
            return self.metrics
        if kind == "tagk":
            return self.tag_names
        if kind == "tagv":
            return self.tag_values
        raise ValueError(f"unknown UID kind {kind!r}")

    def tsuid(self, metric_id: int, tags: Iterable[tuple[int, int]]) -> bytes:
        """TSUID bytes = metric uid + (tagk uid + tagv uid) sorted by tagk."""
        out = bytearray(self.metrics.int_to_uid(metric_id))
        for tagk_id, tagv_id in sorted(tags):
            out += self.tag_names.int_to_uid(tagk_id)
            out += self.tag_values.int_to_uid(tagv_id)
        return bytes(out)
