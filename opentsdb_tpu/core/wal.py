"""Write-ahead log: acknowledged writes survive a crash.

The reference delegates ingest durability to HBase's WAL — every
acknowledged ``put`` is in the RegionServer's log before the Deferred
completes, and batch imports may opt out per-request
(``PutRequest.setDurable(false)``, ref IncomingDataPoints.java:355-360).
Snapshots (:mod:`opentsdb_tpu.core.persist`) alone lose everything
acknowledged since the last ``flush``; this module closes that gap:

- append-only segment files under ``<data_dir>/wal/``, records framed
  ``[type u8 | len u32 | seq u64 | crc32 u32 | payload]``; a torn tail
  (crash mid-write) fails the CRC and replay stops there — exactly the
  acknowledged prefix survives.
- **group-commit fsync v2**: one commit leader fsyncs at a time and
  every waiter acknowledges by SEQUENCE — a waiter whose bytes a
  concurrent leader already covered returns without touching the disk
  at all. With ``tsd.storage.wal.group_window_ms > 0`` the leader
  additionally holds a bounded commit window, absorbing more buffered
  bytes before the fsync (cut short by the ``group_max_records`` /
  ``group_max_bytes`` caps, or as soon as the log goes quiet so a
  lone writer is never delayed by the window)
  (``tsd.storage.wal.fsync`` = ``always`` | ``interval`` | ``never``;
  ``never`` ≙ the reference's ``setDurable(false)``).
- **request-scoped batching** (:meth:`WriteAheadLog.batch`): appends
  inside the scope buffer thread-locally and land as ONE framed write
  under one lock acquisition at scope exit, and every ``sync()``
  requested inside defers to a single group-committed fsync — one
  HTTP put body / telnet line burst / import buffer costs one WAL
  write and one fsync, not one per series-group or per point.
- hot point records are columnar binary (one record per store append —
  the same batch shape the native store takes); series/UID identity
  records carry *names* so replay is self-contained: it re-resolves
  through ``get_or_create`` and remaps sids, immune to sid-numbering
  drift between runs.
- ``truncate()`` after a successful snapshot deletes fully-covered
  segments; the snapshot's ``wal_applied_seq`` (persist.META.json)
  makes replay skip anything the snapshot already contains. Replaying
  a record twice is harmless by construction: point stores dedupe
  (ts, value) last-write-wins on materialize, ``get_or_create`` is
  idempotent, annotation store is keyed.

Single-writer by design (like the snapshot store): the TSD daemon owns
the WAL; CLI tools against a *live* daemon's data_dir are not
coordinated (the reference relies on HBase for that).
"""

from __future__ import annotations

import contextlib
import json
import logging
import os
import struct
import threading
import time
import zlib

import numpy as np

from opentsdb_tpu.utils.faults import call_with_retries

log = logging.getLogger("wal")

_HDR = struct.Struct("<BIQI")  # type, payload_len, seq, crc32
MAGIC = b"OTSDBWAL1\n"

T_SERIES = 1      # json {"k": kind, "sid": int, "m": name, "t": [[k,v]..]}
T_POINTS = 2      # bin: kind | sid i64 | n i32 | ts i64[n] f64[n] u8[n]
T_LINES = 3       # bin: kind | n i32 | sids i64[n] ts i64[n] f64[n] u8[n]
T_UID = 4         # json {"kind", "name"}
T_ANNOT = 5       # json annotation doc (+"tsuid")
T_ANNOT_DEL = 6   # json {"tsuid", "start"}
T_HIST = 7        # json {"m", "t", "ts"} \n blob bytes

_KIND = struct.Struct("<B")     # kind string length prefix
_SID_N = struct.Struct("<qi")   # sid, count
_N = struct.Struct("<i")        # count


def _pack_kind(kind: str) -> bytes:
    kb = kind.encode()
    return _KIND.pack(len(kb)) + kb


def _unpack_kind(buf: bytes, off: int) -> tuple[str, int]:
    (n,) = _KIND.unpack_from(buf, off)
    off += _KIND.size
    return buf[off:off + n].decode(), off + n


def _pack_cols(ts, vals, flags) -> bytes:
    return (np.ascontiguousarray(ts, dtype=np.int64).tobytes()
            + np.ascontiguousarray(vals, dtype=np.float64).tobytes()
            + np.ascontiguousarray(flags, dtype=np.uint8).tobytes())


def _unpack_cols(buf: bytes, off: int, n: int):
    ts = np.frombuffer(buf, np.int64, n, off)
    off += 8 * n
    vals = np.frombuffer(buf, np.float64, n, off)
    off += 8 * n
    flags = np.frombuffer(buf, np.uint8, n, off)
    return ts, vals, flags


class _WalBatch:
    """Thread-local buffer of one request's records (see
    :meth:`WriteAheadLog.batch`)."""

    __slots__ = ("records", "nbytes", "sync_wanted", "known")

    def __init__(self):
        # tsdlint: allow[unbounded-growth] request-scoped buffer: the
        # batch object dies at WriteAheadLog.batch() scope exit
        self.records: list[tuple[int, bytes]] = []
        self.nbytes = 0
        self.sync_wanted = False
        # tsdlint: allow[unbounded-growth] request-scoped (see records)
        self.known: set[tuple[str, int]] = set()


class WriteAheadLog:
    def __init__(self, wal_dir: str, fsync_mode: str = "always",
                 segment_bytes: int = 64 << 20,
                 interval_ms: int = 200, faults=None, retry=None,
                 resync_ms: int = 1000, group_window_ms: int = 0,
                 group_max_records: int = 4096,
                 group_max_bytes: int = 4 << 20):
        if fsync_mode not in ("always", "interval", "never"):
            raise ValueError(f"bad wal fsync mode {fsync_mode!r}")
        self.dir = wal_dir
        self.fsync_mode = fsync_mode
        self.segment_bytes = segment_bytes
        os.makedirs(wal_dir, exist_ok=True)
        self._lock = threading.Lock()       # append framing + seq
        self._fh = None
        self._seq = 0
        self._written = 0   # bytes appended to current segment
        self._synced_seq = 0
        # tsdlint: allow[unbounded-growth] series-identity mirror of
        # the store index — bounded by live series cardinality, and
        # reclaimed with it (demotion-aware UID reclamation, ROADMAP)
        self._known: set[tuple[str, int]] = set()
        self._closed = False
        self._interval_thread = None
        # interval-mode fsync loop stop signal: close() sets it and
        # JOINS the thread — a daemon flag alone would leave the loop
        # (and its reference to this WAL) alive for up to a full
        # interval after close, which the thread-lifecycle lint and
        # the leak witness both flag on a run-forever process
        self._interval_stop = threading.Event()
        # group commit v2: exactly one commit LEADER fsyncs at a time;
        # everyone else acknowledges by sequence (_synced_seq >= their
        # last appended record). A leader may hold a bounded commit
        # window (group_window_s) absorbing more buffered bytes before
        # paying the fsync; the caps below cut the window short, and a
        # quiet log (no new appends in a poll slice) ends it
        # immediately so a lone writer never pays the window.
        self._commit_cond = threading.Condition()
        self._commit_leader = False
        self.group_window_s = max(group_window_ms, 0) / 1000.0
        self.group_max_records = max(int(group_max_records), 1)
        self.group_max_bytes = max(int(group_max_bytes), 1)
        self._bytes_appended = 0  # total framed bytes ever appended
        self._bytes_synced = 0    # ... covered by a successful fsync
        # observability: records_per_sync = records_synced/group_syncs
        self.group_syncs = 0        # physical fsync rounds
        self.records_synced = 0     # records those rounds covered
        self.piggybacked_syncs = 0  # sync() calls another round covered
        self.window_expiries = 0    # commit window closed by timeout
        self.size_triggers = 0      # ... by the records/bytes caps
        self.idle_breaks = 0        # ... by a quiet log (lone writer)
        # request-scoped batching (batch()): per-thread buffer
        self._tls = threading.local()
        # graceful degradation on persistent fsync failure: appends
        # keep being accepted (availability over durability — loudly:
        # the flag is exported via /api/health and stats) and a
        # resync probe retries every resync_ms instead of paying the
        # full retry ladder on every write
        self._faults = faults          # FaultInjector or None
        self._retry = retry            # RetryPolicy or None (= no retry)
        self._resync_s = max(resync_ms, 0) / 1000.0
        self.degraded = False
        self._degraded_until = 0.0
        # append health is tracked separately from fsync health: an
        # fsync-only outage must NOT shed appends (the buffered writes
        # are re-covered by the next successful fsync), while a write
        # outage must not pay the retry ladder per record
        self._append_failing = False
        # a segment was closed (rotation) without a successful fsync:
        # those records stay non-durable until a snapshot covers them
        # (truncate clears the flag); surfaced via health
        self.durability_hole = False
        self.sync_failures = 0    # fsync retry-ladder exhaustions
        self.sync_retries = 0     # individual retried fsyncs
        self.append_failures = 0  # write retry-ladder exhaustions
        self.append_dropped = 0   # records shed while WAL is offline
        self.last_sync_error = ""
        if fsync_mode == "interval":
            self._interval_s = interval_ms / 1000.0
            t = threading.Thread(target=self._interval_loop,
                                 name="wal-fsync", daemon=True)
            self._interval_thread = t
            t.start()

    # ---------------- segments ----------------

    def _segments(self) -> list[str]:
        names = [n for n in os.listdir(self.dir)
                 if n.startswith("wal-") and n.endswith(".log")]
        # wal-<firstseq 20 digits>-<pid>.log sorts by first seq
        return [os.path.join(self.dir, n) for n in sorted(names)]

    def _open_segment(self) -> None:
        name = f"wal-{self._seq + 1:020d}-{os.getpid()}.log"
        path = os.path.join(self.dir, name)
        self._fh = open(path, "ab", buffering=0)
        if self._fh.tell() == 0:
            self._fh.write(MAGIC)
        self._written = self._fh.tell()

    # ---------------- append side ----------------

    def _roll_segment_locked(self) -> bool:
        """Rotate/open the active segment if needed (caller holds
        ``_lock``). Returns False when the write path is offline (the
        caller sheds its record(s))."""
        if self._fh is not None and self._written < self.segment_bytes:
            return True
        if self._fh is not None:
            # rotation must not lose durability: sync() after this
            # append only fsyncs the NEW segment, so the old one's
            # unsynced tail must hit disk now. On a broken disk this
            # degrades (tail may be lost on crash — recorded as a
            # durability hole until a snapshot covers it) rather than
            # failing the write.
            if not self._fsync_or_degrade(self._fh, "rotation fsync"):
                self.durability_hole = True
            try:
                self._fh.close()
            except OSError as exc:
                log.warning("wal segment close failed (%s); "
                            "abandoning handle", exc)
            self._fh = None
        try:
            self._open_segment()
        except OSError as exc:
            # can't even open a new segment: the write path is
            # offline — shed, probe again after the resync window
            self.append_failures += 1
            self._append_failing = True
            self._note_degraded(exc, "segment open")
            return False
        return True

    def _write_framed_locked(self, blob: bytes) -> bool:
        """Write pre-framed record bytes to the active segment under
        the retry ladder (caller holds ``_lock``); False = shed."""

        def write_rec():
            if self._faults is not None:
                self._faults.check("wal.append")
            self._fh.write(blob)

        try:
            # tsdlint: allow[lock-blocking] append framing IS the
            # lock's critical section (single-writer log); the retry
            # ladder is deadline-bounded and exhaustion degrades
            call_with_retries(write_rec, self._retry,
                              retryable=(OSError,))
        except OSError as exc:
            # availability over durability, loudly (the record is
            # lost from the log; /api/health carries the flag)
            self.append_failures += 1
            self._append_failing = True
            self._note_degraded(exc, "append")
            return False
        self._written += len(blob)
        self._bytes_appended += len(blob)
        if self._append_failing:
            self._append_failing = False
            log.info("wal append recovered; records are being "
                     "logged again")
            if self.fsync_mode == "never":
                # no fsync path exists to clear the flag in this
                # mode; append health IS the WAL's health
                self.degraded = False
        return True

    def _append(self, rtype: int, payload: bytes) -> int:
        """Frame + write one record. Returns the record's sequence
        number, or -1 when the record was shed/lost because the WAL
        write path is degraded (callers whose bookkeeping depends on
        the record actually being in the log — ``ensure_series`` —
        must check). Inside a :meth:`batch` scope the record is
        buffered locally (landing at scope exit) and 0 is returned."""
        b = getattr(self._tls, "batch", None)
        if b is not None:
            b.records.append((rtype, payload))
            b.nbytes += _HDR.size + len(payload)
            return 0
        with self._lock:
            if self._closed:
                raise RuntimeError("WAL is closed")
            if self._append_failing and \
                    time.monotonic() < self._degraded_until:
                # write path offline: shed the record entirely — the
                # caller's store write already happened and is
                # acknowledged; durability is what's degraded, and
                # paying the retry ladder (or re-probing segment open)
                # per append would turn the disk outage into a
                # latency outage
                self.append_dropped += 1
                return -1
            if not self._roll_segment_locked():
                return -1
            self._seq += 1
            rec = _HDR.pack(rtype, len(payload), self._seq,
                            zlib.crc32(payload)) + payload
            if not self._write_framed_locked(rec):
                return -1
            return self._seq

    def _append_batch(self, records: list[tuple[int, bytes]]) -> int:
        """Frame + write many records under ONE lock acquisition and
        one ``write()``. Returns the last record's sequence number, or
        -1 when the whole batch was shed (degraded write path)."""
        with self._lock:
            if self._closed:
                raise RuntimeError("WAL is closed")
            if self._append_failing and \
                    time.monotonic() < self._degraded_until:
                self.append_dropped += len(records)
                return -1
            if not self._roll_segment_locked():
                return -1
            frames = []
            for rtype, payload in records:
                self._seq += 1
                frames.append(_HDR.pack(rtype, len(payload), self._seq,
                                        zlib.crc32(payload)) + payload)
            if not self._write_framed_locked(b"".join(frames)):
                return -1
            return self._seq

    # ---------------- request-scoped batching ----------------

    @contextlib.contextmanager
    def batch(self):
        """Request-scoped batching: every record appended inside the
        scope is buffered (per thread) and lands as one framed write
        under a single lock acquisition at scope exit; ``sync()``
        calls inside defer to at most ONE group-committed fsync at
        exit. The scope commits on exceptions too — points the caller
        already wrote to the store (and may have acknowledged per
        point) stay on the durability path. Within the scope,
        post-write hooks may observe a point before its fsync (the
        same window ``fsync=interval`` always has); the caller's own
        return still happens after durability. Nested scopes join the
        outermost one."""
        if getattr(self._tls, "batch", None) is not None:
            yield self
            return
        b = self._tls.batch = _WalBatch()
        try:
            yield self
        finally:
            self._tls.batch = None
            self._commit_batch(b)

    def _commit_batch(self, b: _WalBatch) -> None:
        if b.records:
            try:
                last = self._append_batch(b.records)
            except RuntimeError:
                # closed mid-request (shutdown race): the caller's
                # store writes happened and its per-point accounting
                # is done — raising here (from batch()'s finally)
                # would mask any in-scope exception and fail a
                # request whose writes landed. Shed the records,
                # loudly: the pre-close flush snapshot covers the
                # normal shutdown path anyway.
                log.warning("wal closed mid-batch; %d record(s) shed",
                            len(b.records))
                self.append_dropped += len(b.records)
                return
            if last >= 0 and b.known:
                # the series-identity records are durably framed (or
                # at least written): the mapping is now in the log
                self._known.update(b.known)
        else:
            last = None
        if b.sync_wanted and last != -1:
            self.sync(upto=last)

    def _append_json(self, rtype: int, doc: dict) -> int:
        return self._append(rtype, json.dumps(doc).encode())

    def ensure_series(self, kind: str, sid: int, metric: str,
                      tags: dict[str, str]) -> None:
        """Log the (kind, sid) -> name mapping once per WAL lifetime so
        point records can reference bare sids."""
        key = (kind, sid)
        if key in self._known:
            return
        b = getattr(self._tls, "batch", None)
        if b is not None:
            # buffered: _known is only merged if the batched write
            # actually lands (see _commit_batch) — marking it early
            # would leave durable point records with no T_SERIES
            # entry if the write path sheds the batch
            if key in b.known:
                return
            b.known.add(key)
            self._append_json(T_SERIES, {
                "k": kind, "sid": sid, "m": metric,
                "t": sorted(tags.items())})
            return
        seq = self._append_json(T_SERIES, {
            "k": kind, "sid": sid, "m": metric,
            "t": sorted(tags.items())})
        if seq < 0:
            # record shed/lost (degraded write path): stay un-known so
            # the mapping is re-attempted before this series' next
            # point — marking it known would leave durable point
            # records with no T_SERIES entry, which replay would
            # misattribute through the identity-sid fallback
            return
        self._known.add(key)

    def seed_known(self, kind: str, num_series: int) -> None:
        """Mark sids already covered by the loaded snapshot (their
        numbering is reproduced by snapshot load order)."""
        self._known.update((kind, s) for s in range(num_series))

    def log_points(self, kind: str, sid: int, ts_ms, vals, flags
                   ) -> None:
        n = len(ts_ms)
        self._append(T_POINTS, _pack_kind(kind) + _SID_N.pack(sid, n)
                     + _pack_cols(ts_ms, vals, flags))

    def log_point(self, kind: str, sid: int, ts_ms: int, value: float,
                  is_int: bool) -> None:
        self._append(T_POINTS, _pack_kind(kind) + _SID_N.pack(sid, 1)
                     + struct.pack("<qdB", ts_ms, value, is_int))

    def log_lines(self, kind: str, sids, ts_ms, vals, flags) -> None:
        n = len(sids)
        self._append(T_LINES, _pack_kind(kind) + _N.pack(n)
                     + np.ascontiguousarray(sids, np.int64).tobytes()
                     + _pack_cols(ts_ms, vals, flags))

    def log_uid(self, kind: str, name: str) -> None:
        self._append_json(T_UID, {"kind": kind, "name": name})

    def log_annotation(self, doc: dict) -> None:
        self._append_json(T_ANNOT, doc)

    def log_annotation_delete(self, tsuid: str, start: int) -> None:
        self._append_json(T_ANNOT_DEL, {"tsuid": tsuid, "start": start})

    def log_histogram(self, metric: str, tags: dict[str, str],
                      ts_ms: int, blob: bytes) -> None:
        head = json.dumps({"m": metric, "t": sorted(tags.items()),
                           "ts": ts_ms}).encode()
        self._append(T_HIST, head + b"\n" + blob)

    def sync(self, upto: int | None = None) -> None:
        """Block until the caller's appended records are on disk
        (group commit: one fsync covers every waiter; ``upto`` bounds
        the wait to that sequence — callers that know their last
        record return as soon as a concurrent commit covers it).
        Inside a :meth:`batch` scope this defers to one fsync at
        scope exit."""
        if self.fsync_mode != "always":
            return
        b = getattr(self._tls, "batch", None)
        if b is not None:
            b.sync_wanted = True
            return
        self._sync(upto)

    def _note_degraded(self, exc: Exception, context: str) -> None:
        """Flip (or extend) degraded mode after a retry-ladder
        exhaustion: acknowledged writes may not be durable until the
        disk recovers; probes retry every ``resync_ms``."""
        self.last_sync_error = f"{context}: {type(exc).__name__}: {exc}"
        if not self.degraded:
            log.error("wal %s failing persistently (%s); running "
                      "DEGRADED — acknowledged writes may not be "
                      "durable until the disk recovers", context, exc)
        self.degraded = True
        self._degraded_until = time.monotonic() + self._resync_s

    def _fsync_or_degrade(self, fh, context: str) -> bool:
        """fsync under the retry ladder; exhaustion degrades (counted,
        logged, flagged) instead of raising. Returns True when the
        data is known durable."""

        def do_fsync():
            if self._faults is not None:
                self._faults.check("wal.fsync")
            os.fsync(fh.fileno())

        def on_retry(attempt, exc):
            self.sync_retries += 1
            log.warning("wal fsync failed (attempt %d: %s); "
                        "retrying", attempt, exc)

        try:
            call_with_retries(do_fsync, self._retry,
                              retryable=(OSError,), on_retry=on_retry)
        except ValueError:
            # segment closed mid-sync by truncate — which fsyncs
            # before closing, so the target is already durable
            return True
        except OSError as exc:
            self.sync_failures += 1
            self._note_degraded(exc, context)
            return False
        return True

    def _sync(self, upto: int | None = None) -> None:
        with self._lock:
            target = self._seq if upto is None else min(upto, self._seq)
        if self._synced_seq >= target:
            return
        # trace the group-commit wait (the durability tax one request
        # actually pays — leader fsync or piggyback alike); a no-op
        # thread-local read outside a traced request
        from opentsdb_tpu.obs.trace import trace_begin, trace_end
        _h = trace_begin("wal.commit_wait")
        try:
            self._sync_inner(target)
        finally:
            trace_end(_h)

    def _sync_inner(self, target: int) -> None:
        if self.degraded and time.monotonic() < self._degraded_until:
            # shed durability work until the next resync probe: paying
            # the full retry ladder on every write while the disk is
            # down would turn a durability loss into a latency outage
            return
        # leader election: exactly one commit round runs at a time;
        # everyone else waits on the condition and acknowledges by
        # SEQUENCE — if the in-flight round covers their records they
        # return without ever touching the disk. A failed round can
        # never strand a waiter: the leader always clears leadership +
        # notifies in its finally, and waiters re-check the degraded
        # window (set by the failure) on every wake.
        with self._commit_cond:
            while True:
                if self._synced_seq >= target:
                    self.piggybacked_syncs += 1
                    return
                if self._closed:
                    return
                if self.degraded and \
                        time.monotonic() < self._degraded_until:
                    return
                if not self._commit_leader:
                    self._commit_leader = True
                    break
                self._commit_cond.wait(0.05)
        try:
            self._commit_once()
        finally:
            with self._commit_cond:
                self._commit_leader = False
                self._commit_cond.notify_all()

    def _commit_window_wait(self) -> None:
        """Bounded commit window: the leader absorbs more buffered
        bytes before paying the fsync. Cut short by the records/bytes
        caps, and by a QUIET log — no new appends during a poll slice.
        Waiters blocked in sync() do NOT hold the window open: their
        records are already appended (append happens-before sync), so
        once the log stops growing the fsync covers everyone and
        further waiting is pure latency. A lone writer therefore
        never pays more than ~one poll slice."""
        deadline = time.monotonic() + self.group_window_s
        slice_s = min(self.group_window_s, 0.001)
        while True:
            with self._lock:
                pending = self._seq - self._synced_seq
                pending_bytes = self._bytes_appended - self._bytes_synced
            if pending >= self.group_max_records or \
                    pending_bytes >= self.group_max_bytes:
                self.size_triggers += 1
                return
            now = time.monotonic()
            if now >= deadline:
                self.window_expiries += 1
                return
            time.sleep(min(deadline - now, slice_s))
            with self._lock:
                grew = self._seq - self._synced_seq > pending
            if not grew:
                self.idle_breaks += 1
                return

    def _commit_once(self) -> None:
        """One physical commit round (caller is the elected leader):
        optionally hold the commit window, then fsync once, covering
        every record appended up to the capture point."""
        if self.group_window_s > 0.0 and self.fsync_mode == "always" \
                and not self._closed:
            self._commit_window_wait()
        with self._lock:
            target = self._seq
            covered_bytes = self._bytes_appended
            fh = self._fh
        if fh is None or self._synced_seq >= target:
            # fh None => a concurrent truncate fsync'd + closed the
            # segment, so everything appended before it is durable
            # — unless a rotation closed a segment WITHOUT a
            # successful fsync (durability_hole): then the claim
            # would be a lie; the hole stands until a snapshot
            # covers it (truncate clears it)
            if not self.durability_hole:
                self._synced_seq = max(self._synced_seq, target)
                self._bytes_synced = max(self._bytes_synced,
                                         covered_bytes)
            return
        if not self._fsync_or_degrade(fh, "fsync"):
            # records stay buffered in the segment; the next
            # successful probe re-covers them (one fsync syncs
            # the whole file)
            return
        self.group_syncs += 1
        self.records_synced += target - self._synced_seq
        self._synced_seq = target
        self._bytes_synced = max(self._bytes_synced, covered_bytes)
        if self.degraded:
            log.info("wal fsync recovered after %d failure(s); "
                     "durability restored", self.sync_failures)
            self.degraded = False

    def _interval_loop(self) -> None:
        while not self._interval_stop.wait(self._interval_s):
            try:
                self._sync()
            except (OSError, ValueError):  # pragma: no cover
                if self._closed:
                    return
                log.exception("wal interval fsync failed")

    def last_seq(self) -> int:
        with self._lock:
            return self._seq

    def sync_lag(self) -> int:
        """Appended-but-not-yet-fsynced record count (0 when healthy
        in ``always`` mode; the group-commit window in ``interval``
        mode; grows unboundedly while degraded)."""
        with self._lock:
            return max(self._seq - self._synced_seq, 0)

    def records_per_sync(self) -> float:
        """Mean records covered per physical fsync round — the
        group-commit amortization factor (1.0 = no batching win)."""
        if not self.group_syncs:
            return 0.0
        return self.records_synced / self.group_syncs

    def health_info(self) -> dict:
        return {
            "fsync_mode": self.fsync_mode,
            "last_seq": self.last_seq(),
            "synced_seq": self._synced_seq,
            "sync_lag": self.sync_lag(),
            "degraded": self.degraded,
            "durability_hole": self.durability_hole,
            "sync_failures": self.sync_failures,
            "sync_retries": self.sync_retries,
            "append_failures": self.append_failures,
            "append_dropped": self.append_dropped,
            "last_sync_error": self.last_sync_error,
            "group_window_ms": round(self.group_window_s * 1000.0, 3),
            "group_syncs": self.group_syncs,
            "records_synced": self.records_synced,
            "records_per_sync": round(self.records_per_sync(), 2),
            "piggybacked_syncs": self.piggybacked_syncs,
            "window_expiries": self.window_expiries,
            "size_triggers": self.size_triggers,
            "idle_breaks": self.idle_breaks,
        }

    def collect_stats(self, collector) -> None:
        collector.record("wal.sync_lag", self.sync_lag())
        collector.record("wal.sync_failures", self.sync_failures)
        collector.record("wal.sync_retries", self.sync_retries)
        collector.record("wal.append_failures", self.append_failures)
        collector.record("wal.append_dropped", self.append_dropped)
        collector.record("wal.degraded", int(self.degraded))
        collector.record("wal.group_syncs", self.group_syncs)
        collector.record("wal.records_per_sync",
                         round(self.records_per_sync(), 2))
        collector.record("wal.piggybacked_syncs", self.piggybacked_syncs)
        collector.record("wal.window_expiries", self.window_expiries)
        collector.record("wal.size_triggers", self.size_triggers)
        collector.record("wal.idle_breaks", self.idle_breaks)

    def truncate(self, upto_seq: int) -> None:
        """Drop segments fully covered by a snapshot that recorded
        ``wal_applied_seq = upto_seq``. The current segment is rotated
        so it can be dropped by the next truncate."""
        with self._lock:
            if self._fh is not None:
                # records > upto_seq may live in this segment and must
                # stay durable across the close (see _sync). On a
                # broken disk the segment stays OPEN and active so
                # later sync probes can still fsync its tail — closing
                # it would let _sync's fh-None branch ("closed =>
                # durably closed") overstate durability forever. The
                # flush itself still completes: the snapshot that
                # triggered this truncate IS durable, and segments it
                # fully covers are safe to unlink either way.
                if self._fsync_or_degrade(self._fh, "truncate fsync"):
                    self._fh.close()
                    self._fh = None  # reopened on next append
                    self._synced_seq = self._seq
                    self._bytes_synced = self._bytes_appended
                    # the snapshot covers every earlier record: any
                    # rotation-era durability hole is now irrelevant
                    self.durability_hole = False
            active = self._fh.name if self._fh is not None else None
            for path in self._segments():
                if path == active:
                    continue  # never unlink the live segment
                last = _segment_last_seq(path)
                if last is not None and last <= upto_seq:
                    os.unlink(path)

    def close(self) -> None:
        self._closed = True
        # stop + join the interval fsync thread FIRST, outside every
        # lock (the loop's _sync takes them): after close() returns no
        # thread of this WAL is alive
        self._interval_stop.set()
        t, self._interval_thread = self._interval_thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)
        with self._commit_cond:
            # wake sync waiters so they observe _closed instead of
            # polling out their timeout
            self._commit_cond.notify_all()
        with self._lock:
            if self._fh is not None:
                try:
                    # tsdlint: allow[lock-blocking] final shutdown
                    # fsync; _closed is already set, nothing contends
                    os.fsync(self._fh.fileno())
                except OSError:  # pragma: no cover
                    pass
                self._fh.close()
                self._fh = None

    # ---------------- replay side ----------------

    def replay(self, tsdb, applied_seq: int) -> int:
        """Apply records with seq > applied_seq. Returns points
        recovered. Resumes ``self._seq`` past everything seen so new
        appends never reuse sequence numbers."""
        recovered = 0
        sid_maps: dict[str, dict[int, int]] = {}
        max_seq = applied_seq
        segments = self._segments()
        for i, path in enumerate(segments):
            tail: dict = {}
            for rtype, seq, payload in _read_segment(path, tail=tail):
                if seq > max_seq:
                    max_seq = seq
                if seq <= applied_seq:
                    continue
                try:
                    recovered += self._apply(tsdb, rtype, payload,
                                             sid_maps)
                except Exception:  # noqa: BLE001  pragma: no cover
                    log.exception("wal: failed applying record "
                                  "seq=%d type=%d", seq, rtype)
            if i == len(segments) - 1:
                self._truncate_torn_tail(path, tail)
        with self._lock:
            self._seq = max(self._seq, max_seq)
            self._synced_seq = self._seq
        return recovered

    @staticmethod
    def _truncate_torn_tail(path: str, tail: dict) -> None:
        """Physically truncate a crash's partial final record off the
        last segment so the file ends at the last intact record —
        otherwise the torn bytes linger forever and every future
        replay re-reports them. Never raises: replay must come up on
        whatever disk state exists."""
        if not tail.get("torn"):
            return
        good_end = tail.get("good_end", 0)
        if good_end < len(MAGIC):
            # bad/partial magic: nothing recoverable to keep; leave
            # the segment for manual inspection (it is skipped anyway)
            return
        try:
            size = os.path.getsize(path)
            if good_end < size:
                os.truncate(path, good_end)
                log.warning(
                    "wal: truncated torn tail of %s (%d -> %d bytes)",
                    path, size, good_end)
        except OSError:  # pragma: no cover - best-effort repair
            log.exception("wal: could not truncate torn tail of %s",
                          path)

    def _store_for(self, tsdb, kind: str):
        if kind == "data":
            return tsdb.store
        if kind == "preagg":
            return tsdb.rollup_store.preagg_store()
        if kind.startswith("tier:"):
            _, interval, agg = kind.split(":", 2)
            return tsdb.rollup_store.tier(interval, agg)
        raise ValueError(f"unknown wal store kind {kind!r}")

    def _map_sid(self, tsdb, kind: str, wal_sid: int,
                 sid_maps: dict) -> int:
        m = sid_maps.get(kind)
        if m is not None and wal_sid in m:
            return m[wal_sid]
        # no T_SERIES record: the sid predates this WAL, so snapshot
        # load already recreated it under the same number
        return wal_sid

    def _apply(self, tsdb, rtype: int, payload: bytes,
               sid_maps: dict) -> int:
        if rtype == T_SERIES:
            doc = json.loads(payload)
            kind = doc["k"]
            tags = dict(doc["t"])
            metric_id, tag_ids = tsdb._resolve_write_uids(
                doc["m"], tags)
            store = self._store_for(tsdb, kind)
            real = store.get_or_create_series(metric_id, tag_ids)
            sid_maps.setdefault(kind, {})[doc["sid"]] = real
            if real == doc["sid"]:
                # drifted sids stay un-known: a future series reusing
                # the wal sid must get its own fresh T_SERIES record
                self._known.add((kind, real))
            return 0
        if rtype == T_POINTS:
            kind, off = _unpack_kind(payload, 0)
            wal_sid, n = _SID_N.unpack_from(payload, off)
            off += _SID_N.size
            if n == 1:
                ts, val, flag = struct.unpack_from("<qdB", payload, off)
                ts_arr = np.asarray([ts], np.int64)
                vals = np.asarray([val])
                flags = np.asarray([flag], np.uint8)
            else:
                ts_arr, vals, flags = _unpack_cols(payload, off, n)
            store = self._store_for(tsdb, kind)
            sid = self._map_sid(tsdb, kind, wal_sid, sid_maps)
            store.append_many(sid, ts_arr, vals,
                              flags.astype(bool))
            return n
        if rtype == T_LINES:
            kind, off = _unpack_kind(payload, 0)
            (n,) = _N.unpack_from(payload, off)
            off += _N.size
            sids = np.frombuffer(payload, np.int64, n, off).copy()
            off += 8 * n
            ts_arr, vals, flags = _unpack_cols(payload, off, n)
            m = sid_maps.get(kind)
            if m:
                # remap through a lookup into a FRESH array: sequential
                # in-place substitution corrupts chained maps like
                # {6:5, 5:6} (the second pass re-remaps converted rows)
                keys = np.asarray(sorted(m.keys()), np.int64)
                vals_lut = np.asarray([m[k] for k in keys], np.int64)
                pos = np.searchsorted(keys, sids)
                pos_ok = (pos < len(keys)) & \
                    (keys[np.minimum(pos, len(keys) - 1)] == sids)
                sids = np.where(pos_ok,
                                vals_lut[np.minimum(pos,
                                                    len(keys) - 1)],
                                sids)
            store = self._store_for(tsdb, kind)
            return store.append_lines(sids, ts_arr, vals,
                                      flags.astype(bool))
        if rtype == T_UID:
            doc = json.loads(payload)
            tsdb.uids.by_kind(doc["kind"]).get_or_create_id(
                doc["name"])
            return 0
        if rtype == T_ANNOT:
            from opentsdb_tpu.meta.annotation import Annotation
            tsdb.annotations.store(
                Annotation.from_json(json.loads(payload)),
                _wal=False)
            return 0
        if rtype == T_ANNOT_DEL:
            doc = json.loads(payload)
            tsdb.annotations.delete(doc["tsuid"], doc["start"],
                                    _wal=False)
            return 0
        if rtype == T_HIST:
            head, _, blob = payload.partition(b"\n")
            doc = json.loads(head)
            tsdb.add_histogram_point(
                doc["m"], doc["ts"],
                blob, dict(doc["t"]), _wal=False)
            return 1
        log.warning("wal: unknown record type %d skipped", rtype)
        return 0


def _read_segment(path: str, tail: dict | None = None):
    """Yield (type, seq, payload) until EOF or the first corrupt/torn
    record (normal after a crash — only the fsynced prefix counts).

    When ``tail`` is given it is filled with ``good_end`` (byte offset
    just past the last intact record) and ``torn`` (True when bytes
    beyond ``good_end`` exist but don't form a complete valid record)
    so the caller can repair the file (:meth:`WriteAheadLog.replay`).
    """
    if tail is None:
        tail = {}
    tail.update(good_end=0, torn=False)
    try:
        with open(path, "rb") as fh:
            magic = fh.read(len(MAGIC))
            if magic != MAGIC:
                log.warning("wal: %s has bad magic; skipped", path)
                tail["torn"] = bool(magic)
                return
            tail["good_end"] = len(MAGIC)
            while True:
                hdr = fh.read(_HDR.size)
                if not hdr:
                    return
                if len(hdr) < _HDR.size:
                    log.warning("wal: partial record header at end of "
                                "%s; replay stops here", path)
                    tail["torn"] = True
                    return
                rtype, plen, seq, crc = _HDR.unpack(hdr)
                payload = fh.read(plen)
                if len(payload) < plen or zlib.crc32(payload) != crc:
                    log.warning("wal: torn/corrupt record in %s at "
                                "seq=%d; replay stops here", path, seq)
                    tail["torn"] = True
                    return
                tail["good_end"] += _HDR.size + plen
                yield rtype, seq, payload
    except OSError:  # pragma: no cover
        log.exception("wal: cannot read %s", path)


def _segment_last_seq(path: str) -> int | None:
    last = None
    for _, seq, _ in _read_segment(path):
        last = seq
    return last
