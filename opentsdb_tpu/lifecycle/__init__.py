"""Data-lifecycle subsystem: retention, age-based rollup demotion,
store compaction and cold-tier disk spill (no reference equivalent —
the reference delegates all of this to HBase TTLs and region
compaction, SURVEY.md §5.4).

- :mod:`opentsdb_tpu.lifecycle.policy` — per-metric policies
  (``tsd.lifecycle.*`` keys + the ``/api/lifecycle`` admin surface)
- :mod:`opentsdb_tpu.lifecycle.manager` — the background sweeper:
  retention purge (raw + tiers + histogram arenas + cold segments),
  age-based demotion into rollup tiers, buffer compaction, cold-tier
  spill (:mod:`opentsdb_tpu.coldstore`), post-sweep snapshot + WAL
  truncation
- :mod:`opentsdb_tpu.lifecycle.stitch` — the read-side stitched store
  that serves cold mmap segments before the spill boundary, tier
  history before the demotion boundary and the raw tail after it
  through one `TimeSeriesStore`-shaped view
"""

from opentsdb_tpu.lifecycle.policy import LifecyclePolicy, PolicySet
from opentsdb_tpu.lifecycle.manager import LifecycleManager

__all__ = ["LifecyclePolicy", "PolicySet", "LifecycleManager"]
