"""The lifecycle manager: background sweeper + compactor.

One sweep, per metric with a policy (:mod:`.policy`):

1. **retention** — points older than the TTL are purged from the raw
   store AND every rollup tier/preagg store (the reference delegates
   this to HBase table TTLs, SURVEY.md §5.4).
2. **age-based demotion** — raw points older than the demotion
   boundary are folded into the configured rollup tiers by the
   existing tiled rollup job (:func:`opentsdb_tpu.rollup.job.
   run_rollup_job`, restricted to the metric's series), then dropped
   from raw. The boundary aligns down to the coarsest demoted tier's
   interval so every demoted tier cell is complete; the query engine
   stitches tier history + raw tail transparently
   (:mod:`.stitch`). Boundary publication is ordered so no
   intermediate state double-counts: tiers are written first, the
   boundary moves second (stitched reads clip raw to the tail while
   the stale raw points still exist), the raw purge runs last.
3. **compaction** — swept series buffers are sorted/deduped/
   shrunk-to-fit with timestamps packed to int32 offsets where
   lossless (:meth:`opentsdb_tpu.core.store.SeriesBuffer.compact`),
   and fully-expired (ghost) series release their buffers.
4. **cold spill** — demoted tier history older than the per-metric
   ``spill_after`` horizon is written into mmap-backed columnar
   segment files (:mod:`opentsdb_tpu.coldstore`) and the spilled
   range is deleted from the in-RAM tier stores. Ordering mirrors
   demotion: the segment files are made durable first, the manifest
   (segment list + moved spill boundary) commits atomically second —
   from that moment stitched reads clip the RAM tier at the new
   boundary — and the RAM purge runs last, so a crash anywhere
   leaves either an invisible orphan file or clipped RAM duplicates
   that the next sweep's reconciliation purge removes; never a
   double-serve or a lost range. Segment writes run under the
   ``coldstore.write`` fault site: a failed spill leaves the RAM
   copies authoritative.

Retention (1) also covers histogram arenas (points past the TTL are
purged from the columnar arenas under the ``lifecycle.histogram``
fault site) and the cold store (whole segments whose range fully
expired are dropped).

Every sweep that removed or demoted data bumps the raw store's
``mutation_epoch`` (the PR-2 result cache and PR-3 streaming plans
rebuild instead of serving purged points) and — when a data dir is
configured — flushes a snapshot + truncates the WAL so replay can
never resurrect expired points (the WAL has no delete record type;
the snapshot IS the delete's durability).

Degradation follows the PR-1 idiom: the sweep runs under the
``lifecycle.sweep`` fault site (demotion additionally under
``lifecycle.demote``) and its own circuit breaker
(``tsd.lifecycle.breaker.*``); a failing sweep is counted, logged and
retried next interval — it can NEVER fail or block ingest/queries
(they only share per-buffer locks). Counters export via /api/stats
and /api/health; the ``POST /api/lifecycle/sweep`` admin endpoint
runs one sweep synchronously.

Demotion boundaries persist to ``<data_dir>/lifecycle.json`` so a
restarted TSD keeps stitching tier history + raw tail (without it, a
tier-eligible query after restart would serve tier-only and silently
drop the raw tail).

Known limitation (documented): a write BACKFILLED behind the demotion
boundary is never re-demoted (re-running the rollup job over a purged
range would *replace* complete tier cells with cells computed from
the backfill alone) and stitched reads do not see it; demotion sweeps
leave it alone (the raw purge starts at the fold window, never
before the previous boundary), so it stays visible to
``rollupUsage=ROLLUP_RAW`` queries until retention purges it. The
reference has the same shape: external rollup jobs do not re-run on
backfills either.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Any

import numpy as np

from opentsdb_tpu.lifecycle.policy import LifecyclePolicy, PolicySet
from opentsdb_tpu.lifecycle.stitch import StitchedStore
from opentsdb_tpu.utils.faults import CircuitBreaker

LOG = logging.getLogger("lifecycle")

# the four per-statistic tier stores one demoted tier interval spans
# (rollup/job.py ROLLUP_AGGS — avg derives as sum/count at query time)
_TIER_AGGS = ("sum", "count", "min", "max")


class LifecycleManager:
    """(see module docstring)"""

    def __init__(self, tsdb):
        self.tsdb = tsdb
        cfg = tsdb.config
        self.policies = PolicySet.from_config(cfg)
        self.interval_s = cfg.get_float("tsd.lifecycle.interval_s", 0.0)
        self.compact_enabled = cfg.get_bool("tsd.lifecycle.compact",
                                            True)
        self.pack_timestamps = cfg.get_bool(
            "tsd.lifecycle.pack_timestamps", True)
        self.flush_after_sweep = cfg.get_bool(
            "tsd.lifecycle.flush_after_sweep", True)
        threshold = cfg.get_int(
            "tsd.lifecycle.breaker.failure_threshold", 3)
        self.breaker = CircuitBreaker(
            "lifecycle.sweep", failure_threshold=threshold,
            reset_timeout_ms=cfg.get_float(
                "tsd.lifecycle.breaker.reset_timeout_ms", 60000.0)) \
            if threshold > 0 else None
        if self.breaker is not None:
            tsdb.stats.register(self.breaker)
        # cold-tier disk store (opentsdb_tpu/coldstore/): the manifest
        # lives next to lifecycle.json by default; tsd.coldstore.dir
        # overrides, tsd.coldstore.enable=false opts out. With no
        # directory at all there is nowhere to spill — the spill
        # mechanism stays off and everything else works as before.
        self.coldstore = None
        cold_dir = cfg.get_string("tsd.coldstore.dir", "")
        if not cold_dir and getattr(tsdb, "data_dir", ""):
            import os
            cold_dir = os.path.join(tsdb.data_dir, "coldstore")
        if cold_dir and cfg.get_bool("tsd.coldstore.enable", True):
            from opentsdb_tpu.coldstore import ColdStore
            cb_threshold = cfg.get_int(
                "tsd.coldstore.breaker.failure_threshold", 3)
            read_breaker = CircuitBreaker(
                "coldstore.read", failure_threshold=cb_threshold,
                reset_timeout_ms=cfg.get_float(
                    "tsd.coldstore.breaker.reset_timeout_ms",
                    60000.0)) if cb_threshold > 0 else None
            if read_breaker is not None:
                tsdb.stats.register(read_breaker)
            self.coldstore = ColdStore(
                cold_dir, faults=getattr(tsdb, "faults", None),
                uids=tsdb.uids, read_breaker=read_breaker)
        # merge-compaction threshold: a (metric, tier) holding MORE
        # than this many per-sweep segments gets them merged into one
        # on the next sweep (0 = off)
        self.cold_compact_segments = cfg.get_int(
            "tsd.coldstore.compact_segments", 0)
        # the fifth stat column: per-cell quantile sketches of demoted
        # raw data (opentsdb_tpu/sketch/). Demotion folds the raw
        # points it purges into cells here; the spill moves cells into
        # the cold segments' sketch blob column. tsd.sketch.enable
        # opts out — demotion then loses percentiles past the
        # boundary, exactly the pre-sketch behavior.
        self.sketches = None
        if cfg.get_bool("tsd.sketch.enable", True):
            from opentsdb_tpu.sketch.store import SketchTierStore
            sk_path = ""
            if getattr(tsdb, "data_dir", ""):
                import os
                sk_path = os.path.join(tsdb.data_dir, "sketches.bin")
            self.sketches = SketchTierStore(
                sk_path,
                alpha=cfg.get_float("tsd.sketch.alpha", 0.01),
                max_buckets=cfg.get_int("tsd.sketch.max_buckets",
                                        4096))
            self.sketches.load()
        # one sweep at a time (admin POST vs the interval thread)
        self._sweep_lock = threading.Lock()
        self._lock = threading.Lock()
        # metric_id -> demotion boundary (ms, exclusive): raw points
        # BEFORE it have been folded into tiers and purged from raw
        # tsdlint: allow[unbounded-growth] keyed by policied metric id
        # (metric cardinality; persisted in lifecycle.json); reclaimed
        # with the ROADMAP UID-reclamation item
        self._boundaries: dict[int, int] = {}
        # (metric_id, interval, agg) -> StitchedStore for the current
        # boundary; rebuilt when the boundary moves so cache keys
        # derived from instance_id can never alias across boundaries
        self._stitched: dict[tuple, StitchedStore] = {}
        # metrics whose FIRST demotion is in flight: the rollup job
        # has started writing tier cells (has_data flips true) but no
        # boundary exists yet, so tier selection would serve
        # tier-only results missing the raw tail — the engine pins
        # these metrics to raw until the boundary publishes. Stays
        # set across a failed first demotion (partial tier data with
        # no boundary must not be selected).
        self._first_demotions: set[int] = set()
        self._thread: threading.Thread | None = None
        self._stop = threading.Event()
        # counters
        self.sweeps = 0
        self.sweep_errors = 0
        self.points_purged = 0
        self.points_demoted = 0
        self.tier_points_written = 0
        self.bytes_reclaimed = 0
        self.series_released = 0
        self.points_spilled = 0
        self.histogram_points_purged = 0
        self.histogram_points_spilled = 0
        self.last_sweep_duration_ms = 0.0
        self.last_sweep_time = 0.0
        self.last_error = ""
        self._boundary_path = ""
        data_dir = getattr(tsdb, "data_dir", "")
        if data_dir:
            import os
            self._boundary_path = os.path.join(data_dir,
                                               "lifecycle.json")
            self._load_boundaries()

    # ------------------------------------------------------------------
    # scheduler surface (started by TSDServer, stopped on shutdown)
    # ------------------------------------------------------------------

    def start(self) -> None:
        if self.interval_s <= 0 or self._thread is not None:
            return
        self._stop.clear()
        t = threading.Thread(target=self._loop, name="tsd-lifecycle",
                             daemon=True)
        self._thread = t
        t.start()
        LOG.info("lifecycle sweeper running every %.0fs",
                 self.interval_s)

    def stop(self) -> None:
        self._stop.set()
        t, self._thread = self._thread, None
        if t is not None and t.is_alive():
            t.join(timeout=5)

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            self.sweep()  # never raises

    # ------------------------------------------------------------------
    # read-side surface (query engine / streaming registry)
    # ------------------------------------------------------------------

    def demote_boundary(self, metric_id: int) -> int:
        """The metric's demotion boundary (ms, exclusive), 0 = none."""
        with self._lock:
            return self._boundaries.get(metric_id, 0)

    def demote_boundary_for(self, metric: str) -> int:
        try:
            mid = self.tsdb.uids.metrics.get_id(metric)
        except LookupError:
            return 0
        return self.demote_boundary(mid)

    def first_demotion_in_flight(self, metric_id: int) -> bool:
        """True while this metric's tiers hold (possibly partial)
        demoted cells but no boundary exists yet — tier selection
        must stay on raw (which still has every point)."""
        with self._lock:
            return metric_id in self._first_demotions

    def has_cold(self, metric_id: int, interval: str) -> bool:
        """Whether cold segments exist for this (metric, tier) — tier
        selection must treat that as tier data even when the in-RAM
        tier store was fully spilled and emptied."""
        cold = self.coldstore
        if cold is None:
            return False
        try:
            name = self.tsdb.uids.metrics.get_name(metric_id)
        except LookupError:
            return False
        return cold.has_segments(name, interval)

    def stitched(self, metric_id: int, interval: str, agg: str,
                 tier_store) -> StitchedStore | None:
        """The cached stitched view for one (metric, tier, agg), or
        None when the metric has no demotion boundary (plain tier
        serving stays untouched). When the metric has cold segments
        for this tier, the view gets the cold third (spill boundary +
        mmap read view). The cache revalidates on ONE cold
        mutation-epoch read — every cold mutation (spill commit,
        quarantine, delete rewrite, boundary clamp) bumps it, so the
        full name-resolve + boundary lookup only runs when something
        actually changed."""
        cold = self.coldstore
        cold_epoch = cold.mutation_epoch if cold is not None else 0
        with self._lock:
            boundary = self._boundaries.get(metric_id, 0)
            if not boundary:
                return None
            key = (metric_id, interval, agg)
            st = self._stitched.get(key)
            if st is not None and st.boundary_ms == boundary \
                    and st.tier is tier_store \
                    and getattr(st, "cold_epoch", 0) == cold_epoch:
                return st
        spill_b = 0
        cold_view = None
        if cold is not None:
            try:
                name = self.tsdb.uids.metrics.get_name(metric_id)
            except LookupError:
                name = None
            if name is not None:
                spill_b = cold.spill_boundary(name)
                if spill_b and cold.has_segments(name, interval):
                    cold_view = cold.stat_view(name, interval, agg,
                                               self.tsdb.store)
                else:
                    spill_b = 0
        with self._lock:
            boundary = self._boundaries.get(metric_id, 0)
            if not boundary:
                return None
            st = StitchedStore(self.tsdb.store, tier_store,
                               metric_id, boundary, agg,
                               cold=cold_view,
                               spill_boundary_ms=spill_b,
                               cold_store=cold)
            st.cold_epoch = cold_epoch
            self._stitched[key] = st
            return st

    # ------------------------------------------------------------------
    # the sweep
    # ------------------------------------------------------------------

    def sweep(self, now_ms: int | None = None) -> dict[str, Any]:
        """Run one full sweep; returns a report. Never raises — a
        failure is counted, trips the breaker, and the serve path is
        untouched (this is maintenance, not the request path)."""
        if not self._sweep_lock.acquire(blocking=False):
            return {"skipped": "sweep already running"}
        t0 = time.monotonic()
        report: dict[str, Any] = {
            "purged": 0, "demoted": 0, "tierPointsWritten": 0,
            "bytesReclaimed": 0, "seriesReleased": 0, "metrics": 0,
            "spilled": 0, "histogramPurged": 0,
            "histogramSpilled": 0, "coldCompacted": 0,
        }
        # every sweep is a background trace root (the coldstore spill
        # records its own child span), so maintenance time shows up
        # at /api/trace alongside the requests it competes with
        from opentsdb_tpu.obs import trace as trace_mod
        tracer = getattr(self.tsdb, "tracer", None)
        tctx = tracer.start_background("lifecycle.sweep") \
            if tracer is not None and tracer.enabled else None
        try:
            if self.breaker is not None and not self.breaker.allow():
                report["skipped"] = "breaker open"
                return report
            try:
                with trace_mod.use(tctx):
                    self._sweep_inner(
                        int(now_ms if now_ms is not None
                            else time.time() * 1000), report)
            except Exception as exc:  # noqa: BLE001 - degrade loudly
                self.sweep_errors += 1
                self.last_error = f"{type(exc).__name__}: {exc}"
                if self.breaker is not None:
                    self.breaker.record_failure()
                if tctx is not None:
                    tctx.set_error(exc)
                LOG.warning("lifecycle sweep failed (%s); ingest and "
                            "queries are unaffected", self.last_error)
                report["error"] = self.last_error
                return report
            if self.breaker is not None:
                self.breaker.record_success()
            return report
        finally:
            self.sweeps += 1
            self.last_sweep_time = time.time()
            self.last_sweep_duration_ms = \
                (time.monotonic() - t0) * 1e3
            report["durationMs"] = round(self.last_sweep_duration_ms,
                                         1)
            if tctx is not None:
                if report.get("skipped"):
                    # a breaker-open no-op sweep each interval is not
                    # worth a retained trace — it would churn real
                    # request traces out of the ring (same rule as
                    # the zero-progress spool-replay probe)
                    tctx.sampled = False
                tctx.tag(purged=report.get("purged", 0),
                         demoted=report.get("demoted", 0),
                         spilled=report.get("spilled", 0))
                tracer.finish(tctx)
            self._sweep_lock.release()

    def _sweep_inner(self, now_ms: int, report: dict) -> None:
        t = self.tsdb
        faults = getattr(t, "faults", None)
        if faults is not None:
            faults.check("lifecycle.sweep")
        store = t.store
        changed = False
        uids = t.uids
        # the work list covers every metric ANY store knows: a metric
        # written only through the rollup API (external jobs, no raw
        # series) still needs its tier retention applied
        mids = set(store.metric_ids())
        if t.rollup_store is not None:
            rs = t.rollup_store
            with rs._tiers_lock:
                tier_stores = list(rs._tiers.values())
            tier_stores.append(rs.preagg_store())
            for ts_store in tier_stores:
                mids.update(ts_store.metric_ids())
        # histogram-only metrics need their arena TTL applied too
        with t._histogram_lock:
            mids.update(t._histogram_arenas.keys())
        name_of = {}
        for mid in mids:
            try:
                name_of[mid] = uids.metrics.get_name(mid)
            except LookupError:
                continue  # orphan metric id: fsck's problem
        work = self.policies.metrics_with_policies(name_of.values())
        by_name = {v: k for k, v in name_of.items()}
        for metric, pol in work:
            mid = by_name[metric]
            sids = np.asarray(store.series_ids_for_metric(mid),
                              dtype=np.int64)
            report["metrics"] += 1
            if pol.retention_ms:
                changed |= self._retention(mid, metric, sids, pol,
                                           now_ms, report)
            if pol.demote_after_ms and t.rollup_store is not None:
                changed |= self._demote(mid, metric, sids, pol,
                                        now_ms, report)
            if pol.spill_after_ms:
                from opentsdb_tpu.obs.trace import trace_span
                with trace_span("coldstore.spill", metric=metric):
                    if t.rollup_store is not None:
                        changed |= self._spill(mid, metric, pol,
                                               now_ms, report)
                    changed |= self._spill_histograms(
                        mid, metric, pol, now_ms, report)
            # merge-compaction of accumulated per-sweep cold segments
            # (runs under coldstore.write via the store, so an armed
            # fault degrades it like a failed spill — loud, harmless)
            if self.cold_compact_segments > 0 and \
                    self.coldstore is not None:
                merged = self.coldstore.compact_segments(
                    metric, self.cold_compact_segments)
                if merged:
                    report["coldCompacted"] += merged
                    changed = True
            # pack only COLD buffers (newest point behind the
            # metric's lifecycle horizon): packing a live tail just
            # buys an unpack copy on the next append
            horizon = now_ms - (pol.demote_after_ms
                                or pol.retention_ms)
            changed |= self._release_and_compact(sids, horizon,
                                                 report)
        if changed:
            # belt over the per-op epoch bumps: one extra bump per
            # sweep guarantees every read-side cache (result cache,
            # grid/prep pools, streaming plans) rebuilds rather than
            # serving purged points
            store.mutation_epoch += 1
            if self.flush_after_sweep and getattr(t, "data_dir", ""):
                # the WAL has no delete records: the snapshot (+ WAL
                # truncation inside flush) is what makes the purge
                # durable — without it, replay-on-restart would
                # resurrect expired points
                t.flush()

    def _tier_interval_ms(self, interval: str) -> int:
        """Tier interval string -> ms span (0 when unknown): the cold
        trim keeps cells whose aggregation window spans the cutoff,
        same rule as the RAM tier purge below."""
        try:
            return self.tsdb.rollup_config.get_interval(
                interval).interval_ms
        except ValueError:
            return 0

    def _retention(self, mid: int, metric: str, sids: np.ndarray,
                   pol: LifecyclePolicy, now_ms: int,
                   report: dict) -> bool:
        cutoff = now_ms - pol.retention_ms
        if cutoff <= 0:
            return False
        t = self.tsdb
        store = t.store
        purged = store.delete_range(sids, 1, cutoff - 1)
        # histogram arenas share the metric's TTL (ROADMAP item);
        # own fault site so a broken arena purge is observable —
        # the sweep's never-raise contract keeps ingest unaffected
        faults = getattr(t, "faults", None)
        if faults is not None:
            faults.check("lifecycle.histogram")
        hist_purged = t.purge_histograms_before(mid, cutoff)
        if hist_purged:
            self.histogram_points_purged += hist_purged
            report["histogramPurged"] += hist_purged
        # sketch cells share the metric's TTL (cell-window rule, like
        # the tier purge below); a dropped cell re-persists at once so
        # a restart cannot resurrect expired percentile history
        if self.sketches is not None and \
                self.sketches.delete_before(metric, cutoff):
            self.sketches.save()
        # cold segments are retention-managed too: whole-expired
        # segments drop cheaply (end_ms < cutoff matches the inclusive
        # raw purge of [1, cutoff-1]), then still-live segments
        # STRADDLING the cutoff get their expired prefix trimmed off
        # through the delete-rewrite path — without the trim a single
        # long-lived segment pins its whole range on disk until its
        # newest cell expires
        if self.coldstore is not None:
            purged += self.coldstore.drop_segments_before(
                metric, cutoff, self._tier_interval_ms)
            purged += self.coldstore.trim_segments_before(
                metric, cutoff, self._tier_interval_ms)
        rs = self.tsdb.rollup_store
        if rs is not None:
            config = self.tsdb.rollup_config
            tiers: list[tuple] = [(rs.preagg_store(), 0)]
            with rs._tiers_lock:
                items = list(rs._tiers.items())
            for (interval, _agg), ts_store in items:
                try:
                    iv_ms = config.get_interval(interval).interval_ms
                except ValueError:
                    iv_ms = 0
                tiers.append((ts_store, iv_ms))
            for ts_store, iv_ms in tiers:
                tsids = ts_store.series_ids_for_metric(mid)
                if len(tsids) == 0:
                    continue
                # a tier cell stamped T aggregates [T, T+iv): purge
                # only cells whose WHOLE window expired (T+iv <=
                # cutoff), or unexpired aggregated history would be
                # lost with its cell
                end = cutoff - 1 - iv_ms
                if end >= 1:
                    purged += ts_store.delete_range(tsids, 1, end)
        if purged:
            self.points_purged += purged
            report["purged"] += purged
        return purged > 0 or hist_purged > 0

    def _demote(self, mid: int, metric: str, sids: np.ndarray,
                pol: LifecyclePolicy, now_ms: int,
                report: dict) -> bool:
        t = self.tsdb
        config = t.rollup_config
        tiers = [config.get_interval(iv) for iv in pol.demote_tiers] \
            if pol.demote_tiers else list(config.intervals)
        if not tiers:
            return False
        coarse_ms = max(iv.interval_ms for iv in tiers)
        target = now_ms - pol.demote_after_ms
        boundary = target - target % coarse_ms
        prev = self.demote_boundary(mid)
        if boundary <= prev:
            return False
        counts = t.store.count_range(sids, 1, boundary - 1)
        old_sids = sids[counts > 0]
        total_old = int(counts.sum())
        if total_old == 0:
            # nothing raw to fold: leave the boundary where it is —
            # publishing a boundary no demotion backs would flip
            # externally-rolled-up metrics from plain tier serving to
            # a stitched view whose tier half is clipped for no reason
            return False
        faults = getattr(t, "faults", None)
        if faults is not None:
            faults.check("lifecycle.demote")
        start_ms = self._oldest_ts(t.store, old_sids, max(prev, 1))
        if prev == 0:
            # first demotion: tier cells are about to appear with no
            # boundary to stitch against — pin tier selection to raw
            # until the boundary publishes (cleared only on success;
            # a failed first demotion leaves partial tier data that
            # must keep losing tier selection)
            with self._lock:
                self._first_demotions.add(mid)
        from opentsdb_tpu.rollup.job import run_rollup_job
        written = run_rollup_job(
            t, start_ms, boundary - 1,
            intervals=[iv.interval for iv in tiers],
            series_ids=old_sids)
        wrote = sum(written.values())
        self.tier_points_written += wrote
        report["tierPointsWritten"] += wrote
        # fifth stat: fold the SAME raw window into per-cell quantile
        # sketches (cells at the finest demote tier) BEFORE the
        # boundary publishes and the raw purge runs — a raise here
        # aborts the demotion with raw intact, same as a rollup
        # failure. The sidecar save lands before the purge too
        # (durable-first, like the spill's manifest ordering).
        if self.sketches is not None:
            from opentsdb_tpu.obs.trace import trace_span
            with trace_span("sketch.fold", metric=metric):
                self._fold_sketches(mid, metric, old_sids, tiers,
                                    start_ms, boundary, faults)
        # tiers hold the history now: move the boundary BEFORE the raw
        # purge so stitched reads clip raw to the tail (no double
        # count while the stale raw points still exist), THEN purge.
        # The purge starts at the FOLD window, never before the
        # previous boundary: points backfilled behind it were not
        # re-folded, so deleting them would lose data the tiers never
        # received (they age out via retention instead).
        self._publish_boundary(mid, boundary)
        with self._lock:
            self._first_demotions.discard(mid)
        dropped = t.store.delete_range(old_sids, start_ms,
                                       boundary - 1)
        self.points_demoted += dropped
        report["demoted"] += dropped
        LOG.info("demoted %d raw points of %s into %s (boundary %d)",
                 dropped, metric,
                 "/".join(iv.interval for iv in tiers), boundary)
        return True

    def _fold_sketches(self, mid: int, metric: str,
                       old_sids: np.ndarray, tiers, start_ms: int,
                       boundary: int, faults) -> None:
        """Fold the demoting raw window into the sketch tier: one
        vectorized pass over the materialized batch, cells at the
        finest demote-tier interval keyed by the series' tag NAMES
        (restart-stable, and the identity the cold segment's series
        table uses)."""
        t = self.tsdb
        batch = t.store.materialize(old_sids, start_ms, boundary - 1)
        if not batch.num_points:
            return
        from opentsdb_tpu.ops import sketch_fold
        fine_ms = min(iv.interval_ms for iv in tiers)
        folded = sketch_fold.fold_series_cells(
            batch.series_idx, batch.ts_ms, batch.values, fine_ms,
            self.sketches.alpha, self.sketches.max_buckets,
            faults=faults)
        uids = t.uids
        names_of: dict[int, tuple | None] = {}
        items = []
        for (si, cell_ts), sk in folded.items():
            if si not in names_of:
                rec = t.store.series(int(batch.series_ids[si]))
                try:
                    names_of[si] = tuple(sorted(
                        (uids.tag_names.get_name(k),
                         uids.tag_values.get_name(v))
                        for k, v in rec.tags))
                except LookupError:
                    names_of[si] = None  # unresolvable: skip
            names = names_of[si]
            if names is not None:
                items.append((names, cell_ts, sk))
        if items:
            self.sketches.merge_cells(metric, fine_ms, items)
            self.sketches.save()

    def _spill(self, mid: int, metric: str, pol: LifecyclePolicy,
               now_ms: int, report: dict) -> bool:
        """Mechanism 4: spill demoted tier history older than the
        spill horizon into cold segment files, then release the RAM
        (see module docstring for the crash ordering)."""
        cold = self.coldstore
        t = self.tsdb
        if cold is None:
            return False
        boundary = self.demote_boundary(mid)
        if not boundary:
            return False  # only demoted history spills
        config = t.rollup_config
        tiers = [config.get_interval(iv) for iv in pol.demote_tiers] \
            if pol.demote_tiers else list(config.intervals)
        if not tiers:
            return False
        prev = cold.spill_boundary(metric)
        changed = False
        if prev:
            # reconciliation: RAM duplicates of already-spilled ranges
            # (crash between manifest commit and tier purge, or WAL
            # replay resurrection) are invisible to stitched reads —
            # the clip at the spill boundary hides them — but still
            # hold RAM; purge them here so restarts converge. Only
            # ranges COVERED by cold segments are purged: a tier
            # newly added to the policy has un-spilled history below
            # the boundary that must not be deleted without a disk
            # copy.
            changed = self._purge_spilled_ranges(mid, metric,
                                                 tiers) > 0
        coarse_ms = max(iv.interval_ms for iv in tiers)
        target = now_ms - pol.spill_after_ms
        new_b = min(target - target % coarse_ms, boundary)
        if new_b <= prev:
            return changed
        entries: list[dict] = []
        spilled_rows = 0
        for iv in tiers:
            # a tier with no cold segments yet (first spill, or newly
            # added to the policy after spills began) spills its WHOLE
            # history below the new boundary — starting at prev would
            # strand its older cells behind the clip, unservable and
            # never written to disk
            lo = max(prev, 1) \
                if cold.has_segments(metric, iv.interval) else 1
            data = self._gather_tier_history(mid, iv.interval, lo,
                                             new_b - 1)
            if data is None:
                continue
            series_entries, ts_ms, cols = data
            # the sketch column rides the tier whose grid matches the
            # sketch cells (the finest demote tier at fold time) —
            # rows without a folded cell get a zero-length blob
            sketch = self._gather_sketch_column(metric, iv,
                                                series_entries, ts_ms)
            try:
                # runs under the coldstore.write fault site; a raise
                # here aborts the spill with the RAM copies intact
                # (nothing committed to the manifest yet) and is
                # counted by the sweep's error handler
                entry = cold.write_segment(metric, iv.interval,
                                           series_entries, ts_ms,
                                           cols, sketch=sketch)
            except Exception:
                cold.spill_errors += 1
                raise
            entries.append(entry)
            spilled_rows += len(ts_ms)
        if not entries:
            # nothing cold yet: leave the boundary so a later backlog
            # spill isn't clipped away by an empty range
            return changed
        # segments are durable: publish them + the moved boundary in
        # one atomic manifest write, THEN release the RAM copies
        cold.commit_spill(metric, new_b, entries)
        with self._lock:
            for key in [k for k in self._stitched if k[0] == mid]:
                del self._stitched[key]
        # release the RAM copies — only of ranges the (now committed)
        # segments actually cover
        self._purge_spilled_ranges(mid, metric, tiers)
        # the purge only drops the points: the tier buffers keep their
        # grown capacity until compacted — and releasing that RAM is
        # the whole point of the spill
        self._compact_tiers(mid, tiers, new_b, report)
        # the segments (and their sketch column) are committed: the
        # RAM sketch cells below the boundary are now disk duplicates
        if self.sketches is not None:
            if self.sketches.delete_before(metric, new_b,
                                           spilled=True):
                self.sketches.save()
        self.points_spilled += spilled_rows
        report["spilled"] += spilled_rows
        LOG.info("spilled %d tier points of %s to cold segments "
                 "(spill boundary %d)", spilled_rows, metric, new_b)
        return True

    def _gather_sketch_column(self, metric: str, iv,
                              series_entries: list, ts_ms
                              ) -> tuple | None:
        """The spill payload's fifth column: per-row serialized
        sketches aligned with the gathered tier rows, or None when
        this tier's grid is not the sketch cell grid (coarser tiers
        spill stat columns only) or no cells exist. Rows demoted
        before sketches were enabled blob as zero-length (readers
        treat those cells as percentile-less)."""
        if self.sketches is None:
            return None
        if iv.interval_ms != self.sketches.cell_ms(metric):
            return None
        blobs: list[bytes] = []
        have = 0
        for e in series_entries:
            names = tuple(tuple(p) for p in e["tags"])
            lo = int(e["off"])
            for ts in np.asarray(ts_ms[lo:lo + int(e["cnt"])]) \
                    .tolist():
                blob = self.sketches.blob_for(metric, names, int(ts))
                blobs.append(blob or b"")
                have += blob is not None
        if not have:
            return None
        off = np.zeros(len(blobs) + 1, dtype=np.int64)
        np.cumsum([len(b) for b in blobs], out=off[1:])
        return off, b"".join(blobs)

    def _spill_histograms(self, mid: int, metric: str,
                          pol: LifecyclePolicy, now_ms: int,
                          report: dict) -> bool:
        """Mechanism 4b: spill live histogram arena rows older than
        the spill horizon into cold sketch segments (interval label
        ``"histogram"``), then purge them from the arena. Each row's
        bucket counts fold at bucket midpoints — the same convention
        the arena engine's percentile extraction and the cluster
        partials path use — so a cold percentile read answers within
        alpha of what the live arena would have said. Crash ordering
        matches the tier spill: segment durable, manifest + boundary
        committed atomically, THEN the RAM rows purge."""
        cold = self.coldstore
        t = self.tsdb
        if cold is None or self.sketches is None:
            return False
        with t._histogram_lock:
            arena = t._histogram_arenas.get(mid)
            snaps = [(s.bounds, *s.snapshot())
                     for s in arena.groups.values()] if arena else []
        if not snaps:
            return False
        prev = cold.spill_boundary(metric)
        target = now_ms - pol.spill_after_ms
        rs = t.rollup_store
        if rs is not None:
            # a mixed metric (tier history + arenas) shares ONE spill
            # boundary: never advance it past the demote boundary, or
            # stitched tier reads would clip un-spilled tier RAM
            with rs._tiers_lock:
                tier_stores = list(rs._tiers.values())
            if any(len(st.series_ids_for_metric(mid))
                   for st in tier_stores):
                target = min(target, self.demote_boundary(mid))
        if target <= prev:
            return False
        # first spill of this metric's arenas takes the WHOLE history
        # below the boundary (tier-spill rule); afterwards rows below
        # prev are crash-window disk duplicates the purge clears
        lo = max(prev, 1) \
            if cold.has_segments(metric, "histogram") else 1
        cfg = t.config
        alpha = cfg.get_float("tsd.sketch.alpha", 0.01)
        max_buckets = cfg.get_int("tsd.sketch.max_buckets", 4096)
        from opentsdb_tpu.sketch.ddsketch import DDSketch
        uids = t.uids
        store = t.histogram_store
        names_of: dict[int, tuple | None] = {}
        rows_of: dict[tuple, list] = {}
        for bounds, ts_a, sid_a, rows in snaps:
            b = np.asarray(bounds, dtype=np.float64)
            mids = (b[:-1] + b[1:]) / 2.0
            m = (ts_a >= lo) & (ts_a < target)
            if not m.any():
                continue
            for ts, sid, counts in zip(ts_a[m].tolist(),
                                       sid_a[m].tolist(),
                                       np.asarray(rows)[m]):
                if sid not in names_of:
                    try:
                        rec = store.series(int(sid))
                        names_of[sid] = tuple(sorted(
                            (uids.tag_names.get_name(k),
                             uids.tag_values.get_name(v))
                            for k, v in rec.tags))
                    except LookupError:
                        names_of[sid] = None
                names = names_of[sid]
                if names is None:
                    continue  # unresolvable identity stays in RAM
                counts = np.asarray(counts, dtype=np.float64)
                total = float(counts.sum())
                if total <= 0:
                    continue
                sk = DDSketch(alpha)
                sk.add_weighted(mids, counts)
                if max_buckets:
                    sk.collapse(max_buckets)
                nz = np.nonzero(counts)[0]
                rows_of.setdefault(names, []).append(
                    (int(ts), total, float((mids * counts).sum()),
                     float(mids[nz[0]]), float(mids[nz[-1]]),
                     sk.to_bytes()))
        if not rows_of:
            return False
        series_entries: list[dict] = []
        ts_parts: list[int] = []
        cols: dict[str, list] = {s: [] for s in
                                 ("sum", "count", "min", "max")}
        blobs: list[bytes] = []
        off = 0
        for names in sorted(rows_of):
            srows = sorted(rows_of[names])
            series_entries.append({"tags": [list(p) for p in names],
                                   "off": off, "cnt": len(srows)})
            off += len(srows)
            for ts, cnt, vsum, vmin, vmax, blob in srows:
                ts_parts.append(ts)
                cols["count"].append(cnt)
                cols["sum"].append(vsum)
                cols["min"].append(vmin)
                cols["max"].append(vmax)
                blobs.append(blob)
        ts_ms = np.asarray(ts_parts, dtype=np.int64)
        col_arr = {s: np.asarray(v, dtype=np.float64)
                   for s, v in cols.items()}
        sk_off = np.zeros(len(blobs) + 1, dtype=np.int64)
        np.cumsum([len(bb) for bb in blobs], out=sk_off[1:])
        try:
            entry = cold.write_segment(
                metric, "histogram", series_entries, ts_ms, col_arr,
                sketch=(sk_off, b"".join(blobs)))
        except Exception:
            cold.spill_errors += 1
            raise
        cold.commit_spill(metric, target, [entry])
        t.purge_histograms_before(mid, target)
        self.histogram_points_spilled += len(ts_ms)
        report["histogramSpilled"] += len(ts_ms)
        LOG.info("spilled %d histogram rows of %s to a cold sketch "
                 "segment (spill boundary %d)", len(ts_ms), metric,
                 target)
        return True

    def _purge_spilled_ranges(self, mid: int, metric: str,
                              tiers) -> int:
        """Delete one metric's in-RAM tier cells wherever cold
        segments cover them: per tier interval, [1, max segment
        end_ms]. Strictly safe — only RAM that is duplicated on disk
        is ever released (a tier backfilled after its spill loses the
        backfill here, the same documented divergence as writes
        backfilled behind the demotion boundary: the clip already
        hides them). Returns points removed."""
        cold = self.coldstore
        rs = self.tsdb.rollup_store
        purged = 0
        for iv in tiers:
            handles = cold._handles(metric, iv.interval)
            if not handles:
                continue
            hi = max(h.entry["end_ms"] for h in handles)
            for agg in _TIER_AGGS:
                st = rs._tiers.get((iv.interval, agg))
                if st is None:
                    continue
                tsids = st.series_ids_for_metric(mid)
                if len(tsids):
                    purged += st.delete_range(tsids, 1, hi)
        return purged

    def _compact_tiers(self, mid: int, tiers, spill_b: int,
                       report: dict) -> None:
        """Shrink-to-fit the spilled metric's tier buffers (capacity
        survives delete_range). ``pack_before_ms=spill_b`` keeps the
        still-growing tier band unpacked — the next demotion appends
        to it."""
        if not self.compact_enabled:
            return
        rs = self.tsdb.rollup_store
        for iv in tiers:
            for agg in _TIER_AGGS:
                st = rs._tiers.get((iv.interval, agg))
                if st is None or not hasattr(st, "compact_series"):
                    continue
                tsids = st.series_ids_for_metric(mid)
                if len(tsids) == 0:
                    continue
                reclaimed, released = st.compact_series(
                    tsids, pack_ts=self.pack_timestamps,
                    pack_before_ms=spill_b)
                if reclaimed:
                    self.bytes_reclaimed += reclaimed
                    report["bytesReclaimed"] += reclaimed
                if released:
                    self.series_released += released
                    report["seriesReleased"] += released

    def _gather_tier_history(self, mid: int, interval: str,
                             start_ms: int, end_ms: int):
        """Columnar spill payload for one (metric, tier interval):
        ``(series_entries, ts_ms, {stat: column})`` over
        [start_ms, end_ms], or None when the window holds nothing.
        Per series, the timestamp set is the union across the four
        stat stores (the rollup job writes all four for every cell,
        but external writers may not) with missing stats as NaN —
        which every read path already skips."""
        t = self.tsdb
        rs = t.rollup_store
        uids = t.uids
        stores = {agg: st for agg in _TIER_AGGS
                  if (st := rs._tiers.get((interval, agg)))
                  is not None}
        if not stores:
            return None
        per_series: dict[tuple, dict] = {}
        for agg, st in stores.items():
            for sid in np.asarray(
                    st.series_ids_for_metric(mid)).tolist():
                rec = st.series(int(sid))
                ts, vals = rec.buffer.slice_range(start_ms, end_ms)
                if len(ts):
                    per_series.setdefault(rec.tags, {})[agg] = \
                        (ts.copy(), vals.copy())
        if not per_series:
            return None
        series_entries: list[dict] = []
        ts_parts: list[np.ndarray] = []
        col_parts: dict[str, list] = {agg: [] for agg in _TIER_AGGS}
        off = 0
        for tags in sorted(per_series):
            try:
                names = sorted((uids.tag_names.get_name(k),
                                uids.tag_values.get_name(v))
                               for k, v in tags)
            except LookupError:
                continue  # unresolvable identity stays in RAM
            stats = per_series[tags]
            ts_u = stats[next(iter(stats))][0]
            for agg, (ts_a, _vals) in stats.items():
                if not np.array_equal(ts_a, ts_u):
                    ts_u = np.union1d(ts_u, ts_a)
            n = len(ts_u)
            for agg in _TIER_AGGS:
                col = np.full(n, np.nan)
                if agg in stats:
                    ts_a, vals_a = stats[agg]
                    col[np.searchsorted(ts_u, ts_a)] = vals_a
                col_parts[agg].append(col)
            ts_parts.append(ts_u)
            series_entries.append({"tags": [list(p) for p in names],
                                   "off": off, "cnt": n})
            off += n
        if not series_entries:
            return None
        return (series_entries, np.concatenate(ts_parts),
                {agg: np.concatenate(col_parts[agg])
                 for agg in _TIER_AGGS})

    def _publish_boundary(self, mid: int, boundary: int) -> None:
        with self._lock:
            self._boundaries[mid] = boundary
            # stale stitched views die here; the next query mints
            # fresh instances (new instance_id => new cache keys)
            for key in [k for k in self._stitched if k[0] == mid]:
                del self._stitched[key]
        self._save_boundaries()

    def _save_boundaries(self) -> None:
        """Persist metric-name -> boundary so restarts keep stitching
        (names, not ids: they are stable across UID reloads).
        Best-effort — a failed save means one sweep's boundary move is
        re-derived by the next sweep, never a serve-path error."""
        if not self._boundary_path:
            return
        import json
        with self._lock:
            boundaries = dict(self._boundaries)
        doc: dict[str, int] = {}
        for mid, b in boundaries.items():
            try:
                doc[self.tsdb.uids.metrics.get_name(mid)] = b
            except LookupError:
                continue
        try:
            from opentsdb_tpu.core.persist import _atomic_write
            _atomic_write(self._boundary_path,
                          json.dumps({"boundaries": doc}).encode())
        except OSError as exc:  # pragma: no cover - disk trouble
            LOG.warning("could not persist lifecycle boundaries: %s",
                        exc)

    def _load_boundaries(self) -> None:
        import json
        import os
        if not os.path.isfile(self._boundary_path):
            return
        try:
            with open(self._boundary_path, encoding="utf-8") as fh:
                doc = json.load(fh)
        except (OSError, ValueError) as exc:
            LOG.warning("could not load lifecycle boundaries: %s", exc)
            return
        for name, b in (doc.get("boundaries") or {}).items():
            try:
                mid = self.tsdb.uids.metrics.get_id(name)
            except LookupError:
                continue
            self._boundaries[mid] = int(b)

    @staticmethod
    def _oldest_ts(store, sids: np.ndarray, floor_ms: int) -> int:
        """Oldest live timestamp across ``sids`` (but never below
        ``floor_ms``) — bounds the rollup job's window so its bucket
        grid doesn't span from epoch zero. Buffer-view walk on the
        memory backend; the native arena materializes per series
        (sweeps are background work)."""
        oldest = None
        for sid in sids.tolist():
            ts, _ = store.series(int(sid)).buffer.view()
            if len(ts):
                first = int(ts[0])
                if oldest is None or first < oldest:
                    oldest = first
        if oldest is None:
            return floor_ms
        return max(oldest, floor_ms)

    def _release_and_compact(self, sids: np.ndarray, horizon_ms: int,
                             report: dict) -> bool:
        store = self.tsdb.store
        if not self.compact_enabled or \
                not hasattr(store, "compact_series"):
            return False
        reclaimed, released = store.compact_series(
            sids, pack_ts=self.pack_timestamps,
            pack_before_ms=horizon_ms)
        if reclaimed:
            self.bytes_reclaimed += reclaimed
            report["bytesReclaimed"] += reclaimed
        if released:
            self.series_released += released
            report["seriesReleased"] += released
        return False  # compaction changes no visible data

    # ------------------------------------------------------------------
    # fsck surface
    # ------------------------------------------------------------------

    def scan_expired(self, now_ms: int | None = None
                     ) -> dict[str, int]:
        """Expired-but-present raw point counts per metric (read-only;
        fsck reports these and ``--fix`` purges them through
        :meth:`sweep` so epochs/WAL stay consistent)."""
        now_ms = int(now_ms if now_ms is not None
                     else time.time() * 1000)
        t = self.tsdb
        out: dict[str, int] = {}
        store = t.store
        for mid in store.metric_ids():
            try:
                metric = t.uids.metrics.get_name(mid)
            except LookupError:
                continue
            pol = self.policies.for_metric(metric)
            if pol is None or not pol.retention_ms:
                continue
            cutoff = now_ms - pol.retention_ms
            if cutoff <= 0:
                continue
            sids = store.series_ids_for_metric(mid)
            if len(sids) == 0:
                continue
            n = int(store.count_range(sids, 1, cutoff - 1).sum())
            if n:
                out[metric] = n
        return out

    # ------------------------------------------------------------------
    # admin / observability
    # ------------------------------------------------------------------

    def update_policies(self, obj: dict) -> None:
        """``POST /api/lifecycle`` body: wholesale policy replacement
        (``{"policies": [...]}``; validation failures leave the table
        untouched)."""
        from opentsdb_tpu.query.model import BadRequestError
        if not isinstance(obj, dict):
            raise BadRequestError("body must be an object")
        raw = obj.get("policies")
        if not isinstance(raw, list):
            raise BadRequestError("body needs a 'policies' array")
        self.policies.replace(
            [LifecyclePolicy.from_json(p) for p in raw])

    def describe(self) -> dict[str, Any]:
        with self._lock:
            boundaries = dict(self._boundaries)
        names = {}
        for mid, b in boundaries.items():
            try:
                names[self.tsdb.uids.metrics.get_name(mid)] = b
            except LookupError:
                names[f"#{mid}"] = b
        doc = {
            "enabled": True,
            "intervalS": self.interval_s,
            "policies": self.policies.to_json(),
            "demoteBoundaries": names,
            "counters": self._counters(),
        }
        if self.breaker is not None:
            doc["breaker"] = self.breaker.health_info()
        if self.coldstore is not None:
            doc["coldstore"] = self.coldstore.health_info()
            doc["spillBoundaries"] = self.coldstore.spill_boundaries()
        if self.sketches is not None:
            doc["sketches"] = self.sketches.describe()
        return doc

    def _counters(self) -> dict[str, Any]:
        return {
            "sweeps": self.sweeps,
            "sweepErrors": self.sweep_errors,
            "pointsPurged": self.points_purged,
            "pointsDemoted": self.points_demoted,
            "tierPointsWritten": self.tier_points_written,
            "bytesReclaimed": self.bytes_reclaimed,
            "seriesReleased": self.series_released,
            "pointsSpilled": self.points_spilled,
            "histogramPointsPurged": self.histogram_points_purged,
            "histogramPointsSpilled": self.histogram_points_spilled,
            "lastSweepDurationMs": round(self.last_sweep_duration_ms,
                                         1),
            "lastSweepTime": int(self.last_sweep_time),
            "lastError": self.last_error,
        }

    def health_info(self) -> dict[str, Any]:
        doc = {"enabled": True, **self._counters()}
        if self.breaker is not None:
            doc["breaker"] = self.breaker.health_info()
        if self.coldstore is not None:
            doc["coldstore"] = self.coldstore.health_info()
        return doc

    def collect_stats(self, collector) -> None:
        collector.record("lifecycle.sweeps", self.sweeps)
        collector.record("lifecycle.sweep_errors", self.sweep_errors)
        collector.record("lifecycle.points.purged", self.points_purged)
        collector.record("lifecycle.points.demoted",
                         self.points_demoted)
        collector.record("lifecycle.tier_points.written",
                         self.tier_points_written)
        collector.record("lifecycle.bytes.reclaimed",
                         self.bytes_reclaimed)
        collector.record("lifecycle.series.released",
                         self.series_released)
        collector.record("lifecycle.points.spilled",
                         self.points_spilled)
        collector.record("lifecycle.histogram_points.purged",
                         self.histogram_points_purged)
        collector.record("lifecycle.histogram_points.spilled",
                         self.histogram_points_spilled)
        collector.record("lifecycle.sweep.duration_ms",
                         self.last_sweep_duration_ms)
        if self.sketches is not None:
            collector.record("sketch.points.folded",
                             self.sketches.points_folded)
            collector.record("sketch.cells.folded",
                             self.sketches.cells_folded)
            collector.record("sketch.cells.spilled",
                             self.sketches.cells_spilled)
        if self.coldstore is not None:
            self.coldstore.collect_stats(collector)
