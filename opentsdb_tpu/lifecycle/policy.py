"""Per-metric data-lifecycle policies.

A policy says how long a metric's raw points live (``retention``),
when raw history is demoted into the configured rollup tiers
(``demote_after``, ``demote_tiers``) and when demoted tier history is
spilled from RAM into the mmap-backed cold store (``spill_after``,
:mod:`opentsdb_tpu.coldstore`). Policies come from two places, lowest
precedence first:

1. config keys (read once at manager construction)::

       tsd.lifecycle.retention       = 90d        # default policy
       tsd.lifecycle.demote_after    = 6h
       tsd.lifecycle.demote_tiers    = 1m,1h
       tsd.lifecycle.spill_after     = 7d
       tsd.lifecycle.policy.sys.cpu.retention    = 30d   # per metric
       tsd.lifecycle.policy.sys.cpu.demote_after = 1h

2. the ``POST /api/lifecycle`` admin endpoint (runtime updates)::

       {"policies": [{"metric": "*", "retention": "90d"},
                     {"metric": "sys.cpu", "demoteAfter": "1h",
                      "demoteTiers": ["1m"], "spillAfter": "2d"}]}

The metric name ``*`` is the default policy; an exact metric name
overrides it wholesale (no field-level merging — the resolved policy is
the most specific one, like the reference resolves per-table HBase
TTLs).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any, Iterable

from opentsdb_tpu.query.model import BadRequestError
from opentsdb_tpu.utils import datetime_util

_KNOBS = ("retention", "demote_after", "demote_tiers",
          "spill_after")


def _parse_duration(value: str, what: str) -> int:
    """Duration string -> ms; '' / '0' mean disabled (0)."""
    value = (value or "").strip()
    if value in ("", "0"):
        return 0
    try:
        return datetime_util.parse_duration_ms(value)
    except ValueError as exc:
        raise BadRequestError(f"invalid {what} duration "
                              f"{value!r}: {exc}") from None


@dataclass(frozen=True)
class LifecyclePolicy:
    """One metric's lifecycle rules (``metric == '*'`` is the
    default). ``retention_ms == 0`` keeps points forever;
    ``demote_after_ms == 0`` never demotes; empty ``demote_tiers``
    means every configured rollup tier; ``spill_after_ms == 0`` keeps
    demoted history in RAM forever."""

    metric: str
    retention_ms: int = 0
    demote_after_ms: int = 0
    demote_tiers: tuple[str, ...] = field(default_factory=tuple)
    spill_after_ms: int = 0

    @property
    def active(self) -> bool:
        return self.retention_ms > 0 or self.demote_after_ms > 0

    def validate(self) -> None:
        if self.retention_ms and self.demote_after_ms \
                and self.demote_after_ms >= self.retention_ms:
            raise BadRequestError(
                f"policy for {self.metric!r}: demote_after "
                f"({self.demote_after_ms} ms) must be shorter than "
                f"retention ({self.retention_ms} ms) — demoted history "
                "would be purged the moment it lands in the tiers")
        if self.spill_after_ms:
            if not self.demote_after_ms:
                raise BadRequestError(
                    f"policy for {self.metric!r}: spill_after needs "
                    "demote_after — only demoted tier history spills "
                    "to the cold store")
            if self.spill_after_ms <= self.demote_after_ms:
                raise BadRequestError(
                    f"policy for {self.metric!r}: spill_after "
                    f"({self.spill_after_ms} ms) must be longer than "
                    f"demote_after ({self.demote_after_ms} ms) — "
                    "history demotes to RAM tiers first, spills to "
                    "disk later")
            if self.retention_ms and \
                    self.spill_after_ms >= self.retention_ms:
                raise BadRequestError(
                    f"policy for {self.metric!r}: spill_after "
                    f"({self.spill_after_ms} ms) must be shorter than "
                    f"retention ({self.retention_ms} ms) — spilled "
                    "history would be dropped the moment it lands on "
                    "disk")

    def to_json(self) -> dict[str, Any]:
        return {
            "metric": self.metric,
            "retention": _fmt_ms(self.retention_ms),
            "demoteAfter": _fmt_ms(self.demote_after_ms),
            "demoteTiers": list(self.demote_tiers),
            "spillAfter": _fmt_ms(self.spill_after_ms),
        }

    @classmethod
    def from_json(cls, obj: dict) -> "LifecyclePolicy":
        if not isinstance(obj, dict):
            raise BadRequestError("each policy must be an object")
        metric = obj.get("metric")
        if not metric or not isinstance(metric, str):
            raise BadRequestError(
                "policy needs a 'metric' name ('*' for the default)")
        tiers = obj.get("demoteTiers") or obj.get("demote_tiers") or []
        if isinstance(tiers, str):
            tiers = [t for t in tiers.split(",") if t.strip()]
        if not isinstance(tiers, list) or not all(
                isinstance(t, str) for t in tiers):
            raise BadRequestError("demoteTiers must be a list of "
                                  "interval strings")
        pol = cls(
            metric=metric,
            retention_ms=_parse_duration(
                str(obj.get("retention") or ""), "retention"),
            demote_after_ms=_parse_duration(
                str(obj.get("demoteAfter")
                    or obj.get("demote_after") or ""), "demoteAfter"),
            demote_tiers=tuple(t.strip() for t in tiers),
            spill_after_ms=_parse_duration(
                str(obj.get("spillAfter")
                    or obj.get("spill_after") or ""), "spillAfter"),
        )
        pol.validate()
        return pol


def _fmt_ms(ms: int) -> str:
    """Milliseconds back to the tersest duration string ('' = off)."""
    if ms <= 0:
        return ""
    for unit, size in (("d", 86400_000), ("h", 3600_000),
                       ("m", 60_000), ("s", 1000)):
        if ms % size == 0:
            return f"{ms // size}{unit}"
    return f"{ms}ms"


class PolicySet:
    """Thread-safe resolved policy table: exact metric name wins over
    the ``*`` default."""

    def __init__(self, policies: Iterable[LifecyclePolicy] = ()):
        self._lock = threading.Lock()
        self._by_metric: dict[str, LifecyclePolicy] = {}
        for pol in policies:
            pol.validate()
            self._by_metric[pol.metric] = pol

    @classmethod
    def from_config(cls, config) -> "PolicySet":
        """Build from ``tsd.lifecycle.*`` keys. Metric names may
        themselves contain dots, so per-metric keys parse by known
        suffix: ``tsd.lifecycle.policy.<metric>.<knob>``."""
        prefix = "tsd.lifecycle.policy."
        fields: dict[str, dict[str, str]] = {}
        for key, val in config:
            if not key.startswith(prefix):
                continue
            rest = key[len(prefix):]
            for knob in _KNOBS:
                if rest.endswith("." + knob):
                    metric = rest[:-len(knob) - 1]
                    if metric:
                        fields.setdefault(metric, {})[knob] = val
                    break
        policies = []
        default_fields = {
            "retention": config.get_string("tsd.lifecycle.retention",
                                           ""),
            "demote_after": config.get_string(
                "tsd.lifecycle.demote_after", ""),
            "demote_tiers": config.get_string(
                "tsd.lifecycle.demote_tiers", ""),
            "spill_after": config.get_string(
                "tsd.lifecycle.spill_after", ""),
        }
        if any(v.strip() for v in default_fields.values()):
            policies.append(_policy_from_fields("*", default_fields))
        for metric, fld in sorted(fields.items()):
            policies.append(_policy_from_fields(metric, fld))
        return cls(policies)

    def replace(self, policies: Iterable[LifecyclePolicy]) -> None:
        """Atomic wholesale replacement (the admin POST body is the
        full policy table — idempotent, no partial merges to reason
        about)."""
        table = {}
        for pol in policies:
            pol.validate()
            table[pol.metric] = pol
        with self._lock:
            self._by_metric = table

    def for_metric(self, metric: str) -> LifecyclePolicy | None:
        with self._lock:
            pol = self._by_metric.get(metric)
            if pol is None:
                pol = self._by_metric.get("*")
            return pol if pol is not None and pol.active else None

    def metrics_with_policies(self, all_metrics: Iterable[str]
                              ) -> list[tuple[str, LifecyclePolicy]]:
        """Resolve the policy of every metric that HAS one — the
        sweep's work list. With a ``*`` default, that is every metric
        in ``all_metrics``."""
        out = []
        for m in all_metrics:
            pol = self.for_metric(m)
            if pol is not None:
                out.append((m, pol))
        return out

    def to_json(self) -> list[dict]:
        with self._lock:
            return [self._by_metric[k].to_json()
                    for k in sorted(self._by_metric)]


def _policy_from_fields(metric: str, fld: dict[str, str]
                        ) -> LifecyclePolicy:
    tiers = tuple(t.strip() for t in
                  (fld.get("demote_tiers") or "").split(",")
                  if t.strip())
    pol = LifecyclePolicy(
        metric=metric,
        retention_ms=_parse_duration(fld.get("retention", ""),
                                     "retention"),
        demote_after_ms=_parse_duration(fld.get("demote_after", ""),
                                        "demote_after"),
        demote_tiers=tiers,
        spill_after_ms=_parse_duration(fld.get("spill_after", ""),
                                       "spill_after"))
    pol.validate()
    return pol
