"""Stitched read view: cold disk segments + rollup-tier history + raw
tail across the spill and demotion boundaries.

After age-based demotion, raw points older than a metric's demotion
boundary exist only in the rollup tiers; the raw store keeps the tail.
After a cold spill, the oldest tier history lives in mmap-backed disk
segments (:mod:`opentsdb_tpu.coldstore`) instead of RAM. A query
spanning the boundaries must read all three — this module exposes one
``TimeSeriesStore``-shaped object the query engine can select exactly
like a plain tier store:

- series identity (sids, metric index, tag matrices, shards) is the
  RAW store's: every live series has a raw record even when all its
  points were demoted, so filters/group-by/result assembly are
  unchanged;
- reads split at ``spill_boundary_ms`` and ``boundary_ms``: cold
  segments serve ``[start, spill)``, the in-RAM tier serves
  ``[spill, boundary)`` (raw sids mapped to tier sids by (metric,
  tags) identity; the cold view does its own identity mapping) and
  the raw store serves ``[boundary, end]``;
- ``bucket_reduce`` combines the two halves channel-wise so the
  engine's grid path (and the avg sum/count division) is
  value-identical to an undemoted store for decomposable
  downsample functions — each query bucket receives tier cells whose
  source points it fully contains plus raw tail points, and sums of
  sums / mins of mins / counts of counts are exact (the same
  decomposition ``rollup/job.py`` writes). Queries whose start is not
  tier-aligned inherit the pre-existing rollup divergence (a tier
  cell is attributed to the bucket holding its edge).

``tail_stat`` names the statistic the tier's point VALUES carry, so
the raw tail contributes the matching channel: a ``count`` tier's
stitched view materializes tail points with value 1.0 (summing them
counts them) and adds raw bucket counts into the sums channel of
``bucket_reduce``.

Versioning: ``points_written`` / ``mutation_epoch`` are the sums of
all stitched parts, so every read-side cache (result cache, device
grid cache, prepared-batch pools) invalidates on a write or sweep to
any of them. Instances are cached per (metric, tier, boundary) by the
lifecycle manager — a moved boundary mints a fresh ``instance_id``,
orphaning stale cache entries instead of aliasing them.

Degradation: the cold third runs behind :meth:`StitchedStore._cold`
— a failed cold read (corrupt segment, disk error, armed
``coldstore.read`` fault) or an open cold read breaker degrades that
request to tier/raw serving (partial history, 200) instead of a 500,
and bumps the cold ``mutation_epoch`` so the degraded result is
already stale for every later result-cache lookup. ``delete_range``
deliberately does NOT degrade — a delete that silently skipped the
cold rows would report success for points still on disk.
"""

from __future__ import annotations

import threading

import numpy as np

from opentsdb_tpu.core.store import (PaddedBatch, PointBatch,
                                     STORE_INSTANCE_IDS,
                                     padded_from_batch)

_TAIL_STATS = ("sum", "count", "min", "max")


def guarded_sketch_rows(cold, metric: str, start_ms: int, end_ms: int
                        ) -> tuple[list, bool]:
    """Cold sketch-column read behind the same degradation guard as
    :meth:`StitchedStore._cold`: an open read breaker or a failed read
    degrades to ``([], False)`` — the caller serves the remaining
    zones (partial history, 200) and the epoch bump in the notes makes
    the partial result stale for later cache lookups."""
    breaker = getattr(cold, "read_breaker", None)
    if breaker is not None and not breaker.allow():
        cold.note_degraded_serve()
        return [], False
    try:
        rows = cold.sketch_rows(metric, None, start_ms, end_ms)
    except Exception as exc:  # noqa: BLE001 - degrade, never 500
        if breaker is not None:
            breaker.record_failure()
        cold.note_read_error(exc)
        return [], False
    if breaker is not None:
        breaker.record_success()
    return rows, True


def sketch_zone_read(tsdb, metric: str, metric_id: int,
                     start_ms: int, end_ms: int):
    """The sketch twin of the stitched three-way read: per-series
    quantile sketches split at the spill and demotion boundaries.

    Returns ``(items, raw_rng, cold_ok)``:

    - ``items``: ``(tags_names_tuple, cell_ts, DDSketch)`` rows from
      the cold segments' sketch column (``cell_ts < spill_b``) and the
      in-RAM sketch tier (``spill_b <= cell_ts < demote_b``). The zone
      split is by cell timestamp, so a RAM cell whose spilled disk
      duplicate still lingers (crash reconciliation) is counted once.
    - ``raw_rng``: the ``[demote_b, end]`` raw-tail window the caller
      folds itself (None when the window ends before the boundary).
    - ``cold_ok``: False when the cold zone degraded (breaker open,
      read error, undecodable blob) — partial history, never a 500.
    """
    from opentsdb_tpu.sketch.ddsketch import DDSketch, SketchError
    lc = tsdb.lifecycle
    sketches = getattr(lc, "sketches", None) if lc is not None \
        else None
    demote_b = lc.demote_boundary(metric_id) if lc is not None else 0
    cold = getattr(lc, "coldstore", None) if lc is not None else None
    spill_b = 0
    if cold is not None and sketches is not None and demote_b:
        # same clamp as StitchedStore: cold never serves past the
        # demotion boundary
        spill_b = min(cold.spill_boundary(metric), demote_b)
    items: list[tuple[tuple, int, DDSketch]] = []
    cold_ok = True
    if spill_b and start_ms < spill_b:
        rows, cold_ok = guarded_sketch_rows(
            cold, metric, start_ms, min(end_ms, spill_b - 1))
        for tags, cts, blob in rows:
            try:
                items.append((tags, cts, DDSketch.from_bytes(blob)))
            except (SketchError, ValueError):
                cold_ok = False  # corrupt blob: serve the rest
    if sketches is not None and demote_b:
        lo = max(start_ms, spill_b)
        hi = min(end_ms, demote_b - 1)
        if lo <= hi:
            items.extend(sketches.cells(metric, lo, hi))
    raw_lo = max(start_ms, demote_b)
    raw_rng = (raw_lo, end_ms) if raw_lo <= end_ms else None
    return items, raw_rng, cold_ok


class StitchedStore:
    """(see module docstring)"""

    fault_site = "store"

    def __init__(self, raw_store, tier_store, metric_id: int,
                 boundary_ms: int, tail_stat: str, cold=None,
                 spill_boundary_ms: int = 0, cold_store=None):
        if tail_stat not in _TAIL_STATS:
            raise ValueError(f"bad tail_stat {tail_stat!r}")
        self.instance_id = next(STORE_INSTANCE_IDS)
        self.raw = raw_store
        self.tier = tier_store
        self.metric_id = metric_id
        self.boundary_ms = int(boundary_ms)
        self.tail_stat = tail_stat
        # cold third (ColdStatView) + its owning ColdStore (breaker,
        # degradation counters). The spill boundary is CLAMPED to the
        # demotion boundary: a manifest claiming more would make cold
        # and raw both serve [boundary, spill) — the one invariant a
        # corrupt manifest must not break (fsck reports the excess).
        self.cold = cold
        self.cold_store = cold_store
        self.spill_boundary_ms = min(int(spill_boundary_ms),
                                     self.boundary_ms) \
            if cold is not None else 0
        self.num_shards = raw_store.num_shards
        self._map_lock = threading.Lock()
        # raw sid -> tier sid map, versioned by both stores' series
        # counts (identity indexes are append-only)
        self._sid_map: tuple | None = None

    # -- identity surface: the RAW store's ---------------------------------

    @property
    def fault_injector(self):
        return self.raw.fault_injector

    @property
    def points_written(self) -> int:
        n = self.raw.points_written + self.tier.points_written
        if self.cold is not None:
            n += self.cold.points_written
        return n

    @property
    def mutation_epoch(self) -> int:
        e = (getattr(self.raw, "mutation_epoch", 0)
             + getattr(self.tier, "mutation_epoch", 0))
        if self.cold is not None:
            e += self.cold.mutation_epoch
        return e

    def series(self, series_id: int):
        return self.raw.series(series_id)

    def num_series(self) -> int:
        return self.raw.num_series()

    def metric_ids(self):
        return self.raw.metric_ids()

    def metric_index(self, metric_id: int):
        return self.raw.metric_index(metric_id)

    def series_ids_for_metric(self, metric_id: int) -> np.ndarray:
        return self.raw.series_ids_for_metric(metric_id)

    def shards_of(self, series_ids):
        return self.raw.shards_of(series_ids)

    def total_points(self) -> int:
        n = self.raw.total_points() + self.tier.total_points()
        if self.cold is not None:
            n += self.cold.total_points()
        return n

    # -- sid mapping --------------------------------------------------------

    def _tier_sids(self, sids: np.ndarray) -> np.ndarray:
        """Tier sid per raw sid (-1 when the tier never saw the
        series). Cached over the full metric, invalidated by either
        index growing."""
        from opentsdb_tpu.query.engine import _match_series_by_tags
        key = (self.raw.num_series(), self.tier.num_series())
        with self._map_lock:
            cached = self._sid_map
            if cached is None or cached[0] != key:
                all_raw = self.raw.series_ids_for_metric(self.metric_id)
                mapped = _match_series_by_tags(
                    self.raw, self.tier, all_raw, self.metric_id)
                order = np.argsort(all_raw, kind="stable")
                cached = (key, all_raw[order], mapped[order])
                self._sid_map = cached
        _, sorted_raw, sorted_tier = cached
        sids = np.asarray(sids, dtype=np.int64)
        if len(sorted_raw) == 0:
            return np.full(len(sids), -1, dtype=np.int64)
        pos = np.searchsorted(sorted_raw, sids)
        pos_c = np.minimum(pos, len(sorted_raw) - 1)
        hit = sorted_raw[pos_c] == sids
        return np.where(hit, sorted_tier[pos_c], -1)

    def _split(self, start_ms: int, end_ms: int):
        """(cold_range | None, tier_range | None, raw_range | None)
        for one request. With no cold third the spill boundary is 0
        and the cold range is always None."""
        b = self.boundary_ms
        s = self.spill_boundary_ms
        cold_rng = (start_ms, min(end_ms, s - 1)) \
            if s and start_ms < s else None
        tier_lo = max(start_ms, s)
        tier_rng = (tier_lo, min(end_ms, b - 1)) \
            if tier_lo < b and tier_lo <= end_ms else None
        raw_rng = (max(start_ms, b), end_ms) if end_ms >= b else None
        return cold_rng, tier_rng, raw_rng

    def _cold(self, fn_name: str, *args):
        """Run one cold read behind the degradation guard: an open
        read breaker skips the call, a failure records it — either way
        the caller serves tier/raw only (None return). The cold
        mutation epoch bump inside the notes makes the partial result
        stale for every later result-cache lookup."""
        cs = self.cold_store
        breaker = getattr(cs, "read_breaker", None) \
            if cs is not None else None
        if breaker is not None and not breaker.allow():
            cs.note_degraded_serve()
            return None
        try:
            out = getattr(self.cold, fn_name)(*args)
        except Exception as exc:  # noqa: BLE001 - degrade, never 500
            if breaker is not None:
                breaker.record_failure()
            if cs is not None:
                cs.note_read_error(exc)
            return None
        if breaker is not None:
            breaker.record_success()
        return out

    # -- reads --------------------------------------------------------------

    def count_range(self, series_ids, start_ms: int,
                    end_ms: int) -> np.ndarray:
        sids = np.asarray(series_ids, dtype=np.int64)
        out = np.zeros(len(sids), dtype=np.int64)
        cold_rng, tier_rng, raw_rng = self._split(start_ms, end_ms)
        if raw_rng is not None:
            out += self.raw.count_range(sids, *raw_rng)
        if tier_rng is not None:
            tsids = self._tier_sids(sids)
            present = np.nonzero(tsids >= 0)[0]
            if len(present):
                out[present] += self.tier.count_range(
                    tsids[present], *tier_rng)
        if cold_rng is not None:
            got = self._cold("count_range", sids, *cold_rng)
            if got is not None:
                out += got
        return out

    def bucket_reduce(self, series_ids, start_ms: int, end_ms: int,
                      t0: int, interval_ms: int, nbuckets: int,
                      want_minmax: bool = False):
        """Channel-wise combination of the cold segments, the tier
        part and the raw tail over ONE shared bucket grid (same
        t0/interval/nbuckets for all, so a bucket straddling a
        boundary sums exactly)."""
        sids = np.asarray(series_ids, dtype=np.int64)
        s = len(sids)
        sums = np.zeros((s, nbuckets))
        cnts = np.zeros((s, nbuckets))
        mins = maxs = None
        if want_minmax:
            mins = np.full((s, nbuckets), np.inf)
            maxs = np.full((s, nbuckets), -np.inf)
        cold_rng, tier_rng, raw_rng = self._split(start_ms, end_ms)
        if cold_rng is not None:
            # cold cells carry the same statistic as the tier's (the
            # segment stores all four stat columns; this view reads
            # the matching one), so they combine exactly like tier
            # cells — no tail_stat conversion
            got = self._cold("bucket_reduce", sids, cold_rng[0],
                             cold_rng[1], t0, interval_ms, nbuckets,
                             want_minmax)
            if got is not None:
                c_sums, c_cnts, c_mins, c_maxs = got
                sums += c_sums
                cnts += c_cnts
                if want_minmax:
                    np.minimum(mins, c_mins, out=mins)
                    np.maximum(maxs, c_maxs, out=maxs)
        if tier_rng is not None:
            tsids = self._tier_sids(sids)
            present = np.nonzero(tsids >= 0)[0]
            if len(present):
                t_sums, t_cnts, t_mins, t_maxs = \
                    self.tier.bucket_reduce(
                        tsids[present], tier_rng[0], tier_rng[1], t0,
                        interval_ms, nbuckets, want_minmax=want_minmax)
                sums[present] += t_sums
                cnts[present] += t_cnts
                if want_minmax:
                    # fancy indexing copies: assign back, don't `out=`
                    mins[present] = np.minimum(mins[present], t_mins)
                    maxs[present] = np.maximum(maxs[present], t_maxs)
        if raw_rng is not None:
            r_sums, r_cnts, r_mins, r_maxs = self.raw.bucket_reduce(
                sids, raw_rng[0], raw_rng[1], t0, interval_ms,
                nbuckets, want_minmax=want_minmax)
            # the raw tail contributes the statistic this tier's point
            # values carry: counting a count-tier's tail means adding
            # raw bucket COUNTS into the sums channel
            sums += r_cnts if self.tail_stat == "count" else r_sums
            cnts += r_cnts
            if want_minmax:
                np.minimum(mins, r_mins, out=mins)
                np.maximum(maxs, r_maxs, out=maxs)
        return sums, cnts, mins, maxs

    def materialize(self, series_ids, start_ms: int,
                    end_ms: int) -> PointBatch:
        """Flat merged batch: per series, cold points (oldest) precede
        tier points precede raw tail points, so per-series time order
        is preserved by one stable sort on the series index."""
        sids = np.asarray(series_ids, dtype=np.int64)
        parts: list[tuple[np.ndarray, np.ndarray, np.ndarray]] = []
        cold_rng, tier_rng, raw_rng = self._split(start_ms, end_ms)
        if cold_rng is not None:
            cb = self._cold("materialize", sids, *cold_rng)
            if cb is not None and cb.num_points:
                parts.append((cb.series_idx, cb.ts_ms, cb.values))
        if tier_rng is not None:
            tsids = self._tier_sids(sids)
            present = np.nonzero(tsids >= 0)[0]
            if len(present):
                tb = self.tier.materialize(tsids[present], *tier_rng)
                parts.append((present[tb.series_idx].astype(np.int32),
                              tb.ts_ms, tb.values))
        if raw_rng is not None:
            rb = self.raw.materialize(sids, *raw_rng)
            vals = rb.values
            if self.tail_stat == "count" and len(vals):
                # summing the tail must COUNT it (count-tier cells
                # hold counts; see module docstring)
                vals = np.ones_like(vals)
            parts.append((rb.series_idx, rb.ts_ms, vals))
        if not parts:
            return PointBatch(sids,
                              np.empty(0, dtype=np.int32),
                              np.empty(0, dtype=np.int64),
                              np.empty(0, dtype=np.float64))
        series_idx = np.concatenate([p[0] for p in parts])
        ts_ms = np.concatenate([p[1] for p in parts])
        values = np.concatenate([p[2] for p in parts])
        order = np.argsort(series_idx, kind="stable")
        return PointBatch(sids, series_idx[order], ts_ms[order],
                          values[order])

    def materialize_padded(self, series_ids, start_ms: int,
                           end_ms: int) -> PaddedBatch:
        return padded_from_batch(
            self.materialize(series_ids, start_ms, end_ms))

    # -- destructive ops (delete=true queries) ------------------------------

    def delete_range(self, series_ids, start_ms: int,
                     end_ms: int) -> int:
        """delete=true over a stitched view removes the range from ALL
        parts (cold segments, tier history, raw tail). The cold delete
        is NOT behind the degradation guard: silently skipping it
        would report success for points still on disk."""
        sids = np.asarray(series_ids, dtype=np.int64)
        deleted = self.raw.delete_range(sids, start_ms, end_ms)
        tsids = self._tier_sids(sids)
        present = tsids[tsids >= 0]
        if len(present):
            deleted += self.tier.delete_range(present, start_ms,
                                              end_ms)
        if self.cold is not None and self.spill_boundary_ms \
                and start_ms < self.spill_boundary_ms:
            deleted += self.cold.delete_range(sids, start_ms, end_ms)
        return deleted
