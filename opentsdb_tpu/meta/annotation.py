"""Annotations: notes overlaid on series or global
(ref: ``src/meta/Annotation.java:79``).

The reference stores annotations as 0x01-prefixed cells in the data table
next to the datapoints; here they live in a per-TSUID sorted dict. Global
annotations use the empty TSUID, like the reference's empty-row-key
convention.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass, field
from typing import Any

GLOBAL_TSUID = ""


@dataclass
class Annotation:
    """(ref: Annotation.java:79) Times in seconds like the JSON API."""
    tsuid: str = GLOBAL_TSUID
    start_time: int = 0
    end_time: int = 0
    description: str = ""
    notes: str = ""
    custom: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "tsuid": self.tsuid,
            "description": self.description,
            "notes": self.notes,
            "custom": self.custom or None,
            "startTime": self.start_time,
            "endTime": self.end_time,
        }
        if not self.tsuid:
            out.pop("tsuid")
        return out

    @classmethod
    def from_json(cls, obj: dict[str, Any]) -> "Annotation":
        def ts(key: str) -> int:
            v = obj.get(key, 0)
            if v is None:
                return 0
            if isinstance(v, bool) or not isinstance(v, (int, float,
                                                         str)):
                # surfaces as a 400 through the router's ValueError
                # mapping instead of a TypeError 500
                raise ValueError(f"{key} must be a unix timestamp")
            return int(v)

        return cls(
            tsuid=str(obj.get("tsuid", "") or ""),
            start_time=ts("startTime"),
            end_time=ts("endTime"),
            description=str(obj.get("description", "") or ""),
            notes=str(obj.get("notes", "") or ""),
            custom=obj.get("custom") or {},
        )


class AnnotationStore:
    """CRUD + range scan (ref: Annotation.java:156-266 + getGlobalAnnotations)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # tsuid -> {start_time_sec: Annotation}
        # tsdlint: allow[unbounded-growth] outer keys are series
        # cardinality; entries evict through the inner-dict pops in
        # delete()/delete_range (the alias the static pass can't see)
        self._by_tsuid: dict[str, dict[int, Annotation]] = {}
        # set by TSDB when a write-ahead log is active; edits are
        # crash-durable like the reference's HBase-backed annotations
        self.wal = None
        # bumped on every mutation: annotations ride inside query
        # results, so the serve-path result cache folds this into its
        # invalidation version (TSDB.serve_version)
        self.version = 0

    def store(self, note: Annotation, _wal: bool = True) -> Annotation:
        if not note.start_time:
            raise ValueError("missing or invalid start time")
        with self._lock:
            self._by_tsuid.setdefault(note.tsuid, {})[note.start_time] = note
            self.version += 1
        if _wal and self.wal is not None:
            self.wal.log_annotation(note.to_json() | {"tsuid": note.tsuid})
            self.wal.sync()
        return note

    def has_any(self) -> bool:
        """Cheap emptiness probe so the query path can skip per-series
        annotation scans entirely (1M-member groups otherwise pay a
        tsuid-encode + lookup per series)."""
        with self._lock:
            return any(self._by_tsuid.values())

    def get(self, tsuid: str, start_time: int) -> Annotation | None:
        with self._lock:
            return self._by_tsuid.get(tsuid, {}).get(start_time)

    def delete(self, tsuid: str, start_time: int,
               _wal: bool = True) -> bool:
        with self._lock:
            d = self._by_tsuid.get(tsuid, {})
            removed = d.pop(start_time, None) is not None
            if removed:
                self.version += 1
        if removed and _wal and self.wal is not None:
            self.wal.log_annotation_delete(tsuid, start_time)
            self.wal.sync()
        return removed

    def delete_range(self, tsuids: list[str] | None, start_sec: int,
                     end_sec: int) -> int:
        """Bulk delete (ref: AnnotationRpc bulk delete). ``tsuids=None``
        means global annotations only, matching the reference's
        global-flag semantics."""
        count = 0
        removed: list[tuple[str, int]] = []
        with self._lock:
            keys = tsuids if tsuids is not None else [GLOBAL_TSUID]
            for tsuid in keys:
                d = self._by_tsuid.get(tsuid)
                if not d:
                    continue
                doomed = [t for t in d if start_sec <= t <= end_sec]
                for t in doomed:
                    del d[t]
                    removed.append((tsuid, t))
                count += len(doomed)
            if count:
                self.version += 1
        if removed and self.wal is not None:
            for tsuid, t in removed:
                self.wal.log_annotation_delete(tsuid, t)
            self.wal.sync()
        return count

    def global_range(self, start_sec: int, end_sec: int) -> list[Annotation]:
        return self.range(GLOBAL_TSUID, start_sec, end_sec)

    def range(self, tsuid: str, start_sec: int, end_sec: int
              ) -> list[Annotation]:
        with self._lock:
            d = self._by_tsuid.get(tsuid, {})
            return [a for t, a in sorted(d.items())
                    if start_sec <= t <= end_sec]
