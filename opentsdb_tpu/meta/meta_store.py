"""UIDMeta / TSMeta metadata documents
(ref: ``src/meta/UIDMeta.java:71``, ``src/meta/TSMeta.java:75``).

Created on first write when realtime-meta tracking is enabled (matching
``tsd.core.meta.enable_realtime_ts`` / ``enable_tsuid_tracking``), kept
in process dictionaries, and pushed to the search plugin when present.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any


@dataclass
class UIDMeta:
    """(ref: UIDMeta.java:71)"""
    uid: str = ""           # hex string form, like the JSON API
    type: str = ""          # METRIC | TAGK | TAGV
    name: str = ""
    display_name: str = ""
    description: str = ""
    notes: str = ""
    created: int = 0
    custom: dict[str, str] = field(default_factory=dict)

    def to_json(self) -> dict[str, Any]:
        return {
            "uid": self.uid, "type": self.type.upper(), "name": self.name,
            "displayName": self.display_name, "description": self.description,
            "notes": self.notes, "created": self.created,
            "custom": self.custom or None,
        }


@dataclass
class TSMeta:
    """(ref: TSMeta.java:75)"""
    tsuid: str = ""
    display_name: str = ""
    description: str = ""
    notes: str = ""
    created: int = 0
    custom: dict[str, str] = field(default_factory=dict)
    units: str = ""
    data_type: str = ""
    retention: int = 0
    max_value: float = float("nan")
    min_value: float = float("nan")
    last_received: int = 0
    total_dps: int = 0
    metric: UIDMeta | None = None
    tags: list[UIDMeta] = field(default_factory=list)

    def to_json(self) -> dict[str, Any]:
        out: dict[str, Any] = {
            "tsuid": self.tsuid, "displayName": self.display_name,
            "description": self.description, "notes": self.notes,
            "created": self.created, "custom": self.custom or None,
            "units": self.units, "dataType": self.data_type,
            "retention": self.retention,
            "lastReceived": self.last_received, "totalDatapoints": self.total_dps,
        }
        if self.metric:
            out["metric"] = self.metric.to_json()
        if self.tags:
            out["tags"] = [t.to_json() for t in self.tags]
        return out


class MetaStore:
    """Realtime TSMeta/UIDMeta tracking (ref: TSDB.java:1225-1245)."""

    def __init__(self, tsdb) -> None:
        self._tsdb = tsdb
        cfg = tsdb.config
        self.track_ts = (cfg.get_bool("tsd.core.meta.enable_realtime_ts")
                         or cfg.get_bool(
                             "tsd.core.meta.enable_tsuid_tracking"))
        self.track_uid = cfg.get_bool("tsd.core.meta.enable_realtime_uid")
        self._lock = threading.Lock()
        self.ts_meta: dict[str, TSMeta] = {}
        self.uid_meta: dict[tuple[str, str], UIDMeta] = {}
        self.ts_counters: dict[str, int] = {}

    def _check_fault(self) -> None:
        """``meta.store`` fault-injection site: every meta WRITE path
        runs it (realtime tracking + the HTTP sync edits). Ingest is
        insulated by the TSDB hook guard — an armed meta fault counts
        a hook error and the point write still acknowledges."""
        faults = getattr(self._tsdb, "faults", None)
        if faults is not None:
            faults.check("meta.store")

    def on_datapoint(self, metric_id: int, tag_ids, series_id: int,
                     count: int = 1) -> None:
        """Realtime TSMeta tracking; ``count`` lets the bulk write path
        account a whole per-series batch in one call. A newly-created
        TSMeta is also filed through the realtime tree processor when
        ``tsd.core.tree.enable_processing`` is set (ref:
        TSDB.processTSMetaThroughTrees :2033)."""
        if not self.track_ts:
            return
        self._check_fault()
        tsuid = self._tsdb.uids.tsuid(metric_id, tag_ids).hex().upper()
        now = int(time.time())
        created = False
        with self._lock:
            self.ts_counters[tsuid] = (self.ts_counters.get(tsuid, 0)
                                       + count)
            meta = self.ts_meta.get(tsuid)
            if meta is None:
                created = True
                meta = TSMeta(tsuid=tsuid, created=now)
                meta.metric = self._uid_meta_locked(
                    "metric", metric_id, now)
                for kid, vid in sorted(tag_ids):
                    meta.tags.append(self._uid_meta_locked("tagk", kid, now))
                    meta.tags.append(self._uid_meta_locked("tagv", vid, now))
                self.ts_meta[tsuid] = meta
                if self._tsdb.search_plugin is not None:
                    self._tsdb.search_plugin.index_ts_meta(meta)
            meta.last_received = now
            meta.total_dps = self.ts_counters[tsuid]
        if created and self._tsdb.config.get_bool(
                "tsd.core.tree.enable_processing"):
            # outside the meta lock (the tree manager has its own);
            # guarded so a tree failure can neither fail the write nor
            # unwind the meta update above
            from opentsdb_tpu.tree.tree import tree_manager
            mgr = tree_manager(self._tsdb)
            uids = self._tsdb.uids
            tags = {uids.tag_names.get_name(k):
                    uids.tag_values.get_name(v)
                    for k, v in sorted(tag_ids)}
            self._tsdb._run_hook(
                "tree.rt", mgr.process_series, tsuid,
                uids.metrics.get_name(metric_id), tags)

    def _uid_meta_locked(self, kind: str, uid_int: int,
                         now: int) -> UIDMeta:
        registry = self._tsdb.uids.by_kind(kind)
        key = (kind, registry.int_to_uid(uid_int).hex().upper())
        meta = self.uid_meta.get(key)
        if meta is None:
            meta = UIDMeta(uid=key[1],
                           type={"metric": "METRIC", "tagk": "TAGK",
                                 "tagv": "TAGV"}[kind],
                           name=registry.get_name(uid_int), created=now)
            self.uid_meta[key] = meta
            if self.track_uid and self._tsdb.search_plugin is not None:
                self._tsdb.search_plugin.index_uid_meta(meta)
        return meta

    # -- editing RPC surface (ref: UniqueIdRpc.java:179-226,314;
    # merge-on-POST / replace-on-PUT via syncToStorage's overwrite
    # flag, TSMeta.java:222 / UIDMeta CAS sync) ----------------------

    # JSON field -> attribute, the reference's editable field set
    _UID_FIELDS = {"displayName": "display_name",
                   "description": "description", "notes": "notes",
                   "custom": "custom"}
    _TS_FIELDS = {"displayName": "display_name",
                  "description": "description", "notes": "notes",
                  "custom": "custom", "units": "units",
                  "dataType": "data_type", "retention": "retention",
                  "max": "max_value", "min": "min_value"}

    @staticmethod
    def _apply_fields(meta, fields: dict, field_map: dict,
                      overwrite: bool) -> bool:
        """POST merges only the provided fields; PUT resets every
        editable field then applies the provided ones (ref:
        syncToStorage(overwrite)). Returns True when anything
        changed."""

        def same(a, b) -> bool:
            if isinstance(a, float) and isinstance(b, float):
                return a == b or (a != a and b != b)  # NaN == NaN here
            return a == b

        changed = False
        defaults = {"custom": {}, "retention": 0,
                    "max_value": float("nan"),
                    "min_value": float("nan")}
        for json_key, attr in field_map.items():
            if json_key in fields:
                val = fields[json_key]
                if val is None:
                    val = defaults.get(attr, "")
                if attr == "retention":
                    val = int(val)
                elif attr in ("max_value", "min_value"):
                    val = float(val)
                elif attr == "custom":
                    val = dict(val or {})
            elif overwrite:
                val = defaults.get(attr, "")
            else:
                continue
            if not same(getattr(meta, attr), val):
                setattr(meta, attr, val)
                changed = True
        return changed

    class NotModified(Exception):
        """Raised when a sync carries no actual change (ref: the 304
        NOT_MODIFIED reply on IllegalStateException)."""

    def sync_uid_meta(self, kind: str, uid_hex: str, fields: dict,
                      overwrite: bool) -> UIDMeta:
        """Merge (POST) or replace (PUT) a UIDMeta document. The UID
        must exist in the UID table; a missing doc starts from the
        skeleton (ref: UIDMeta.getUIDMeta default docs)."""
        uid_hex = uid_hex.upper()
        self._check_fault()
        registry = self._tsdb.uids.by_kind(kind)
        name = registry.get_name(bytes.fromhex(uid_hex))  # may raise
        with self._lock:
            key = (kind, uid_hex)
            meta = self.uid_meta.get(key)
            if meta is None:
                meta = UIDMeta(uid=uid_hex,
                               type={"metric": "METRIC",
                                     "tagk": "TAGK",
                                     "tagv": "TAGV"}[kind],
                               name=name, created=int(time.time()))
                created = True
            else:
                created = False
            changed = self._apply_fields(meta, fields,
                                         self._UID_FIELDS, overwrite)
            if not changed and not created:
                raise MetaStore.NotModified()
            self.uid_meta[key] = meta
        if self._tsdb.search_plugin is not None:
            self._tsdb.search_plugin.index_uid_meta(meta)
        return meta

    def delete_uid_meta(self, kind: str, uid_hex: str) -> None:
        with self._lock:
            meta = self.uid_meta.pop((kind, uid_hex.upper()), None)
        if meta is not None and self._tsdb.search_plugin is not None:
            self._tsdb.search_plugin.delete_uid_meta(meta)

    def sync_ts_meta(self, tsuid: str, fields: dict, overwrite: bool,
                     create: bool = False) -> TSMeta:
        """Merge/replace a TSMeta document; ``create`` materializes a
        new doc for a known-but-untracked timeseries (ref: the
        create=true counter bootstrap in UniqueIdRpc tsmeta POST)."""
        tsuid = tsuid.upper()
        self._check_fault()
        with self._lock:
            meta = self.ts_meta.get(tsuid)
            created = False
            if meta is None:
                if not create:
                    raise LookupError(
                        f"Could not find Timeseries meta data "
                        f"for {tsuid}")
                meta = TSMeta(tsuid=tsuid, created=int(time.time()))
                self.ts_counters.setdefault(tsuid, 0)
                created = True
            changed = self._apply_fields(meta, fields, self._TS_FIELDS,
                                         overwrite)
            if not changed and not created:
                raise MetaStore.NotModified()
            self.ts_meta[tsuid] = meta
        if self._tsdb.search_plugin is not None:
            self._tsdb.search_plugin.index_ts_meta(meta)
        return meta

    def delete_ts_meta(self, tsuid: str) -> None:
        with self._lock:
            meta = self.ts_meta.pop(tsuid.upper(), None)
        if meta is not None and self._tsdb.search_plugin is not None:
            self._tsdb.search_plugin.delete_ts_meta(meta.tsuid)

    def get_ts_meta(self, tsuid: str) -> TSMeta | None:
        with self._lock:
            return self.ts_meta.get(tsuid.upper())

    def get_uid_meta(self, kind: str, uid_hex: str) -> UIDMeta | None:
        with self._lock:
            return self.uid_meta.get((kind, uid_hex.upper()))

    def all_ts_meta(self) -> list[TSMeta]:
        with self._lock:
            return list(self.ts_meta.values())

    def purge(self) -> tuple[int, int]:
        """Remove every TSMeta/UIDMeta doc and counter
        (ref: src/tools/MetaPurge.java — the `uid metapurge` path).
        Returns (n_tsmeta, n_uidmeta) purged."""
        with self._lock:
            n_ts, n_uid = len(self.ts_meta), len(self.uid_meta)
            self.ts_meta.clear()
            self.uid_meta.clear()
            self.ts_counters.clear()
        return n_ts, n_uid
