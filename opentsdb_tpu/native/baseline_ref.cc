// Reference-architecture baseline: a faithful C++ replica of
// OpenTSDB's per-datapoint query hot loop, used to MEASURE the
// "single-TSD iterator chain" baseline that bench.py compares against
// (BASELINE.md; the image ships no JVM, so the Java path cannot run —
// a C++ replica with the same per-point virtual-dispatch architecture
// is an upper bound on the Java chain's throughput, i.e. GENEROUS to
// the reference).
//
// Architecture mirrored (semantics only, written from the documented
// behavior — see SURVEY.md §3.3):
//   per series: RowSeq iterator -> Downsampler (window aggregate per
//   time bucket, ref src/core/Downsampler.java:28) -> optional
//   RateSpan (dv/dt between successive points, ref RateSpan.java:21)
//   per group: AggregationIterator k-way timestamp-ordered merge with
//   linear interpolation at unaligned timestamps feeding
//   Aggregator.runDouble through a values-iterator virtual interface
//   (ref AggregationIterator.java:27-119, Aggregators.java:95-102).
// Everything is pull-based per datapoint through virtual calls, and
// single-threaded per query, exactly like the reference.
//
// Build: g++ -O2 -o baseline_ref baseline_ref.cc   (bench_baseline.py)
// Usage: baseline_ref S P B G rate reps
//   S series, P points/series, B downsample buckets, G groups,
//   rate 0/1, reps repetitions; prints seconds-per-run minimum.

#include <algorithm>
#include <chrono>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <limits>
#include <memory>
#include <random>
#include <vector>

struct DataPoint {
  int64_t ts;  // ms
  double val;
};

// ref: src/core/SeekableView.java:37 — the per-datapoint pull ABI
struct SeekableView {
  virtual bool hasNext() = 0;
  virtual DataPoint next() = 0;
  virtual ~SeekableView() = default;
};

// ref: src/core/RowSeq.java:527 — iterate one series' stored points
struct RowSeqView : SeekableView {
  const int64_t* ts;
  const double* vals;
  int n;
  int i = 0;
  RowSeqView(const int64_t* t, const double* v, int n_)
      : ts(t), vals(v), n(n_) {}
  bool hasNext() override { return i < n; }
  DataPoint next() override {
    DataPoint dp{ts[i], vals[i]};
    ++i;
    return dp;
  }
};

// ref: src/core/Downsampler.java:28 + ValuesInInterval :295 — average
// of each fixed interval window, emitted at the window start
struct DownsamplerView : SeekableView {
  SeekableView* src;
  int64_t interval_ms;
  DataPoint pending{0, 0};
  bool has_pending = false;
  bool done = false;
  DownsamplerView(SeekableView* s, int64_t iv)
      : src(s), interval_ms(iv) {}
  bool hasNext() override { return has_pending || !done || prime(); }
  // fill one window starting at ``seed``; sets pending and
  // carry/done for the point that overran the window
  void fill(DataPoint seed) {
    int64_t b = seed.ts - (seed.ts % interval_ms);
    double sum = seed.val;
    int cnt = 1;
    has_carry = false;
    while (src->hasNext()) {
      DataPoint nx = src->next();
      int64_t nb = nx.ts - (nx.ts % interval_ms);
      if (nb != b) {
        carry = nx;
        has_carry = true;
        break;
      }
      sum += nx.val;
      ++cnt;
    }
    if (!has_carry) done = true;
    pending = DataPoint{b, sum / cnt};
    has_pending = true;
  }
  bool prime() {
    if (done) return false;
    if (!src->hasNext()) {
      done = true;
      return false;
    }
    fill(src->next());
    return true;
  }
  DataPoint next() override {
    if (!has_pending) prime();
    has_pending = false;
    DataPoint out = pending;
    if (has_carry) fill(carry);
    return out;
  }

 private:
  DataPoint carry{0, 0};
  bool has_carry = false;
};

// ref: src/core/RateSpan.java:21 — dv/dt between successive points
struct RateSpanView : SeekableView {
  SeekableView* src;
  DataPoint prev{0, 0};
  bool has_prev = false;
  RateSpanView(SeekableView* s) : src(s) {}
  bool hasNext() override {
    if (!has_prev) {
      if (!src->hasNext()) return false;
      prev = src->next();
      has_prev = true;
    }
    return src->hasNext();
  }
  DataPoint next() override {
    DataPoint cur = src->next();
    double dt = (cur.ts - prev.ts) / 1000.0;
    if (dt <= 0) dt = 1.0;
    DataPoint out{cur.ts, (cur.val - prev.val) / dt};
    prev = cur;
    return out;
  }
};

// ref: src/core/Aggregator.java:73 — the values-iterator fed to
// runDouble at each output timestamp
struct Doubles {
  virtual bool hasNextValue() = 0;
  virtual double nextDoubleValue() = 0;
  virtual ~Doubles() = default;
};

struct Aggregator {
  virtual double runDouble(Doubles& d) = 0;
  virtual ~Aggregator() = default;
};

struct SumAgg : Aggregator {
  double runDouble(Doubles& d) override {
    double acc = 0;
    while (d.hasNextValue()) acc += d.nextDoubleValue();
    return acc;
  }
};

// ref: src/core/AggregationIterator.java:27-119 — k-way merge across a
// group's spans with linear interpolation at unaligned timestamps.
// Keeps per-iterator (current, next) pairs; each emitted timestamp
// scans every member iterator through the Doubles virtual interface.
struct AggregationIterator : Doubles {
  std::vector<SeekableView*> its;
  std::vector<DataPoint> cur, nxt;
  std::vector<uint8_t> has_cur, has_nxt;
  int64_t emit_ts = 0;
  size_t scan_i = 0;
  Aggregator* agg;

  AggregationIterator(std::vector<SeekableView*> members, Aggregator* a)
      : its(std::move(members)), agg(a) {
    size_t k = its.size();
    cur.resize(k);
    nxt.resize(k);
    has_cur.assign(k, 0);
    has_nxt.assign(k, 0);
    for (size_t j = 0; j < k; ++j)
      if (its[j]->hasNext()) {
        nxt[j] = its[j]->next();
        has_nxt[j] = 1;
      }
  }

  bool hasNextTimestamp(int64_t* out) {
    int64_t best = std::numeric_limits<int64_t>::max();
    bool any = false;
    for (size_t j = 0; j < its.size(); ++j)
      if (has_nxt[j] && nxt[j].ts < best) {
        best = nxt[j].ts;
        any = true;
      }
    if (any) *out = best;
    return any;
  }

  void advanceTo(int64_t ts) {
    for (size_t j = 0; j < its.size(); ++j)
      if (has_nxt[j] && nxt[j].ts == ts) {
        cur[j] = nxt[j];
        has_cur[j] = 1;
        if (its[j]->hasNext()) {
          nxt[j] = its[j]->next();
        } else {
          has_nxt[j] = 0;
        }
      }
    emit_ts = ts;
    scan_i = 0;
  }

  // Doubles over the group members at emit_ts: exact value when the
  // member has a point here, LERP between its neighbors otherwise
  bool hasNextValue() override {
    while (scan_i < its.size()) {
      if (has_cur[scan_i]) return true;
      ++scan_i;
    }
    return false;
  }
  double nextDoubleValue() override {
    size_t j = scan_i++;
    if (cur[j].ts == emit_ts) return cur[j].val;
    if (has_nxt[j]) {  // lerp (ref AggregationIterator.java:99-113)
      double span = double(nxt[j].ts - cur[j].ts);
      double w = span > 0 ? double(emit_ts - cur[j].ts) / span : 0.0;
      return cur[j].val + w * (nxt[j].val - cur[j].val);
    }
    return cur[j].val;
  }

  // run the merge to exhaustion; returns checksum + count of emitted
  // group datapoints
  std::pair<double, long> run() {
    double checksum = 0;
    long emitted = 0;
    int64_t ts;
    while (hasNextTimestamp(&ts)) {
      advanceTo(ts);
      checksum += agg->runDouble(*this);
      ++emitted;
    }
    return {checksum, emitted};
  }
};

int main(int argc, char** argv) {
  if (argc < 7) {
    std::fprintf(stderr,
                 "usage: baseline_ref S P B G rate reps\n");
    return 2;
  }
  long S = atol(argv[1]);
  long P = atol(argv[2]);
  long B = atol(argv[3]);
  long G = atol(argv[4]);
  int rate = atoi(argv[5]);
  int reps = atoi(argv[6]);

  // regular-cadence synthetic data shaped like the bench workloads
  std::vector<int64_t> ts(P);
  int64_t span_ms = 3'600'000;
  int64_t step = span_ms / P;
  for (long i = 0; i < P; ++i) ts[i] = 1'356'998'400'000 + i * step;
  int64_t interval = span_ms / B;
  std::vector<double> vals((size_t)S * P);
  std::mt19937_64 rng(0);
  std::normal_distribution<double> nd(100.0, 15.0);
  for (auto& v : vals) v = nd(rng);

  double best = 1e100;
  double checksum = 0;
  long emitted = 0;
  for (int r = 0; r < reps; ++r) {
    auto t0 = std::chrono::steady_clock::now();
    checksum = 0;
    emitted = 0;
    SumAgg agg;
    // one SpanGroup per group, exactly like GroupByAndAggregateCB
    for (long g = 0; g < G; ++g) {
      std::vector<std::unique_ptr<SeekableView>> owned;
      std::vector<SeekableView*> members;
      for (long s = g; s < S; s += G) {
        auto row = std::make_unique<RowSeqView>(
            ts.data(), &vals[(size_t)s * P], (int)P);
        SeekableView* tip = row.get();
        owned.push_back(std::move(row));
        auto dsv = std::make_unique<DownsamplerView>(tip, interval);
        tip = dsv.get();
        owned.push_back(std::move(dsv));
        if (rate) {
          auto rv = std::make_unique<RateSpanView>(tip);
          tip = rv.get();
          owned.push_back(std::move(rv));
        }
        members.push_back(tip);
      }
      AggregationIterator merge(std::move(members), &agg);
      auto res = merge.run();
      checksum += res.first;
      emitted += res.second;
    }
    auto t1 = std::chrono::steady_clock::now();
    double dt = std::chrono::duration<double>(t1 - t0).count();
    if (dt < best) best = dt;
  }
  std::printf("{\"seconds\": %.6f, \"datapoints\": %ld, "
              "\"dps\": %.0f, \"emitted\": %ld, \"checksum\": %.3f}\n",
              best, S * P, (double)(S * P) / best, emitted, checksum);
  return 0;
}
