"""ctypes bindings for the native C++ column store.

Drop-in storage backend (``tsd.storage.backend = native``): same
interface as :class:`opentsdb_tpu.core.store.TimeSeriesStore`, with
point columns living in the C++ arena (``tsdbstore.cc``) and series
identity / tag indexing staying in Python (they need UID strings
anyway). Built on demand with g++; transparently falls back to the
Python backend when no compiler is available.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterable, Sequence

import numpy as np

from opentsdb_tpu.core import const
from opentsdb_tpu.core.store import MetricIndex, PaddedBatch, PointBatch

_SRC = os.path.join(os.path.dirname(__file__), "tsdbstore.cc")
_LIB_PATH = os.path.join(os.path.dirname(__file__), "libtsdbstore.so")
_lib = None
_build_error: str | None = None  # negative cache for failed builds
_lib_lock = threading.Lock()


class NativeBuildError(RuntimeError):
    pass


def build_library(force: bool = False) -> str:
    """Compile libtsdbstore.so if needed; returns its path.

    Built on demand on the host that uses it (-march=native is safe
    because the .so never ships to another machine); staleness checks
    both the C++ source and THIS file (the build flags live here)."""
    newest_src = max(os.path.getmtime(_SRC), os.path.getmtime(__file__))
    if not force and os.path.isfile(_LIB_PATH) and \
            os.path.getmtime(_LIB_PATH) >= newest_src:
        return _LIB_PATH
    cmd = ["g++", "-O3", "-march=native", "-shared", "-fPIC",
           "-std=c++17", "-pthread", _SRC, "-o", _LIB_PATH]
    try:
        proc = subprocess.run(cmd, capture_output=True, text=True,
                              timeout=180)
    except (OSError, subprocess.TimeoutExpired) as e:
        raise NativeBuildError(f"g++ unavailable: {e}") from e
    if proc.returncode != 0:
        raise NativeBuildError(f"native build failed:\n{proc.stderr}")
    return _LIB_PATH


def load_library():
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            # negative cache: without it every probe re-runs g++ —
            # seconds per call on a toolchain-less host
            raise NativeBuildError(_build_error)
        try:
            path = build_library()
        except NativeBuildError as e:
            _build_error = str(e)
            raise
        try:
            lib = ctypes.CDLL(path)
        except OSError as e:
            # a stale/corrupt/ABI-incompatible cached .so must behave
            # exactly like a failed build: negative-cached (CDLL is
            # retried per call otherwise) and surfaced as
            # NativeBuildError so every caller's fallback engages
            _build_error = f"cannot load {path}: {e}"
            raise NativeBuildError(_build_error) from e
        lib.tss_create.restype = ctypes.c_void_p
        lib.tss_destroy.argtypes = [ctypes.c_void_p]
        lib.tss_add_series.argtypes = [ctypes.c_void_p]
        lib.tss_add_series.restype = ctypes.c_int64
        lib.tss_add_series_n.argtypes = [ctypes.c_void_p, ctypes.c_int64]
        lib.tss_add_series_n.restype = ctypes.c_int64
        lib.tss_series_count.argtypes = [ctypes.c_void_p]
        lib.tss_series_count.restype = ctypes.c_int64
        lib.tss_append.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                   ctypes.c_int64, ctypes.c_double,
                                   ctypes.c_int]
        lib.tss_append_many.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.tss_points_written.argtypes = [ctypes.c_void_p]
        lib.tss_points_written.restype = ctypes.c_int64
        lib.tss_repair_series.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int64, ctypes.c_int64,
                                          ctypes.c_int64, ctypes.c_int]
        lib.tss_repair_series.restype = ctypes.c_int64
        lib.tss_patch_value.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                        ctypes.c_int64, ctypes.c_double,
                                        ctypes.c_int]
        lib.tss_patch_value.restype = ctypes.c_int
        lib.tss_append_grid.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int]
        lib.tss_append_grid.restype = ctypes.c_int64
        lib.tss_delete_range.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                         ctypes.c_int64, ctypes.c_int64]
        lib.tss_delete_range.restype = ctypes.c_int64
        lib.tss_series_length.argtypes = [ctypes.c_void_p,
                                          ctypes.c_int64]
        lib.tss_series_length.restype = ctypes.c_int64
        lib.tss_read_series.argtypes = [
            ctypes.c_void_p, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.tss_read_series.restype = ctypes.c_int64
        lib.tss_count_range.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_int]
        lib.tss_fill_range.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_int]
        lib.tss_bucket_reduce.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int]
        lib.tss_bucket_reduce.restype = ctypes.c_int
        lib.tss_parse_import.argtypes = [
            ctypes.c_char_p, ctypes.c_int64, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p,
            ctypes.c_int64, ctypes.c_void_p, ctypes.c_int]
        lib.tss_parse_import.restype = ctypes.c_int64
        lib.tss_count_lines.argtypes = [ctypes.c_char_p,
                                        ctypes.c_int64]
        lib.tss_count_lines.restype = ctypes.c_int64
        lib.tss_append_lines.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_void_p]
        lib.tss_append_lines.restype = ctypes.c_int64
        lib.tss_format_dps.argtypes = [
            ctypes.c_void_p, ctypes.c_void_p, ctypes.c_int64,
            ctypes.c_int, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_int64]
        lib.tss_format_dps.restype = ctypes.c_int64
        lib.tss_fmt_fast.argtypes = []
        lib.tss_fmt_fast.restype = ctypes.c_int64
        _lib = lib
        return lib


def _ptr(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.c_void_p)


class _NativeSeriesView:
    """Buffer-compatible facade over one native series (read side)."""

    def __init__(self, store: "NativeTimeSeriesStore", sid: int):
        self._store = store
        self._sid = sid

    def view(self):
        ts, vals, _ = self.view_full()
        return ts, vals

    def view_full(self):
        lib = self._store._lib
        n = lib.tss_series_length(self._store._h, self._sid)
        ts = np.empty(n, dtype=np.int64)
        vals = np.empty(n, dtype=np.float64)
        ints = np.empty(n, dtype=np.uint8)
        if n:
            # the copy is capped at n and returns the actual count:
            # concurrent appends/deletes between the length call and
            # the read can change the buffer (trim to what was copied)
            got = lib.tss_read_series(self._store._h, self._sid, n,
                                      _ptr(ts), _ptr(vals), _ptr(ints))
            if got < n:
                got = max(got, 0)
                ts, vals, ints = ts[:got], vals[:got], ints[:got]
        return ts, vals, ints.astype(bool)

    def slice_range(self, start_ms: int, end_ms: int):
        ts, vals = self.view()
        lo = np.searchsorted(ts, start_ms, side="left")
        hi = np.searchsorted(ts, end_ms, side="right")
        return ts[lo:hi], vals[lo:hi]

    def __len__(self):
        return int(self._store._lib.tss_series_length(self._store._h,
                                                      self._sid))


class _NativeSeriesRecord:
    __slots__ = ("series_id", "metric_id", "tags", "shard", "buffer")

    def __init__(self, series_id, metric_id, tags, shard, buffer):
        self.series_id = series_id
        self.metric_id = metric_id
        self.tags = tags
        self.shard = shard
        self.buffer = buffer


class NativeTimeSeriesStore:
    """C++-backed TimeSeriesStore (same duck-typed interface)."""

    # fault-injection hook for the scan path (tsd.faults.store_*);
    # set by the owning TSDB, None everywhere else; rollup tier /
    # preagg instances override fault_site with "rollup.store"
    fault_injector = None
    fault_site = "store"

    def __init__(self, num_shards: int | None = None,
                 materialize_threads: int | None = None):
        from opentsdb_tpu.core.store import STORE_INSTANCE_IDS
        self.instance_id = next(STORE_INSTANCE_IDS)
        self._lib = load_library()
        self._h = ctypes.c_void_p(self._lib.tss_create())
        self.num_shards = num_shards or const.salt_buckets()
        self.threads = materialize_threads or min(
            16, os.cpu_count() or 4)
        self._lock = threading.Lock()
        # tsdlint: allow[unbounded-growth] the native backend's store
        # index — live-series-bounded like the Python twin (core/
        # store.py _series); reclamation is the ROADMAP UID item
        self._records: list[_NativeSeriesRecord] = []
        # tsdlint: allow[unbounded-growth] see _records
        self._key_to_sid: dict[tuple, int] = {}
        # tsdlint: allow[unbounded-growth] see _records
        self._metric_index: dict[int, MetricIndex] = {}
        # destructive-op version for read-side caches (cf. the Python
        # backend's counterpart)
        self.mutation_epoch = 0

    def __del__(self):
        try:
            if self._h:
                self._lib.tss_destroy(self._h)
        except Exception:  # noqa: BLE001
            # tsdlint: allow[swallow] a destructor must never raise
            # (interpreter teardown may have torn the lib down first)
            pass

    # -- write path ---------------------------------------------------

    def get_or_create_series(self, metric_id: int,
                             tags: Sequence[tuple[int, int]]) -> int:
        key = (metric_id, tuple(sorted(tags)))
        sid = self._key_to_sid.get(key)
        if sid is not None:
            return sid
        with self._lock:
            sid = self._key_to_sid.get(key)
            if sid is not None:
                return sid
            native_sid = self._lib.tss_add_series(self._h)
            assert native_sid == len(self._records)
            shard = hash((metric_id, key[1])) % self.num_shards
            rec = _NativeSeriesRecord(
                native_sid, metric_id, key[1], shard,
                _NativeSeriesView(self, native_sid))
            self._records.append(rec)
            idx = self._metric_index.get(metric_id)
            if idx is None:
                idx = self._metric_index[metric_id] = MetricIndex(
                    metric_id)
            idx.add(native_sid, key[1])
            self._key_to_sid[key] = native_sid
            return native_sid

    def get_or_create_series_bulk(self, metric_id: int,
                                  tags_list) -> np.ndarray:
        """Vectorized get_or_create_series: one native bulk allocation
        (``tss_add_series_n``) + one directory/index update per batch
        (see the Python backend's docstring for rationale)."""
        keys = [(metric_id, tuple(sorted(t))) for t in tags_list]
        out = np.empty(len(keys), dtype=np.int64)
        missing: list[int] = []
        get = self._key_to_sid.get
        for i, key in enumerate(keys):
            sid = get(key)
            if sid is None:
                missing.append(i)
                out[i] = -1
            else:
                out[i] = sid
        if not missing:
            return out
        with self._lock:
            # re-check under the lock, then allocate the still-missing
            # contiguously in one native call
            fresh = [i for i in missing
                     if self._key_to_sid.get(keys[i]) is None]
            # dedupe identical keys inside the batch (first wins)
            seen: dict[tuple, int] = {}
            alloc: list[int] = []
            for i in fresh:
                if keys[i] not in seen:
                    seen[keys[i]] = -1
                    alloc.append(i)
            if alloc:
                first = self._lib.tss_add_series_n(self._h, len(alloc))
                assert first == len(self._records)
                idx = self._metric_index.get(metric_id)
                if idx is None:
                    idx = self._metric_index[metric_id] = MetricIndex(
                        metric_id)
                new_sids: list[int] = []
                new_tags: list[tuple[tuple[int, int], ...]] = []
                for j, i in enumerate(alloc):
                    sid = first + j
                    key = keys[i]
                    self._records.append(_NativeSeriesRecord(
                        sid, metric_id, key[1],
                        hash((metric_id, key[1])) % self.num_shards,
                        _NativeSeriesView(self, sid)))
                    self._key_to_sid[key] = sid
                    new_sids.append(sid)
                    new_tags.append(key[1])
                idx.add_bulk(new_sids, new_tags)
            for i in missing:
                out[i] = self._key_to_sid[keys[i]]
        return out

    def append(self, series_id: int, ts_ms: int, value: float,
               is_int: bool = False) -> None:
        rc = self._lib.tss_append(self._h, series_id, ts_ms, value,
                                  int(is_int))
        if rc != 0:
            raise IndexError(f"no such series {series_id}")

    def append_many(self, series_id: int, ts_ms, values,
                    is_int=False) -> None:
        ts = np.ascontiguousarray(ts_ms, dtype=np.int64)
        vals = np.ascontiguousarray(values, dtype=np.float64)
        if isinstance(is_int, np.ndarray):
            ints = np.ascontiguousarray(is_int, dtype=np.uint8)
        else:
            ints = np.full(len(ts), int(bool(is_int)), dtype=np.uint8)
        rc = self._lib.tss_append_many(self._h, series_id, len(ts),
                                       _ptr(ts), _ptr(vals), _ptr(ints))
        if rc != 0:
            raise IndexError(f"no such series {series_id}")

    # -- read path ----------------------------------------------------

    @property
    def points_written(self) -> int:
        return int(self._lib.tss_points_written(self._h))

    def series(self, series_id: int) -> _NativeSeriesRecord:
        return self._records[series_id]

    def num_series(self) -> int:
        return len(self._records)

    def metric_ids(self) -> list[int]:
        with self._lock:
            return list(self._metric_index)

    def metric_index(self, metric_id: int) -> MetricIndex | None:
        return self._metric_index.get(metric_id)

    def series_ids_for_metric(self, metric_id: int) -> np.ndarray:
        idx = self._metric_index.get(metric_id)
        if idx is None:
            return np.empty(0, dtype=np.int64)
        sids, _ = idx.arrays()
        return sids

    def materialize(self, series_ids: Sequence[int], start_ms: int,
                    end_ms: int) -> PointBatch:
        if self.fault_injector is not None:
            self.fault_injector.check(self.fault_site)
        sids = np.ascontiguousarray(series_ids, dtype=np.int64)
        counts = np.empty(len(sids), dtype=np.int64)
        rc = self._lib.tss_count_range(self._h, _ptr(sids), len(sids),
                                       start_ms, end_ms, _ptr(counts),
                                       self.threads)
        if rc != 0:
            raise IndexError("invalid series id in materialize")
        offsets = np.zeros(len(sids), dtype=np.int64)
        np.cumsum(counts[:-1], out=offsets[1:]) if len(sids) > 1 else None
        total = int(counts.sum())
        ts_out = np.empty(total, dtype=np.int64)
        vals_out = np.empty(total, dtype=np.float64)
        sidx_out = np.empty(total, dtype=np.int32)
        if total:
            self._lib.tss_fill_range(
                self._h, _ptr(sids), len(sids), start_ms, end_ms,
                _ptr(offsets), _ptr(counts), _ptr(ts_out),
                _ptr(vals_out), _ptr(sidx_out), self.threads)
        return PointBatch(sids, sidx_out, ts_out, vals_out)

    def append_grid(self, series_ids, bucket_ts: np.ndarray,
                    grid: np.ndarray, mask: np.ndarray) -> int:
        """Bulk write one [S, B] grid: mask-selected cells of row i
        append onto series_ids[i]. C++ thread pool, one lock take per
        row — the rollup job's output path."""
        sids = np.ascontiguousarray(series_ids, dtype=np.int64)
        bts = np.ascontiguousarray(bucket_ts, dtype=np.int64)
        g = np.ascontiguousarray(grid, dtype=np.float64)
        m = np.ascontiguousarray(mask, dtype=np.uint8)
        n = self._lib.tss_append_grid(
            self._h, _ptr(sids), len(sids), _ptr(bts), g.shape[1],
            _ptr(g), _ptr(m), self.threads)
        if n < 0:
            raise IndexError("invalid series id in append_grid")
        return int(n)

    def repair_series(self, series_id: int, min_ts: int, max_ts: int,
                      drop_nonfinite: bool = True) -> int:
        """fsck in-place repair: drop out-of-range timestamps and
        (optionally) non-finite values. Returns points removed."""
        n = self._lib.tss_repair_series(self._h, series_id, min_ts,
                                        max_ts, int(drop_nonfinite))
        if n < 0:
            raise IndexError(f"no such series {series_id}")
        if n:
            self.mutation_epoch += 1
        return int(n)

    def patch_value(self, series_id: int, ts_ms: int, value: float,
                    is_int: bool = False) -> None:
        """fsck in-place repair: overwrite the value at an exact
        timestamp (raises KeyError when absent)."""
        rc = self._lib.tss_patch_value(self._h, series_id, ts_ms,
                                       float(value), int(is_int))
        if rc == -1:
            raise IndexError(f"no such series {series_id}")
        if rc == -2:
            raise KeyError(f"series {series_id} has no point at "
                           f"{ts_ms}")
        self.mutation_epoch += 1

    def count_range(self, series_ids: Sequence[int], start_ms: int,
                    end_ms: int) -> np.ndarray:
        sids = np.ascontiguousarray(series_ids, dtype=np.int64)
        counts = np.empty(len(sids), dtype=np.int64)
        rc = self._lib.tss_count_range(self._h, _ptr(sids), len(sids),
                                       start_ms, end_ms, _ptr(counts),
                                       self.threads)
        if rc != 0:
            raise IndexError("invalid series id in count_range")
        return counts

    def materialize_padded(self, series_ids: Sequence[int],
                           start_ms: int, end_ms: int) -> PaddedBatch:
        """Row-padded materialize: reuses ``tss_fill_range`` by passing
        per-row offsets ``i * Pmax`` — each series' contiguous run lands
        in its own row of the padded buffers, no extra pass."""
        if self.fault_injector is not None:
            self.fault_injector.check(self.fault_site)
        sids = np.ascontiguousarray(series_ids, dtype=np.int64)
        counts = np.empty(len(sids), dtype=np.int64)
        rc = self._lib.tss_count_range(self._h, _ptr(sids), len(sids),
                                       start_ms, end_ms, _ptr(counts),
                                       self.threads)
        if rc != 0:
            raise IndexError("invalid series id in materialize")
        pmax = max(1, int(counts.max())) if len(sids) else 1
        values2d = np.full(len(sids) * pmax, np.nan)
        ts2d = np.zeros(len(sids) * pmax, dtype=np.int64)
        if counts.sum():
            offsets = np.arange(len(sids), dtype=np.int64) * pmax
            sidx_scratch = np.empty(len(sids) * pmax, dtype=np.int32)
            # fill writes counts[i] elements at offsets[i]; sidx output
            # is positional scratch we don't need in the padded layout
            self._lib.tss_fill_range(
                self._h, _ptr(sids), len(sids), start_ms, end_ms,
                _ptr(offsets), _ptr(counts), _ptr(ts2d),
                _ptr(values2d), _ptr(sidx_scratch), self.threads)
        return PaddedBatch(sids, values2d.reshape(len(sids), pmax),
                           ts2d.reshape(len(sids), pmax), counts)

    def append_lines(self, sids, ts_ms, values, is_int) -> int:
        """Scatter-append: element i lands on series ``sids[i]``
        (negative skips). One native call for a whole import buffer."""
        sid_arr = np.ascontiguousarray(sids, dtype=np.int64)
        ts_arr = np.ascontiguousarray(ts_ms, dtype=np.int64)
        val_arr = np.ascontiguousarray(values, dtype=np.float64)
        int_arr = np.ascontiguousarray(is_int, dtype=np.uint8)
        n = self._lib.tss_append_lines(self._h, _ptr(sid_arr),
                                       len(sid_arr), _ptr(ts_arr),
                                       _ptr(val_arr), _ptr(int_arr))
        if n < 0:
            raise IndexError("invalid series id in append_lines")
        return int(n)

    def bucket_reduce(self, series_ids, start_ms: int, end_ms: int,
                      t0: int, interval_ms: int, nbuckets: int,
                      want_minmax: bool = False):
        """Fused range-scan + fixed-interval pre-reduction: one C++
        pass returns [S, B] sum/count (and min/max on request) grids —
        the device then starts at the grid stage of the pipeline
        instead of receiving every point (SURVEY §7: HBM bandwidth is
        the bottleneck; don't ship what the host can pre-reduce 60x)."""
        if self.fault_injector is not None:
            self.fault_injector.check(self.fault_site)
        sids = np.ascontiguousarray(series_ids, dtype=np.int64)
        s = len(sids)
        sums = np.empty((s, nbuckets), dtype=np.float64)
        cnts = np.empty((s, nbuckets), dtype=np.float64)
        mins = maxs = None
        pmin = pmax = None
        if want_minmax:
            mins = np.empty((s, nbuckets), dtype=np.float64)
            maxs = np.empty((s, nbuckets), dtype=np.float64)
            pmin, pmax = _ptr(mins), _ptr(maxs)
        rc = self._lib.tss_bucket_reduce(
            self._h, _ptr(sids), s, start_ms, end_ms, t0, interval_ms,
            nbuckets, _ptr(sums), _ptr(cnts), pmin, pmax, self.threads)
        if rc != 0:
            raise IndexError("invalid series id in bucket_reduce")
        return sums, cnts, mins, maxs

    def shards_of(self, series_ids: Iterable[int]) -> np.ndarray:
        return np.asarray([self._records[s].shard for s in series_ids],
                          dtype=np.int32)

    def delete_range(self, series_ids, start_ms: int,
                     end_ms: int) -> int:
        deleted = 0
        for sid in series_ids:
            n = int(self._lib.tss_delete_range(self._h, int(sid),
                                               start_ms, end_ms))
            if n > 0:
                deleted += n
        if deleted:
            self.mutation_epoch += 1
        return deleted

    def total_points(self) -> int:
        return sum(int(self._lib.tss_series_length(self._h, sid))
                   for sid in range(len(self._records)))

    def memory_info(self) -> dict:
        """Memory-footprint report (health/stats). The C++ arena does
        not expose per-series capacity, so resident is estimated as
        live bytes (17 bytes/point: int64 ts + float64 value + flag);
        cached on the write/delete counters like the Python twin."""
        key = (self.points_written, self.mutation_epoch,
               len(self._records))
        cached = getattr(self, "_memory_info_cache", None)
        if cached is not None and cached[0] == key:
            return cached[1]
        points = self.total_points()
        info = {"series": len(self._records), "points": points,
                "resident_bytes": points * 17, "live_bytes": points * 17,
                "dead_bytes": 0, "estimated": True}
        self._memory_info_cache = (key, info)
        return info

    def collect_stats(self, collector) -> None:
        collector.record("storage.series.count", self.num_series())
        collector.record("storage.points.written", self.points_written)
        collector.record("storage.shards", self.num_shards)
        collector.record("storage.backend", 1, backend="native")
        mi = self.memory_info()
        collector.record("storage.resident_bytes",
                         mi["resident_bytes"])
        collector.record("storage.live_bytes", mi["live_bytes"])
        collector.record("storage.dead_bytes", mi["dead_bytes"])


IMPORT_ERRORS = {
    1: "too few fields (metric ts value tag=value...)",
    2: "invalid timestamp",
    3: "invalid value",
    4: "malformed tag (need tagk=tagv) or too many tags",
    5: "invalid character in metric or tag",
}


class ParsedImport:
    """Columnar result of one native import-buffer parse.

    ``group_ids[i]`` labels line i with its distinct (metric, sorted
    tags) key (-1 for errors/blanks); ``rep_lines[g]`` is group g's
    first line as bytes, so the caller resolves metric/tag strings and
    UIDs once per distinct series instead of once per point (the whole
    point of the bulk path — ref: TextImporter.java:40 importing via
    per-series WritableDataPoints batches)."""

    __slots__ = ("ts", "values", "is_int", "group_ids", "errors",
                 "rep_lines", "num_groups", "num_lines")

    def __init__(self, ts, values, is_int, group_ids, errors,
                 rep_lines, num_groups, num_lines):
        self.ts = ts                  # int64 [L] raw (s or ms)
        self.values = values          # float64 [L]
        self.is_int = is_int          # uint8 [L]
        self.group_ids = group_ids    # int64 [L], -1 = error/blank
        self.errors = errors          # int32 [L], 0 ok / -1 blank / >0
        self.rep_lines = rep_lines    # list[bytes], one per group
        self.num_groups = num_groups
        self.num_lines = num_lines


# byte classes mirrored from tsdbstore.cc's parser: names allow the
# reference's charset (alnum -_./ plus UTF-8 lead/continuation bytes,
# re-validated python-side for non-ASCII); values allow the decimal
# float shape ONLY — strtod leniency (nan/inf/hex) and python
# int()/float() leniency (underscores, unicode digits) must both be
# rejected or a malformed value silently stores the wrong number
_NAME_BYTES = frozenset(
    b"abcdefghijklmnopqrstuvwxyz"
    b"ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789-_./")
_FLOAT_BYTES = frozenset(b"0123456789.+-eE")


def _py_valid_name(tok: bytes) -> bool:
    return bool(tok) and all(c in _NAME_BYTES or c >= 0x80
                             for c in tok)


def _parse_import_py(buf: bytes) -> ParsedImport:
    """Pure-Python twin of ``tss_parse_import`` for toolchain-less
    hosts (numpy column outputs, same error codes / strict value
    shape / grouping semantics) — the columnar ingest decode must not
    depend on a C++ compiler being present."""
    lines = buf.split(b"\n")
    if buf.endswith(b"\n"):
        lines.pop()
    n = len(lines)
    ts = np.zeros(n, dtype=np.int64)
    vals = np.zeros(n, dtype=np.float64)
    ints = np.zeros(n, dtype=np.uint8)
    gids = np.full(n, -1, dtype=np.int64)
    errs = np.zeros(n, dtype=np.int32)
    group_map: dict[bytes, int] = {}
    reps: list[bytes] = []
    prev_key = None
    prev_gid = -1
    max_ts = 1 << 47
    for i, line in enumerate(lines):
        if line.endswith(b"\r"):
            line = line[:-1]
        stripped = line.strip()
        if not stripped or stripped.startswith(b"#"):
            errs[i] = -1
            continue
        toks = line.replace(b"\t", b" ").split()
        if len(toks) < 4:
            errs[i] = 1
            continue
        if len(toks) > 16:
            errs[i] = 4
            continue
        if not _py_valid_name(toks[0]):
            errs[i] = 5
            continue
        t = toks[1]
        if not (0 < len(t) < 15 and t.isdigit()):
            errs[i] = 2
            continue
        tval = int(t)
        if tval <= 0 or tval > max_ts:
            errs[i] = 2
            continue
        ts[i] = tval
        v = toks[2]
        st = 1 if v[:1] in (b"-", b"+") else 0
        digits = v[st:]
        if digits and len(digits) < 19 and digits.isdigit():
            acc = int(digits)
            vals[i] = -float(acc) if v[:1] == b"-" else float(acc)
            ints[i] = 1
        else:
            ok = 0 < len(v) < 64 and all(c in _FLOAT_BYTES for c in v)
            if ok:
                try:
                    fv = float(v)
                    ok = fv == fv  # strtod parity: NaN rejected
                except ValueError:
                    ok = False
            if not ok:
                errs[i] = 3
                continue
            vals[i] = fv
            ints[i] = 0
        tags = toks[3:]
        if len(tags) > 8:  # the reference's hard tag cap
            errs[i] = 4
            continue
        bad = 0
        for tag in tags:
            eq = tag.find(b"=")
            if eq <= 0 or eq == len(tag) - 1:
                bad = 4
                break
            if not _py_valid_name(tag[:eq]) or \
                    not _py_valid_name(tag[eq + 1:]):
                bad = 5
                break
        if bad:
            errs[i] = bad
            continue
        key = toks[0] + b" " + b" ".join(sorted(tags))
        if prev_gid >= 0 and key == prev_key:
            gid = prev_gid
        else:
            gid = group_map.get(key)
            if gid is None:
                gid = len(group_map)
                group_map[key] = gid
                reps.append(line)
            prev_key, prev_gid = key, gid
        gids[i] = gid
    return ParsedImport(ts, vals, ints, gids, errs, reps,
                        len(group_map), n)


def parse_import_buffer(buf: bytes,
                        threads: int | None = None) -> ParsedImport:
    """Parse a whole import text buffer in one native pass, parallel
    over newline-aligned chunks (pure-Python columnar fallback when
    the native library cannot build)."""
    if not buf:
        e = np.empty(0, dtype=np.int64)
        return ParsedImport(e, np.empty(0), np.empty(0, np.uint8),
                            e.copy(), np.empty(0, np.int32), [], 0, 0)
    try:
        lib = load_library()
    except NativeBuildError:
        return _parse_import_py(buf)
    if threads is None:
        threads = min(16, os.cpu_count() or 1)
    nl = lib.tss_count_lines(buf, len(buf))
    ts = np.empty(nl, dtype=np.int64)
    vals = np.empty(nl, dtype=np.float64)
    ints = np.empty(nl, dtype=np.uint8)
    gids = np.empty(nl, dtype=np.int64)
    errs = np.empty(nl, dtype=np.int32)
    rep_off = np.empty(nl, dtype=np.int64)
    rep_len = np.empty(nl, dtype=np.int64)
    nlines = ctypes.c_int64(0)
    ng = lib.tss_parse_import(
        buf, len(buf), _ptr(ts), _ptr(vals), _ptr(ints), _ptr(gids),
        _ptr(errs), _ptr(rep_off), _ptr(rep_len), nl,
        ctypes.byref(nlines), threads)
    if ng < 0:
        raise RuntimeError("import parse overflow")
    n = nlines.value
    reps = [bytes(buf[rep_off[g]:rep_off[g] + rep_len[g]])
            for g in range(ng)]
    return ParsedImport(ts[:n], vals[:n], ints[:n], gids[:n], errs[:n],
                        reps, int(ng), n)


def format_dps_is_fast() -> bool:
    """True when the native dps formatter writes doubles through real
    ``std::to_chars`` (libstdc++ >= 11). On gcc-10 hosts the library
    builds (the formatter falls back to a verified %g precision walk,
    value-identical output) but that walk is SLOWER than the Python
    columnar bulk formatter, so serializers should skip native
    formatting there. Raises NativeBuildError when no library."""
    return bool(load_library().tss_fmt_fast())


def format_dps(ts_ms: np.ndarray, vals: np.ndarray, seconds: bool,
               as_arrays: bool) -> bytes:
    """JSON-format one series' dps natively (comma-joined entries, no
    envelope) — ~20x the Python per-point formatting rate. Raises
    NativeBuildError when no compiler exists (callers fall back)."""
    lib = load_library()
    ts_arr = np.ascontiguousarray(ts_ms, dtype=np.int64)
    val_arr = np.ascontiguousarray(vals, dtype=np.float64)
    cap = len(ts_arr) * 64 + 64
    buf = ctypes.create_string_buffer(cap)
    n = lib.tss_format_dps(_ptr(ts_arr), _ptr(val_arr), len(ts_arr),
                           int(seconds), int(as_arrays), buf, cap)
    if n < 0:
        raise RuntimeError("format_dps buffer overflow")
    return buf.raw[:n]


def make_store(config, num_shards: int | None = None):
    """Storage backend factory honoring ``tsd.storage.backend``.

    Defaults to the C++ engine (libtsdbstore) — the production path,
    preserving the reference's swappable-storage-client shape
    (asynchbase/asyncbigtable/asynccassandra, SURVEY.md §5.8); set
    ``tsd.storage.backend=memory`` for the pure-Python twin, e.g. where
    no compiler exists. Falls back automatically if the build fails.
    """
    backend = config.get_string("tsd.storage.backend", "native")
    if backend == "native":
        try:
            return NativeTimeSeriesStore(num_shards=num_shards)
        except NativeBuildError as e:
            import logging
            logging.getLogger(__name__).warning(
                "native store unavailable (%s); using memory backend", e)
    from opentsdb_tpu.core.store import TimeSeriesStore
    return TimeSeriesStore(num_shards=num_shards)
